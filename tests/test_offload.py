"""Host-DRAM KV offload tier: evicted blocks round-trip through host memory and
serve prefix hits with no recompute (reference capability #5,
docs/architecture.md:91-96)."""

import asyncio

import pytest

from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import EngineRequest

from tests.test_engine import tiny_engine_config, greedy_reference, _collect


# compile-heavy JAX e2e: runs in the full matrix, not the <2-min default tier
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def engine():
    # tiny device pool (12 usable pages) so eviction happens fast; big host tier
    eng = AsyncJaxEngine(
        tiny_engine_config(num_pages=13, max_seqs=2, host_cache_blocks=64)
    )

    async def boot():
        await eng.start()

    asyncio.run(boot())
    yield eng
    asyncio.run(eng.shutdown())


def run_req(engine, rid, prompt, n=4):
    req = EngineRequest(
        request_id=rid,
        token_ids=list(prompt),
        sampling=SamplingParams(temperature=0.0, max_tokens=n),
    )

    async def go():
        return await _collect(engine, req)

    return asyncio.run(go())


PROMPT_A = [11, 12, 13, 14, 15, 16, 17, 18]  # 2 full blocks
PROMPT_B = [91, 92, 93, 94, 95, 96, 97, 98, 99, 100, 101, 102]


def test_offload_roundtrip_preserves_kv(engine):
    toks_a1, _, cached_a1 = run_req(engine, "a1", PROMPT_A)
    assert cached_a1 == 0
    expected = greedy_reference(engine, PROMPT_A, 4)
    assert toks_a1 == expected

    # Burn through the device pool so A's cached blocks get offloaded to host.
    for i in range(4):
        run_req(engine, f"b{i}", [120 + 16 * i + j for j in range(12)])
    assert engine.offload.saves > 0

    # A again: prefix must come back from the HOST tier, and the continuation
    # must be token-exact (proves the offloaded KV bytes are intact).
    toks_a2, _, cached_a2 = run_req(engine, "a2", PROMPT_A)
    assert engine.offload.loads > 0
    assert cached_a2 >= 4
    assert toks_a2 == expected


def test_offload_lru_bound():
    async def body():
        eng = AsyncJaxEngine(
            tiny_engine_config(num_pages=9, max_seqs=1, host_cache_blocks=2)
        )
        await eng.start()
        try:
            for i in range(6):
                req = EngineRequest(
                    request_id=f"r{i}",
                    token_ids=[i * 20 + j for j in range(8)],
                    sampling=SamplingParams(temperature=0.0, max_tokens=2),
                )
                async for _ in eng.generate(req):
                    pass
            assert len(eng.offload) <= 2
            assert eng.offload.drops > 0
        finally:
            await eng.shutdown()

    asyncio.run(body())


def test_offload_tier_is_the_differentiator():
    """Same eviction pressure WITHOUT the host tier: the revisit gets zero
    cached tokens — the offload tier is what preserves prefix reuse under
    pressure (reference claim: 40% TTFT improvement from KV offload beyond
    prefix caching, docs/architecture.md:91-96)."""

    async def body():
        eng = AsyncJaxEngine(
            tiny_engine_config(num_pages=13, max_seqs=2, host_cache_blocks=0)
        )
        await eng.start()
        try:
            async def go(rid, prompt):
                req = EngineRequest(
                    request_id=rid,
                    token_ids=list(prompt),
                    sampling=SamplingParams(temperature=0.0, max_tokens=4),
                )
                return (await _collect(eng, req))[2]

            assert await go("a1", PROMPT_A) == 0
            for i in range(4):  # burn through the device pool
                await go(f"b{i}", [120 + 16 * i + j for j in range(12)])
            # revisit: evicted blocks are simply gone without the host tier
            assert await go("a2", PROMPT_A) == 0
        finally:
            await eng.shutdown()

    asyncio.run(body())


def test_offload_batched_restore_odd_block_count():
    """A 3-block restore pads the batched inject to the 4-bucket (pad ids are
    dropped by the scatter) and must stay token-exact."""
    async def body():
        eng = AsyncJaxEngine(
            tiny_engine_config(num_pages=21, max_seqs=2, host_cache_blocks=64)
        )
        await eng.start()
        try:
            # 4 full blocks: the full-hit trim leaves 3 to restore -> padded
            prompt = [31 + j for j in range(16)]
            req = lambda rid: EngineRequest(
                request_id=rid, token_ids=list(prompt),
                sampling=SamplingParams(temperature=0.0, max_tokens=4),
            )
            toks1, _, _ = await _collect(eng, req("p1"))
            for i in range(6):  # evict through the tiny pool
                await _collect(eng, EngineRequest(
                    request_id=f"f{i}", token_ids=[150 + 20 * i + j for j in range(16)],
                    sampling=SamplingParams(temperature=0.0, max_tokens=2),
                ))
            assert eng.offload.saves > 0
            toks2, _, cached = await _collect(eng, req("p2"))
            assert eng.offload.loads >= 3
            assert cached >= 12  # host-tier prefix hit
            assert toks2 == toks1
        finally:
            await eng.shutdown()

    asyncio.run(body())


def test_host_cache_bytes_budget_resolves_at_model_page_cost():
    """A byte-budget host tier resolves capacity from the model's ACTUAL
    kv_page_bytes at engine init (the PR-8 follow-up): the pool's block
    capacity, the per-block bytes, and the resident-bytes gauge all ride
    resource_snapshot — and the same budget holds ~2x blocks under int8."""
    async def capacity(**cfg_over):
        eng = AsyncJaxEngine(tiny_engine_config(num_pages=13, max_seqs=2,
                                                **cfg_over))
        await eng.start()
        try:
            page_bytes = eng.model.kv_page_bytes(eng.config.page_size)
            snap = eng.resource_snapshot()
            assert eng.offload is not None
            assert eng.offload.block_bytes == page_bytes
            assert snap["offload_capacity_blocks"] == eng.offload.capacity_blocks
            assert snap["offload_block_bytes"] == page_bytes
            assert snap["offload_bytes_resident"] == 0  # nothing drained yet
            return eng.offload.capacity_blocks, page_bytes
        finally:
            await eng.shutdown()

    async def body():
        budget = 1 << 20
        blocks, page_bytes = await capacity(host_cache_bytes=budget)
        assert blocks == budget // page_bytes
        blocks8, page8 = await capacity(host_cache_bytes=budget,
                                        kv_cache_dtype="int8")
        assert blocks8 == budget // page8
        assert blocks8 > blocks  # same budget, cheaper int8 pages
        # both knobs set: the larger resolved capacity wins
        big, _ = await capacity(host_cache_bytes=budget,
                                host_cache_blocks=blocks + 1000)
        assert big == blocks + 1000

    asyncio.run(body())


def test_disk_tier_cold_resume_parity():
    """Park -> demote past the host tier -> resume: the prefix must come back
    via the DISK restore path (scheduler disk_restore_hits), and the
    continuation must be token-identical to the fresh run — under
    kv_cache_dtype="int8" the wire blocks round-trip disk bit-exact, so
    greedy parity is exact, not approximate."""
    from dynamo_tpu.engine.kv_store import disk_block_bytes

    async def body():
        eng = AsyncJaxEngine(tiny_engine_config(
            num_pages=13, max_seqs=2, host_cache_blocks=4,
            disk_cache_bytes=64 << 20, kv_cache_dtype="int8",
        ))
        await eng.start()
        try:
            disk = eng.offload.disk
            assert disk is not None
            # block cost resolved from the model's ACTUAL dims at int8 wire cost
            mcfg = eng.model.config
            assert disk.block_bytes == disk_block_bytes(
                eng.config.page_size, mcfg.num_kv_heads, mcfg.head_dim,
                mcfg.num_layers,
            )

            async def go(rid, prompt, n=4):
                req = EngineRequest(
                    request_id=rid, token_ids=list(prompt),
                    sampling=SamplingParams(temperature=0.0, max_tokens=n),
                )
                return await _collect(eng, req)

            toks1, _, cached1 = await go("s1", PROMPT_A)
            assert cached1 == 0
            # churn: 6 fillers x 3 blocks through a 4-block host pool pushes
            # the parked session's blocks all the way down to disk
            for i in range(6):
                await go(f"f{i}", [140 + 16 * i + j for j in range(12)])
            assert disk.spills > 0
            hits_before = eng.scheduler.disk_restore_hits

            toks2, _, cached2 = await go("s2", PROMPT_A)
            assert eng.scheduler.disk_restore_hits > hits_before
            assert eng.scheduler.disk_restore_tokens > 0
            assert disk.restores > 0
            assert cached2 >= 4  # the restored block served as a prefix hit
            assert toks2 == toks1  # token-identical resume
        finally:
            await eng.shutdown()

    asyncio.run(body())


def test_eviction_truthfulness_across_three_tiers():
    """The event ledger is truthful across HBM -> host -> disk -> gone: a
    demotion down the ladder emits NO `removed`; the one `removed` fires only
    when a block leaves its LAST tier. Invariant checked per block hash:
    stored_count - removed_count == 1 iff the hash is live in some tier."""

    events = []

    async def body():
        eng = AsyncJaxEngine(
            tiny_engine_config(num_pages=13, max_seqs=2, host_cache_blocks=4,
                               disk_cache_bytes=64 << 20),
            kv_event_sink=events.append,
        )
        await eng.start()
        try:
            async def go(rid, prompt, n=2):
                req = EngineRequest(
                    request_id=rid, token_ids=list(prompt),
                    sampling=SamplingParams(temperature=0.0, max_tokens=n),
                )
                return await _collect(eng, req)

            await go("a1", PROMPT_A, 4)
            for i in range(6):  # walk blocks HBM -> host -> disk
                await go(f"f{i}", [140 + 16 * i + j for j in range(12)])
            disk = eng.offload.disk
            assert disk.spills > 0
            # shrink the disk budget mid-run (white-box: a tiny CONFIG budget
            # would also starve the fillers) so the next churn round forces
            # blocks off the END of the ladder — the only point where
            # `removed` is truthful
            entry_bytes = next(iter(disk._index.values())).nbytes
            disk.budget_bytes = 2 * entry_bytes
            for i in range(3):
                await go(f"g{i}", [260 + 16 * i + j for j in range(12)])
            assert disk.drops > 0
            disk.flush()

            stored, removed = {}, {}
            for ev in events:
                if ev.kind == "stored":
                    for b in ev.blocks:
                        stored[b.block_hash] = stored.get(b.block_hash, 0) + 1
                else:
                    for h in ev.block_hashes:
                        removed[h] = removed.get(h, 0) + 1
            assert set(removed) <= set(stored)  # never remove the unstored

            def live(h):
                return (h in eng.allocator._cache or h in eng.offload._blocks
                        or h in disk._index)

            gone = 0
            for h, n_stored in stored.items():
                n_removed = removed.get(h, 0)
                expect = 1 if live(h) else 0
                assert n_stored - n_removed == expect, (
                    f"hash {h:x}: stored={n_stored} removed={n_removed} "
                    f"live={bool(expect)}"
                )
                gone += 0 if expect else 1
            # at least one block actually walked the full ladder off the end
            assert gone > 0
        finally:
            await eng.shutdown()

    asyncio.run(body())


def test_load_many_device_roundtrip_with_bucket_padding():
    """HostKvPool.load_many against the REAL jitted scatter: 3 blocks pad to
    a 4-bucket whose pad id is far out of range — the donated scatter must
    drop it (no live page clobbered) while the 3 real blocks restore
    byte-exact. Also covers the contiguous-leading-run cutoff when a block
    is LRU-dropped between the membership check and the injection."""
    import numpy as np

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.model_runner import ModelRunner
    from dynamo_tpu.engine.offload import HostKvPool
    from dynamo_tpu.models.registry import load_model

    cfg = EngineConfig(
        model_id="tiny", page_size=4, num_pages=16, max_seqs=2,
        max_model_len=32, prefill_buckets=(8,),
    )
    model, params = load_model("tiny")
    runner = ModelRunner(cfg, model, params)
    pool = HostKvPool(runner, capacity_blocks=8)

    src = np.array([1, 2, 3], np.int32)
    rng = np.random.default_rng(3)
    data = rng.normal(size=runner.extract_pages(src).shape).astype(np.float32)
    runner.inject_pages(src, data)
    src_data = runner.extract_pages(src)
    for h, p in ((901, 1), (902, 2), (903, 3)):
        pool.save(h, p)

    sentinel = runner.extract_pages(np.array([12], np.int32)).copy()
    hits = pool.load_many([(901, 7), (902, 8), (903, 9)])
    assert hits == {901, 902, 903}
    np.testing.assert_array_equal(
        runner.extract_pages(np.array([7, 8, 9], np.int32)), src_data
    )
    # the pad id (bucket 4 > 3 hits) was dropped by the scatter: untouched
    # pages keep their bytes
    np.testing.assert_array_equal(
        runner.extract_pages(np.array([12], np.int32)), sentinel
    )

    # leading-run cutoff: 902 dropped between membership check and injection
    pool.discard(902)
    before_11 = runner.extract_pages(np.array([11], np.int32)).copy()
    hits = pool.load_many([(901, 10), (902, 11), (903, 12)])
    assert hits == {901}
    np.testing.assert_array_equal(
        runner.extract_pages(np.array([10], np.int32)), src_data[:, :, :1]
    )
    # pages past the first miss were never written
    np.testing.assert_array_equal(
        runner.extract_pages(np.array([11], np.int32)), before_11
    )
