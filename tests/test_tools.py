"""Tool calling: matcher parsing semantics + E2E over the HTTP service.

Mirrors reference lib/llm/src/preprocessor/tools.rs (four accepted JSON
shapes, forced-choice failure) plus the request-side template rendering.
"""

import asyncio
import json

import aiohttp
import pytest

from dynamo_tpu.llm.tools import ToolCallError, ToolCallingMatcher, parse_tool_choice

WEATHER_CALL = {"name": "get_weather", "parameters": {"city": "SF", "unit": "C"}}


def test_matcher_single_parameters_form():
    calls = ToolCallingMatcher("auto").get_calls(json.dumps(WEATHER_CALL))
    assert len(calls) == 1
    call = calls[0]
    assert call["id"].startswith("call-")
    assert call["type"] == "function"
    assert call["function"]["name"] == "get_weather"
    assert json.loads(call["function"]["arguments"]) == WEATHER_CALL["parameters"]


def test_matcher_arguments_form_and_list():
    msg = json.dumps([{"name": "a", "arguments": {"x": 1}}, {"name": "b", "arguments": {}}])
    calls = ToolCallingMatcher("auto").get_calls(msg)
    assert [c["function"]["name"] for c in calls] == ["a", "b"]


def test_matcher_plain_text_is_not_a_call():
    assert ToolCallingMatcher("auto").get_calls("hello there") == []
    # JSON that is not a call shape
    assert ToolCallingMatcher("auto").get_calls('{"foo": 1}') == []


def test_matcher_none_choice_disables():
    assert ToolCallingMatcher("none").get_calls(json.dumps(WEATHER_CALL)) == []


def test_matcher_markdown_fenced_json():
    msg = "```json\n" + json.dumps(WEATHER_CALL) + "\n```"
    calls = ToolCallingMatcher("auto").get_calls(msg)
    assert calls and calls[0]["function"]["name"] == "get_weather"


def test_matcher_forced_choice_errors():
    forced = {"type": "function", "function": {"name": "get_weather"}}
    with pytest.raises(ToolCallError):
        ToolCallingMatcher(forced).get_calls("no call here")
    with pytest.raises(ToolCallError):
        ToolCallingMatcher(forced).get_calls(json.dumps({"name": "other", "parameters": {}}))
    calls = ToolCallingMatcher(forced).get_calls(json.dumps(WEATHER_CALL))
    assert calls[0]["function"]["name"] == "get_weather"
    with pytest.raises(ToolCallError):
        ToolCallingMatcher("required").get_calls("just text")


def test_parse_tool_choice_forms():
    assert parse_tool_choice(None) == ("auto", None)
    assert parse_tool_choice("auto") == ("auto", None)
    assert parse_tool_choice("none") == ("none", None)
    assert parse_tool_choice("required") == ("required", None)
    assert parse_tool_choice({"type": "function", "function": {"name": "f"}}) == (
        "required",
        "f",
    )
    with pytest.raises(ValueError):
        parse_tool_choice({"type": "function"})


def test_preprocessor_renders_tools_into_template():
    from dynamo_tpu.frontends.pipeline import card_for_model
    from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
    from dynamo_tpu.llm.protocols.openai import ChatCompletionRequest
    from dynamo_tpu.llm.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    pre = OpenAIPreprocessor(tok, "tiny", max_model_len=2048)
    tools = [{"type": "function", "function": {"name": "get_weather"}}]
    req = ChatCompletionRequest.from_dict(
        {
            "messages": [{"role": "user", "content": "hi"}],
            "tools": tools,
            "ext": {"annotations": ["formatted_prompt"]},
        }
    )
    _, annotations = pre.preprocess_chat(req)
    prompt = annotations["formatted_prompt"]
    assert "get_weather" in prompt and prompt.startswith("<tools>")

    # tool_choice "none" suppresses tool rendering
    req.tool_choice = "none"
    _, annotations = pre.preprocess_chat(req)
    assert "get_weather" not in annotations["formatted_prompt"]


class ScriptedEngine:
    """Emits a fixed utf-8 text as byte tokens (ByteTokenizer ids)."""

    def __init__(self, text: str):
        self.token_ids = list(text.encode("utf-8"))

    async def generate(self, request):
        from dynamo_tpu.engine.scheduler import StepOutput

        for i, tok in enumerate(self.token_ids):
            yield StepOutput(
                request_id=request.request_id,
                token=tok,
                finished=i == len(self.token_ids) - 1,
                finish_reason="stop" if i == len(self.token_ids) - 1 else None,
            )

    async def shutdown(self):
        return None

    def metrics(self):
        from dynamo_tpu.engine.engine import ForwardPassMetrics

        return ForwardPassMetrics()


@pytest.fixture(scope="module")
def tool_server():
    from dynamo_tpu.frontends.pipeline import build_pipeline, card_for_model
    from dynamo_tpu.llm.http.service import HttpService

    loop = asyncio.new_event_loop()

    async def boot():
        service = HttpService(host="127.0.0.1", port=0)
        card = card_for_model("tiny", max_model_len=2048)
        card.display_name = "caller"
        service.manager.add(build_pipeline(ScriptedEngine(json.dumps(WEATHER_CALL)), card))
        plain = card_for_model("tiny", max_model_len=2048)
        plain.display_name = "talker"
        service.manager.add(build_pipeline(ScriptedEngine("plain words"), plain))
        port = await service.start()
        return service, f"http://127.0.0.1:{port}"

    service, url = loop.run_until_complete(boot())
    yield loop, url
    loop.run_until_complete(service.stop())
    loop.close()


def _post(loop, url, body):
    async def go():
        async with aiohttp.ClientSession() as s:
            async with s.post(url + "/v1/chat/completions", json=body) as resp:
                return resp.status, await resp.json()

    return loop.run_until_complete(go())


TOOLS = [
    {
        "type": "function",
        "function": {"name": "get_weather", "parameters": {"type": "object"}},
    }
]


def test_e2e_unary_tool_call(tool_server):
    loop, url = tool_server
    status, body = _post(
        loop,
        url,
        {
            "model": "caller",
            "messages": [{"role": "user", "content": "weather?"}],
            "tools": TOOLS,
        },
    )
    assert status == 200
    choice = body["choices"][0]
    assert choice["finish_reason"] == "tool_calls"
    assert choice["message"]["content"] is None
    call = choice["message"]["tool_calls"][0]
    assert call["function"]["name"] == "get_weather"
    assert json.loads(call["function"]["arguments"])["city"] == "SF"


def test_e2e_stream_tool_call(tool_server):
    loop, url = tool_server

    async def go():
        async with aiohttp.ClientSession() as s:
            async with s.post(
                url + "/v1/chat/completions",
                json={
                    "model": "caller",
                    "messages": [{"role": "user", "content": "weather?"}],
                    "tools": TOOLS,
                    "stream": True,
                },
            ) as resp:
                assert resp.status == 200
                chunks = []
                async for line in resp.content:
                    line = line.decode().strip()
                    if line.startswith("data: ") and line != "data: [DONE]":
                        chunks.append(json.loads(line[6:]))
                return chunks

    chunks = loop.run_until_complete(go())
    deltas = [c["choices"][0]["delta"] for c in chunks]
    # no content deltas leak when the response is a tool call
    assert not any(d.get("content") for d in deltas)
    calls = [d for d in deltas if d.get("tool_calls")]
    assert calls and calls[0]["tool_calls"][0]["function"]["name"] == "get_weather"
    assert chunks[-1]["choices"][0]["finish_reason"] == "tool_calls"


def test_e2e_text_response_with_tools_active(tool_server):
    loop, url = tool_server
    status, body = _post(
        loop,
        url,
        {
            "model": "talker",
            "messages": [{"role": "user", "content": "hi"}],
            "tools": TOOLS,
        },
    )
    assert status == 200
    choice = body["choices"][0]
    assert choice["finish_reason"] == "stop"
    assert choice["message"]["content"] == "plain words"
    assert "tool_calls" not in choice["message"]


def test_e2e_tool_choice_without_tools_is_400(tool_server):
    loop, url = tool_server
    status, body = _post(
        loop,
        url,
        {
            "model": "talker",
            "messages": [{"role": "user", "content": "hi"}],
            "tool_choice": "required",
        },
    )
    assert status == 400
    assert "tools" in body["error"]["message"]


def test_e2e_forced_name_not_in_tools_is_400(tool_server):
    loop, url = tool_server
    status, body = _post(
        loop,
        url,
        {
            "model": "talker",
            "messages": [{"role": "user", "content": "hi"}],
            "tools": TOOLS,
            "tool_choice": {"type": "function", "function": {"name": "unknown_fn"}},
        },
    )
    assert status == 400
    assert "unknown_fn" in body["error"]["message"]


def test_e2e_required_choice_unsatisfied_is_422(tool_server):
    loop, url = tool_server
    status, body = _post(
        loop,
        url,
        {
            "model": "talker",  # emits prose, not a tool call
            "messages": [{"role": "user", "content": "hi"}],
            "tools": TOOLS,
            "tool_choice": "required",
        },
    )
    assert status == 422
    assert "required" in body["error"]["message"]
