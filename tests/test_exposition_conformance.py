"""Exposition conformance: every render_metrics / render_stage_metrics
surface must pass utils.prometheus.check_exposition, so new metric families
can't regress HELP/TYPE/label format.

The surfaces come from utils.prometheus._sample_surfaces — the same builders
`python -m dynamo_tpu.utils.prometheus --check` (the lint-gate self-check)
runs, so CI and pytest enforce one list. A composition test additionally
checks the combined colocated exposition (HTTP metrics + SLO + engine stage +
resource families in ONE document) for cross-surface family collisions.
"""

import pytest

from dynamo_tpu.utils.prometheus import _sample_surfaces, check_exposition, self_check

_SURFACES = _sample_surfaces()


@pytest.mark.parametrize(
    "name,text", _SURFACES, ids=[name for name, _ in _SURFACES]
)
def test_surface_exposition_conformant(name, text):
    assert text.strip(), f"{name} rendered empty exposition"
    problems = check_exposition(text)
    assert problems == [], f"{name}: {problems}"


def test_self_check_green():
    assert self_check() == []


def test_surfaces_cover_every_layer():
    """The list must keep covering dataplane client/server, prefill worker,
    engine, http metrics, and components.metrics (the satellite's contract);
    shrinking it silently would hollow the gate out."""
    names = {name for name, _ in _SURFACES}
    for required in (
        "llm.http.metrics",
        "utils.slo",
        "utils.health",
        "utils.goodput",
        "loadgen.replay",
        "engine.render_stage_metrics",
        "disagg.dataplane.server",
        "disagg.dataplane.client",
        "disagg.prefix_fetch.server",
        "disagg.prefix_fetch.client",
        "disagg.prefill_worker",
        "components.metrics",
    ):
        assert required in names, f"missing exposition surface {required}"


def test_goodput_and_replay_families_on_surface():
    """The goodput/replay planes must stay on the conformance-checked
    surface list: windowed goodput by scenario + lifetime verdict counters +
    the per-tenant breakdown (dynamo_goodput_*), and the replay client's
    request/token/schedule-lag families (dynamo_replay_*)."""
    text = dict(_SURFACES)["utils.goodput"]
    assert "# TYPE dynamo_goodput_ratio gauge" in text
    assert 'dynamo_goodput_ratio{scenario="bursty_chat"}' in text
    assert "# TYPE dynamo_goodput_requests_total counter" in text
    assert 'dynamo_goodput_requests_total{result="met",scenario="bursty_chat"}' in text
    assert 'dynamo_goodput_requests_total{result="error",scenario="lora_churn"}' in text
    assert "# TYPE dynamo_goodput_tenant_ratio gauge" in text
    assert 'dynamo_goodput_tenant_ratio{tenant="tenant-a"}' in text
    replay = dict(_SURFACES)["loadgen.replay"]
    assert "# TYPE dynamo_replay_requests_total counter" in replay
    assert 'dynamo_replay_requests_total{result="ok",scenario="bursty_chat"}' in replay
    assert "# TYPE dynamo_replay_tokens_total counter" in replay
    assert "# TYPE dynamo_replay_schedule_lag_seconds histogram" in replay
    assert "# TYPE dynamo_replay_inflight_requests gauge" in replay


def test_engine_surface_carries_goodput_families():
    """The engine-scoped goodput families (colocated compositions keep
    dynamo_goodput_* for the frontend tracker) must stay on the engine
    surface."""
    text = dict(_SURFACES)["engine.render_stage_metrics"]
    assert "# TYPE dynamo_engine_goodput_ratio gauge" in text
    assert "# TYPE dynamo_engine_goodput_requests_total counter" in text


def test_slo_surface_carries_tenant_series():
    """Per-tenant SLO breakdown (item 5's input) must render tenant-labeled
    samples on the same dynamo_slo_* families as the aggregate."""
    text = dict(_SURFACES)["utils.slo"]
    assert 'tenant="tenant-a"' in text
    assert 'dynamo_slo_latency_seconds{metric="ttft",quantile="0.99"}' in text


def test_engine_surface_carries_kv_dtype_bytes_gauges():
    """The int8-KV telemetry families must stay on the conformance-checked
    engine surface: actual-dtype pool bytes + the dtype-labeled per-page
    cost (tools like dynotop render KV bytes from these instead of assuming
    bf16)."""
    text = dict(_SURFACES)["engine.render_stage_metrics"]
    assert "# TYPE dynamo_engine_kv_cache_bytes gauge" in text
    assert "# TYPE dynamo_engine_kv_cache_page_bytes gauge" in text
    assert 'dynamo_engine_kv_cache_page_bytes{dtype="' in text


def test_engine_surface_carries_prefix_fetch_families():
    """The fleet-prefix-cache requester families must stay on the
    conformance-checked engine surface: pull outcomes, pulled blocks/bytes/
    tokens, and the FETCHING_KV dwell histogram."""
    text = dict(_SURFACES)["engine.render_stage_metrics"]
    assert "# TYPE dynamo_prefix_fetch_requests_total counter" in text
    assert 'dynamo_prefix_fetch_requests_total{result="hit"}' in text
    assert 'dynamo_prefix_fetch_requests_total{result="fallback"}' in text
    assert "# TYPE dynamo_prefix_fetch_blocks_total counter" in text
    assert "# TYPE dynamo_prefix_fetch_bytes_total counter" in text
    assert "# TYPE dynamo_prefix_fetch_tokens_total counter" in text
    assert "# TYPE dynamo_prefix_fetch_seconds histogram" in text


def test_engine_surface_carries_long_context_families():
    """The long-context telemetry must stay on the conformance-checked
    engine surface: page-table ladder dispatches by width + rung
    promotions, depth-aware prefill chunk buckets, and the watermark-driven
    cold-KV host drain counter (all validated by `tools/lint.sh --check`
    through the same surface list)."""
    text = dict(_SURFACES)["engine.render_stage_metrics"]
    assert "# TYPE dynamo_engine_context_table_dispatch_total counter" in text
    assert 'dynamo_engine_context_table_dispatch_total{width="' in text
    assert "# TYPE dynamo_engine_context_table_promotions_total counter" in text
    assert "# TYPE dynamo_engine_context_chunk_total counter" in text
    assert 'dynamo_engine_context_chunk_total{len="' in text
    assert "# TYPE dynamo_engine_offload_pressure_blocks_total counter" in text


def test_engine_surface_carries_spec_draft_families():
    """The draft-model speculation telemetry must stay on the conformance-
    checked engine surface: drafting seconds by phase, dispatch/prefill
    counters, the draft model's own KV page pool, and acceptance labeled by
    proposer kind (all validated by `tools/lint.sh --check` through the same
    surface list)."""
    text = dict(_SURFACES)["engine.render_stage_metrics"]
    assert "# TYPE dynamo_spec_draft_seconds_total counter" in text
    assert 'dynamo_spec_draft_seconds_total{phase="dispatch"}' in text
    assert 'dynamo_spec_draft_seconds_total{phase="prefill"}' in text
    assert "# TYPE dynamo_spec_draft_dispatch_total counter" in text
    assert "# TYPE dynamo_spec_draft_prefill_total counter" in text
    assert "# TYPE dynamo_spec_draft_pages gauge" in text
    assert 'dynamo_spec_draft_pages{state="total"}' in text
    assert 'dynamo_spec_draft_pages{state="used"}' in text
    assert "# TYPE dynamo_spec_acceptance_ratio gauge" in text
    assert 'dynamo_spec_acceptance_ratio{proposer="draft"}' in text


def test_colocated_composition_has_no_family_collisions():
    """The in=http serving path concatenates HTTP metrics + frontend SLO +
    engine stage/resource/health/SLO families into one /metrics document;
    duplicate families across surfaces (e.g. two dynamo_slo_* trackers)
    would be a conformance break only visible in composition."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.page_table import PageAllocator
    from dynamo_tpu.engine.scheduler import Scheduler
    from dynamo_tpu.llm.http.metrics import Metrics
    from dynamo_tpu.utils.slo import SloTracker

    cfg = EngineConfig(model_id="tiny", page_size=4, num_pages=8, max_seqs=2,
                       prefill_buckets=(16,))
    eng = AsyncJaxEngine(cfg)
    eng.allocator = PageAllocator(cfg.num_pages, cfg.page_size)
    eng.scheduler = Scheduler(cfg, None, eng.allocator)
    eng.slo.observe("ttft", 0.1)

    m = Metrics()
    m.inc_request("tiny", "chat_completions", "unary", "200")
    m.observe_ttft("tiny", 0.1)
    front_slo = SloTracker({"ttft": 0.5})
    front_slo.observe("ttft", 0.1)

    combined = m.render(front_slo.render_metrics() + eng.render_stage_metrics())
    problems = check_exposition(combined)
    assert problems == [], problems
    # both trackers present, under distinct prefixes
    assert "# TYPE dynamo_slo_latency_seconds gauge" in combined
    assert "# TYPE dynamo_engine_slo_latency_seconds gauge" in combined
