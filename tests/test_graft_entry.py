"""Artifact tests for __graft_entry__.py — the driver's only external probe.

Round-1 postmortem: dryrun_multichip crashed in the driver environment (one real
chip, no virtual mesh) because nothing in tests/ ever executed the artifact.
These tests run it the way the driver does, including the self-provisioning
fallback path, so it can't silently rot again.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_entry_jits():
    import jax

    sys.path.insert(0, REPO)
    try:
        import __graft_entry__ as g
    finally:
        sys.path.remove(REPO)

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.ndim == 2 and out.shape[0] == 4


@pytest.mark.slow
def test_dryrun_multichip_self_provisions():
    """Simulate the driver host: a fresh interpreter with ONE visible device and
    no virtual-mesh flags. dryrun_multichip(8) must detect the shortfall and
    re-exec itself onto an 8-device virtual CPU mesh."""
    sys.path.insert(0, REPO)
    try:
        import __graft_entry__ as g
    finally:
        sys.path.remove(REPO)

    # n_devices=None: 1 CPU device stands in for the 1 real chip
    env = g._virtual_mesh_env(None)
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        "assert len(jax.devices()) == 1, jax.devices(); "
        "import __graft_entry__ as g; g.dryrun_multichip(8); print('GATE-OK')"
    )
    r = subprocess.run(
        [sys.executable, "-c", code],
        env=env,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=900,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "GATE-OK" in r.stdout
