"""Int8 KV cache (quant/kv.py QuantizedPages) across every layer it touches:
per-row quantize/roundtrip error bounds, the ~2x page-capacity arithmetic,
config/registry gating, the XLA reference scatter/gather paths, the Pallas
decode + flash-prefill kernels (interpret mode on CPU), the host-offload
tier, the disagg dataplane's scales-in-header wire format, and end-to-end
greedy agreement against the bf16 cache.

Pure-numpy / loopback-socket tests ride the fast tier; compile-heavy JAX
e2e is marked slow (the repo convention)."""

import asyncio

import numpy as np
import pytest

from dynamo_tpu.quant.kv import (
    QuantizedPages,
    dequantize_rows,
    kv_page_bytes,
    pages_for_hbm_budget,
    quantize_kv_rows,
    wire_concat,
    wire_nbytes,
    wire_pad,
)


# ---------------- quantization math (fast) ----------------


def test_per_row_quantize_roundtrip_error_bound():
    """Symmetric per-row int8: |x - dequant(quant(x))| <= scale/2 per value,
    where scale = row absmax / 127 — the bound the greedy-agreement claims
    rest on."""
    rng = np.random.default_rng(0)
    x = rng.normal(scale=3.0, size=(32, 4, 16)).astype(np.float32)
    x[5] = 0.0  # all-zero row must divide cleanly to zeros
    q, s = quantize_kv_rows(x)
    q, s = np.asarray(q), np.asarray(s)
    assert q.dtype == np.int8
    back = np.asarray(dequantize_rows(q, s))
    err = np.abs(back - x)
    bound = s[:, None, None] * 0.5 + 1e-7
    assert np.all(err <= bound), float((err - bound).max())
    np.testing.assert_array_equal(back[5], 0.0)
    # scales are the per-row absmax / 127
    np.testing.assert_allclose(
        s, np.maximum(np.abs(x).reshape(32, -1).max(axis=1), 1e-12) / 127.0,
        rtol=1e-6,
    )


def test_wire_helpers_dict_and_plain():
    rng = np.random.default_rng(1)
    plain = [rng.normal(size=(2, 2, n, 4)).astype(np.float32) for n in (1, 2)]
    assert wire_concat(plain, axis=2).shape == (2, 2, 3, 4)
    assert wire_nbytes(plain[0]) == plain[0].nbytes
    padded = wire_pad(plain[0], 2, 3)
    assert padded.shape == (2, 2, 4, 4)

    blocks = [
        {"q": rng.integers(-127, 127, (2, 2, n, 4)).astype(np.int8),
         "s": rng.random((2, 2, n, 4)).astype(np.float32)}
        for n in (1, 2)
    ]
    cat = wire_concat(blocks, axis=2)
    assert cat["q"].shape == (2, 2, 3, 4) and cat["s"].shape == (2, 2, 3, 4)
    assert wire_nbytes(blocks[0]) == blocks[0]["q"].nbytes + blocks[0]["s"].nbytes
    pad = wire_pad(blocks[0], 2, 1)
    assert pad["q"].shape == (2, 2, 2, 4)
    np.testing.assert_array_equal(pad["q"][:, :, 1], 0)


def test_page_capacity_doubles_at_equal_hbm_budget():
    """The acceptance arithmetic: ~2x pages at the same HBM budget. At the
    bench headline geometry (ps=128 Hkv=8 D=128 L=24) the scale planes cost
    4/1024 of the int8 page, so the ratio is ~1.97, not exactly 2."""
    args = (128, 8, 128, 24)  # ps, Hkv, D, L
    bf16 = kv_page_bytes(*args, None)
    int8 = kv_page_bytes(*args, "int8")
    assert bf16 == 2 * 24 * 128 * 8 * 128 * 2
    assert int8 == 2 * 24 * 128 * (8 * 128 + 4)
    ratio = pages_for_hbm_budget(1 << 30, *args, "int8") / pages_for_hbm_budget(
        1 << 30, *args, None
    )
    assert 1.9 <= ratio <= 2.0
    # "bf16" spelled explicitly == None
    assert kv_page_bytes(*args, "bf16") == bf16


def test_host_capacity_blocks_resolve_at_actual_wire_dtype():
    """PR-8 follow-up satellite: the host tier's byte budget divides by the
    model's ACTUAL per-page wire cost, not an assumed-bf16 page — so the
    same DRAM budget holds ~2x blocks under an int8 KV cache, and the
    watermark-drain targets operate on a truthful capacity."""
    from dynamo_tpu.engine.offload import resolve_host_capacity_blocks

    args = (128, 8, 128, 24)  # ps, Hkv, D, L
    bf16, int8 = kv_page_bytes(*args, None), kv_page_bytes(*args, "int8")
    budget = 1 << 30
    blocks_bf16 = resolve_host_capacity_blocks(0, budget, bf16)
    blocks_int8 = resolve_host_capacity_blocks(0, budget, int8)
    assert blocks_bf16 == budget // bf16
    assert blocks_int8 == budget // int8
    assert 1.9 <= blocks_int8 / blocks_bf16 <= 2.0
    # when both knobs are set the LARGER resolved capacity wins, either way
    assert resolve_host_capacity_blocks(10, budget, int8) == blocks_int8
    assert resolve_host_capacity_blocks(blocks_int8 + 7, budget, int8) \
        == blocks_int8 + 7
    # a model without page-cost accounting can't honor a byte budget: the
    # engine passes budget_bytes=0 and the explicit block knob stands alone
    assert resolve_host_capacity_blocks(16, 0, 0) == 16
    assert resolve_host_capacity_blocks(0, 0, bf16) == 0


def test_engine_config_validates_host_cache_bytes():
    from dynamo_tpu.engine.config import EngineConfig

    assert EngineConfig(host_cache_bytes=1 << 30).host_cache_bytes == 1 << 30
    with pytest.raises(ValueError, match="host cache"):
        EngineConfig(host_cache_bytes=-1)
    with pytest.raises(ValueError, match="host cache"):
        EngineConfig(host_cache_blocks=-2)


def test_engine_config_validates_kv_cache_dtype():
    from dynamo_tpu.engine.config import EngineConfig

    assert EngineConfig(kv_cache_dtype="int8").kv_quantized
    assert not EngineConfig(kv_cache_dtype="bf16").kv_quantized
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        EngineConfig(kv_cache_dtype="fp8")
    with pytest.raises(ValueError, match="pp"):
        EngineConfig(kv_cache_dtype="int8", pp=2)


def test_registry_gates_mla_and_threads_dtype():
    from dynamo_tpu.models.registry import load_model

    model, _ = load_model("tiny", kv_cache_dtype="int8")
    assert model.config.kv_quantized
    # "bf16" normalizes to the default storage dtype
    model, _ = load_model("tiny", kv_cache_dtype="bf16")
    assert not model.config.kv_quantized
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        load_model("tiny-mla", kv_cache_dtype="int8")


# ---------------- XLA reference paths (fast: tiny shapes) ----------------


def _quantized_pools(rng, P=8, ps=4, Hkv=2, D=8):
    import jax.numpy as jnp

    from dynamo_tpu.quant.kv import init_quantized_pages

    k = init_quantized_pages((P, ps, Hkv, D))
    v = init_quantized_pages((P, ps, Hkv, D))
    return k, v


def test_scatter_gather_reference_roundtrip():
    """scatter_kv quantizes fresh rows into QuantizedPages; gather_pages
    dequantizes the gathered context — the roundtrip error obeys the per-row
    bound and the trash-page convention survives."""
    import jax.numpy as jnp

    from dynamo_tpu.ops.attention import gather_pages, scatter_kv

    rng = np.random.default_rng(2)
    P, ps, Hkv, D = 8, 4, 2, 8
    kp, vp = _quantized_pools(rng, P, ps, Hkv, D)
    T = 6
    k_new = jnp.asarray(rng.normal(size=(T, Hkv, D)), jnp.float32)
    v_new = jnp.asarray(rng.normal(size=(T, Hkv, D)), jnp.float32)
    phys = jnp.asarray([1, 1, 1, 1, 2, 2], jnp.int32)
    offs = jnp.asarray([0, 1, 2, 3, 0, 1], jnp.int32)
    kp, vp = scatter_kv(kp, vp, k_new, v_new, phys, offs)
    assert isinstance(kp, QuantizedPages)
    ctx = gather_pages(kp, jnp.asarray([1, 2], jnp.int32), head_dim=D)
    got = np.asarray(ctx)[:T]
    scales = np.abs(np.asarray(k_new)).reshape(T, -1).max(axis=1) / 127.0
    assert np.all(
        np.abs(got - np.asarray(k_new)) <= scales[:, None, None] * 0.5 + 1e-7
    )
    # untouched page rows stay exactly zero (zero scale plane)
    np.testing.assert_array_equal(np.asarray(ctx)[ps * 2 - 2 :], 0.0)
    # folded layout: same roundtrip through [T, Hkv*D] rows
    from dynamo_tpu.quant.kv import init_quantized_pages

    kf = init_quantized_pages((P, ps, Hkv * D))
    vf = init_quantized_pages((P, ps, Hkv * D))
    kf, vf = scatter_kv(kf, vf, k_new, v_new, phys, offs)
    ctx_f = gather_pages(kf, jnp.asarray([1, 2], jnp.int32), head_dim=D)
    np.testing.assert_allclose(np.asarray(ctx_f)[:T], got, atol=1e-6)


# ---------------- dataplane wire format (fast: loopback) ----------------


def test_dataplane_int8_part_half_bytes_and_scales_in_header():
    """An int8 part's payload is the int8 data (~half the bf16 wire bytes);
    the scale plane rides the header and comes back on KvPart.scales; the
    per-part checksum still covers (and rejects) the payload."""
    from dynamo_tpu.disagg.dataplane import KvDataPlaneClient, KvDataPlaneServer

    async def body():
        server = await KvDataPlaneServer(host="127.0.0.1").start()
        client = KvDataPlaneClient()
        try:
            rng = np.random.default_rng(5)
            L, n, ps, H, D = 2, 3, 4, 2, 8
            q = rng.integers(-127, 127, (L, 2, n, ps, H, D)).astype(np.int8)
            s = rng.random((L, 2, n, ps)).astype(np.float32)
            bf16_equiv_bytes = q.size * 2

            token = server.expect("r1")
            parts = []
            server.set_consumer("r1", parts.append)
            await client.send_part(
                server.address, "r1", {"q": q, "s": s}, token=token,
                part_seq=0, part_total=1, page_from=0, page_to=n, cat_axis=2,
            )
            await server.receive("r1", timeout=5)
            (part,) = parts
            np.testing.assert_array_equal(part.data, q)
            np.testing.assert_array_equal(part.scales, s)
            wd = part.wire_data()
            assert set(wd) == {"q", "s"}
            # the wire payload halves: int8 bytes vs the bf16 equivalent
            assert server.bytes_received == q.nbytes
            assert server.bytes_received * 2 == bf16_equiv_bytes

            # corrupt payload still trips the per-part checksum
            token2 = server.expect("r2")
            orig = KvDataPlaneClient.send_part
            import xxhash

            async def bad_send(self, *a, **kw):
                real = xxhash.xxh3_64_intdigest
                xxhash.xxh3_64_intdigest = lambda _: 0xBAD
                try:
                    return await orig(self, *a, **kw)
                finally:
                    xxhash.xxh3_64_intdigest = real

            await bad_send(client, server.address, "r2", {"q": q, "s": s},
                           token=token2)
            with pytest.raises(Exception):
                await server.receive("r2", timeout=5)
            assert server.checksum_failures == 1
        finally:
            await client.close()
            await server.stop()

    asyncio.run(body())


def test_dataplane_int8_multipart_reassembly_without_consumer():
    """Consumer-less reassembly of int8 parts concatenates BOTH leaves on
    the page axis and yields the {"q","s"} wire dict."""
    from dynamo_tpu.disagg.dataplane import KvDataPlaneClient, KvDataPlaneServer

    async def body():
        server = await KvDataPlaneServer(host="127.0.0.1").start()
        client = KvDataPlaneClient()
        try:
            rng = np.random.default_rng(6)
            def blk(n, seed):
                r = np.random.default_rng(seed)
                return (r.integers(-127, 127, (2, 2, n, 4)).astype(np.int8),
                        r.random((2, 2, n, 4)).astype(np.float32))

            token = server.expect("r3")
            (q0, s0), (q1, s1) = blk(1, 1), blk(2, 2)
            # out of order: tail first
            await client.send_part(server.address, "r3", {"q": q1, "s": s1},
                                   token=token, part_seq=1, part_total=2,
                                   page_from=1, page_to=3, cat_axis=2)
            await client.send_part(server.address, "r3", {"q": q0, "s": s0},
                                   token=token, part_seq=0, part_total=2,
                                   page_from=0, page_to=1, cat_axis=2)
            got = await server.receive("r3", timeout=5)
            np.testing.assert_array_equal(got["q"], np.concatenate([q0, q1], axis=2))
            np.testing.assert_array_equal(got["s"], np.concatenate([s0, s1], axis=2))
        finally:
            await client.close()
            await server.stop()

    asyncio.run(body())


def test_prefill_result_inline_carries_scales():
    from dynamo_tpu.llm.remote_prefill import PrefillResult

    rng = np.random.default_rng(7)
    q = rng.integers(-127, 127, (2, 2, 1, 4, 2, 8)).astype(np.int8)
    s = rng.random((2, 2, 1, 4)).astype(np.float32)
    r = PrefillResult(
        request_id="x", first_token=1, prompt_len=4, skip_leading_tokens=0,
        kv_shape=q.shape, kv_dtype=str(q.dtype), kv_bytes=q.tobytes(),
        kv_scales_bytes=s.tobytes(), kv_scales_shape=s.shape,
        kv_scales_dtype=str(s.dtype),
    )
    r2 = PrefillResult.from_wire(r.to_wire())
    arr = r2.kv_array()
    np.testing.assert_array_equal(arr["q"], q)
    np.testing.assert_array_equal(arr["s"], s)


# ---------------- compile-heavy JAX e2e (slow tier) ----------------

pytest_slow = pytest.mark.slow


@pytest.mark.slow
def test_runner_extract_inject_roundtrip_int8():
    """ModelRunner block IO with an int8 cache: extract returns the {"q","s"}
    wire dict, inject_pages_bucketed pads both leaves, and a full roundtrip
    between two runners is byte-exact (int8 + scales are copied verbatim —
    no requantization on the wire)."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.model_runner import ModelRunner
    from dynamo_tpu.models.registry import load_model

    cfg = EngineConfig(
        model_id="tiny", page_size=4, num_pages=16, max_seqs=2,
        max_model_len=32, prefill_buckets=(8,), kv_cache_dtype="int8",
    )
    model, params = load_model("tiny", kv_cache_dtype="int8")
    runner = ModelRunner(cfg, model, params)

    rng = np.random.default_rng(8)
    tmpl = runner.extract_pages(np.array([1, 2, 3], np.int32))
    assert set(tmpl) == {"q", "s"} and tmpl["q"].dtype == np.int8
    data = {
        "q": rng.integers(-127, 127, tmpl["q"].shape).astype(np.int8),
        "s": rng.random(tmpl["s"].shape).astype(np.float32),
    }
    runner.inject_pages_bucketed(np.array([1, 2, 3], np.int32), data)

    got = runner.extract_pages(np.array([1, 2, 3], np.int32))
    np.testing.assert_array_equal(got["q"], data["q"])
    np.testing.assert_array_equal(got["s"], data["s"])

    # second runner adopts the blocks verbatim (the disagg inject path)
    model2, params2 = load_model("tiny", kv_cache_dtype="int8")
    runner2 = ModelRunner(cfg, model2, params2)
    runner2.inject_pages(np.array([5, 6, 7], np.int32), got)
    got2 = runner2.extract_pages(np.array([5, 6, 7], np.int32))
    np.testing.assert_array_equal(got2["q"], data["q"])
    np.testing.assert_array_equal(got2["s"], data["s"])


@pytest.mark.slow
def test_host_kv_pool_roundtrip_int8():
    """HostKvPool save/load with int8 pages + scales: blocks survive the
    host tier byte-exact, including the bucketed load_many restore."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.model_runner import ModelRunner
    from dynamo_tpu.engine.offload import HostKvPool
    from dynamo_tpu.models.registry import load_model

    cfg = EngineConfig(
        model_id="tiny", page_size=4, num_pages=16, max_seqs=2,
        max_model_len=32, prefill_buckets=(8,), kv_cache_dtype="int8",
    )
    model, params = load_model("tiny", kv_cache_dtype="int8")
    runner = ModelRunner(cfg, model, params)
    pool = HostKvPool(runner, capacity_blocks=8)

    rng = np.random.default_rng(9)
    tmpl = runner.extract_pages(np.array([1, 2, 3], np.int32))
    data = {
        "q": rng.integers(-127, 127, tmpl["q"].shape).astype(np.int8),
        "s": rng.random(tmpl["s"].shape).astype(np.float32),
    }
    runner.inject_pages(np.array([1, 2, 3], np.int32), data)
    for h, p in ((901, 1), (902, 2), (903, 3)):
        pool.save(h, p)
    hits = pool.load_many([(901, 7), (902, 8), (903, 9)])
    assert hits == {901, 902, 903}
    got = runner.extract_pages(np.array([7, 8, 9], np.int32))
    np.testing.assert_array_equal(got["q"], data["q"])
    np.testing.assert_array_equal(got["s"], data["s"])


def _kernel_case(seed=0, B=3, Hq=4, Hkv=2, D=128, P=16, ps=8, mp=6):
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((B, Hq, D)), jnp.float32)

    def qpages(x):
        flat = jnp.asarray(x.reshape(P * ps, *x.shape[2:]), jnp.float32)
        qq, ss = quantize_kv_rows(flat)
        return QuantizedPages(qq.reshape(x.shape), ss.reshape(P, ps))

    k = rng.standard_normal((P, ps, Hkv, D)).astype(np.float32)
    v = rng.standard_normal((P, ps, Hkv, D)).astype(np.float32)
    pt = np.zeros((B, mp), np.int32)
    for b in range(B):
        pt[b] = rng.choice(np.arange(1, P), size=mp, replace=False)
    pos = jnp.asarray([3, 21, 47], jnp.int32)[:B]
    return q, qpages(k), qpages(v), jnp.asarray(pt), pos


@pytest.mark.slow
def test_decode_kernels_int8_match_reference():
    """perseq / lookahead / folded decode kernels on int8 pools (interpret
    mode) match the XLA reference, which dequantizes the same int8 values —
    the comparison isolates the kernels' in-VMEM scale application."""
    from dynamo_tpu.ops.attention import paged_decode_attention
    from dynamo_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_pallas,
        paged_decode_attention_pallas_folded,
        paged_decode_attention_pallas_lookahead,
    )

    q, kq, vq, pt, pos = _kernel_case()
    ref = np.asarray(paged_decode_attention(q, kq, vq, pt, pos))
    for fn in (
        paged_decode_attention_pallas,
        paged_decode_attention_pallas_lookahead,
        paged_decode_attention_pallas_folded,
    ):
        got = np.asarray(fn(q, kq, vq, pt, pos, interpret=True))
        np.testing.assert_allclose(got, ref, atol=2e-4, err_msg=fn.__name__)


@pytest.mark.slow
def test_prefill_kernels_int8_match_reference():
    """Lookahead + basic flash prefill on int8 pools (interpret mode) match
    the dequantizing XLA reference; the folded variant covers sub-128
    head_dim."""
    import jax.numpy as jnp

    from dynamo_tpu.ops.attention import paged_prefill_attention
    from dynamo_tpu.ops.pallas.prefill_attention import (
        paged_prefill_attention_pallas,
        paged_prefill_attention_pallas_folded,
    )

    rng = np.random.default_rng(4)
    q, kq, vq, _, _ = _kernel_case(seed=4)
    T = 128
    qp = jnp.asarray(rng.standard_normal((T, 4, 128)), jnp.float32)
    ptab = jnp.asarray(np.arange(1, 7, dtype=np.int32))
    positions = jnp.asarray(np.arange(T, dtype=np.int32) + 5)
    ref = np.asarray(paged_prefill_attention(qp, kq, vq, ptab, positions))
    for lookahead in (True, False):
        got = np.asarray(paged_prefill_attention_pallas(
            qp, kq, vq, ptab, positions, interpret=True, lookahead=lookahead
        ))
        np.testing.assert_allclose(
            got, ref, atol=2e-4, err_msg=f"lookahead={lookahead}"
        )

    # folded: D=16, Hkv=8 -> F=128
    P, ps, Hkv, D = 16, 8, 8, 16
    q2 = jnp.asarray(rng.standard_normal((T, 8, D)), jnp.float32)

    def qpages(x):
        flat = jnp.asarray(x.reshape(P * ps, -1), jnp.float32)
        qq, ss = quantize_kv_rows(flat)
        return QuantizedPages(
            qq.reshape(P, ps, Hkv * D), ss.reshape(P, ps)
        )

    k2 = rng.standard_normal((P, ps, Hkv, D)).astype(np.float32)
    v2 = rng.standard_normal((P, ps, Hkv, D)).astype(np.float32)
    k2q, v2q = qpages(k2), qpages(v2)
    ref2 = np.asarray(paged_prefill_attention(q2, k2q, v2q, ptab, positions))
    got2 = np.asarray(paged_prefill_attention_pallas_folded(
        q2, k2q, v2q, ptab, positions, block_q=64, interpret=True
    ))
    np.testing.assert_allclose(got2, ref2, atol=2e-4)


@pytest.mark.slow
def test_prefill_folded_tp2_shard_map(monkeypatch):
    """The ISSUE satellite: the folded (sub-128 head_dim) prefill kernel now
    runs under shard_map at tp>1 instead of silently falling back to the
    gather reference — per-shard folded lanes stay 128-aligned (Hkv/tp * D
    = 8 * 16 = 128) and the output matches the unsharded reference."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from dynamo_tpu.ops.attention import (
        dispatch_paged_prefill_attention,
        paged_prefill_attention,
    )

    monkeypatch.setenv("DYNTPU_PALLAS", "1")
    rng = np.random.default_rng(11)
    T, Hq, Hkv, D, P, ps, mp = 128, 16, 16, 16, 12, 8, 6
    q = jnp.asarray(rng.standard_normal((T, Hq, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((P, ps, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((P, ps, Hkv, D)), jnp.float32)
    kf = k.reshape(P, ps, Hkv * D)
    vf = v.reshape(P, ps, Hkv * D)
    ptab = jnp.asarray(np.arange(1, mp + 1, dtype=np.int32))
    positions = jnp.asarray(np.arange(T, dtype=np.int32))
    ref = np.asarray(paged_prefill_attention(q, kf, vf, ptab, positions))
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    got = np.asarray(jax.jit(
        lambda *a: dispatch_paged_prefill_attention(*a, mesh=mesh)
    )(q, kf, vf, ptab, positions))
    np.testing.assert_allclose(got, ref, atol=2e-4)

    # and the int8 variant shards too (scale plane replicated over tp)
    flat_k = quantize_kv_rows(k.reshape(P * ps, Hkv * D))
    flat_v = quantize_kv_rows(v.reshape(P * ps, Hkv * D))
    kq = QuantizedPages(flat_k[0].reshape(P, ps, Hkv * D), flat_k[1].reshape(P, ps))
    vq = QuantizedPages(flat_v[0].reshape(P, ps, Hkv * D), flat_v[1].reshape(P, ps))
    ref_q = np.asarray(paged_prefill_attention(q, kq, vq, ptab, positions))
    got_q = np.asarray(jax.jit(
        lambda *a: dispatch_paged_prefill_attention(*a, mesh=mesh)
    )(q, kq, vq, ptab, positions))
    np.testing.assert_allclose(got_q, ref_q, atol=2e-4)


@pytest.mark.slow
def test_engine_int8_kv_teacher_forced_agreement():
    """The acceptance bar: greedy decode agreement >= 0.9 over 64
    teacher-forced steps with kv_cache_dtype=int8 vs the bf16 cache (same
    weights; the cache is the only delta)."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.models.registry import load_model

    PROMPT, STEPS, PS = 48, 64, 16
    rng = np.random.default_rng(23)
    probe = rng.integers(1, 250, PROMPT)
    positions = np.arange(PROMPT, dtype=np.int32)
    n_pages = -(-(PROMPT + STEPS) // PS) + 1
    page_table = np.arange(1, n_pages + 1, dtype=np.int32)

    def chain(kv_dtype, forced=None):
        model, params = load_model("tiny", kv_cache_dtype=kv_dtype)
        kv = model.init_kv_cache(n_pages + 2, PS)
        pts = np.zeros((1, n_pages + 2), np.int32)
        pts[0, : len(page_table)] = page_table
        logits, kv = jax.jit(model.prefill)(
            params, kv, jnp.asarray(probe, jnp.int32), jnp.asarray(positions),
            jnp.asarray(page_table), jnp.ones(PROMPT, bool),
            jnp.asarray(PROMPT - 1),
        )
        decode = jax.jit(model.decode)
        out = [int(np.asarray(jax.device_get(logits)).argmax())]
        feed = out[0] if forced is None else forced[0]
        for i in range(STEPS - 1):
            logits, kv = decode(
                params, kv, jnp.asarray([feed], jnp.int32),
                jnp.asarray([PROMPT + i], jnp.int32), jnp.asarray(pts),
                jnp.asarray([True]),
            )
            tok = int(np.asarray(jax.device_get(logits))[0].argmax())
            out.append(tok)
            feed = tok if forced is None else forced[i + 1]
        return out

    ref = chain(None)
    tf = chain("int8", forced=ref)
    agreement = sum(int(a == b) for a, b in zip(ref, tf)) / STEPS
    assert agreement >= 0.9, f"teacher-forced agreement {agreement}"


@pytest.mark.slow
def test_engine_e2e_int8_kv_serves():
    """Full engine with kv_cache_dtype=int8: generates greedy tokens through
    the scheduler/runner (packed prefill + fused decode windows) and the
    resource snapshot reports the int8 page-byte accounting."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import EngineRequest

    cfg = EngineConfig(
        model_id="tiny", page_size=4, num_pages=64, max_seqs=2,
        max_model_len=128, prefill_buckets=(16, 32), decode_steps=4,
        pipeline_depth=2, kv_cache_dtype="int8",
    )

    async def body():
        eng = AsyncJaxEngine(cfg)
        await eng.start()
        try:
            req = EngineRequest(
                request_id="r", token_ids=list(range(40, 59)),
                sampling=SamplingParams(temperature=0.0, max_tokens=8,
                                        ignore_eos=True),
            )
            toks = []
            async for out in eng.generate(req):
                if out.token is not None:
                    toks.append(out.token)
            assert len(toks) == 8
            snap = eng.resource_snapshot()
            assert snap["kv_cache_dtype"] == "int8"
            assert snap["kv_page_bytes"] == eng.runner.model.kv_page_bytes(4)
            assert snap["kv_pool_bytes_total"] == snap["kv_page_bytes"] * 63
            # the int8 page costs ~half the bf16 page
            from dynamo_tpu.quant.kv import kv_page_bytes as pb

            c = eng.runner.model.config
            bf16 = pb(4, c.num_kv_heads, c.head_dim, c.num_layers, None)
            assert snap["kv_page_bytes"] < 0.6 * bf16
        finally:
            await eng.shutdown()

    asyncio.run(body())
