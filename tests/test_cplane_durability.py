"""Control-plane durability + recovery: the broker persists non-lease KV and
work queues to an append log (the etcd raft-log / JetStream file-store slot,
reference: lib/runtime/src/transports/{etcd,nats}.rs), and clients heal a
broker restart transparently — reconnect, re-subscribe, re-watch (with
synthetic resync events), re-attach leases under their original ids, and
re-register served endpoints."""

import asyncio

import pytest

from dynamo_tpu.cplane.broker import Broker
from dynamo_tpu.cplane.client import CplaneClient
from dynamo_tpu.runtime.distributed import DistributedRuntime


def test_broker_persistence_across_restart(tmp_path):
    path = str(tmp_path / "broker.log")

    async def body():
        b1 = Broker(persist_path=path)
        port = await b1.start()
        c1 = await CplaneClient(f"127.0.0.1:{port}").connect()
        await c1.kv_put("durable/a", b"v1")
        await c1.kv_put("durable/b", b"v2")
        await c1.kv_delete("durable/b")
        lease = await c1.lease_create(ttl=5.0)
        await c1.kv_put("ephemeral/x", b"gone", lease_id=lease.lease_id)
        await c1.queue_push("jobs", {"n": 1})
        await c1.queue_push("jobs", {"n": 2})
        m = await c1.queue_pull("jobs")
        await c1.queue_ack("jobs", m.msg_id)  # n=1 consumed; n=2 must survive
        await c1.close()
        await b1.stop()

        b2 = Broker(persist_path=path)
        port2 = await b2.start()
        c2 = await CplaneClient(f"127.0.0.1:{port2}").connect()
        assert await c2.kv_get("durable/a") == b"v1"
        assert await c2.kv_get("durable/b") is None
        assert await c2.kv_get("ephemeral/x") is None  # lease keys not durable
        m2 = await c2.queue_pull("jobs", timeout=2)
        assert m2.payload == {"n": 2}
        await c2.close()
        await b2.stop()

    asyncio.new_event_loop().run_until_complete(body())


def test_client_heals_broker_restart_mid_serving(tmp_path):
    """Kill the broker under a served endpoint + watcher + queue, restart it
    on the same port, and verify the whole session heals: lease re-attached
    under its original id, endpoint re-registered and callable, watch resync
    events delivered, queued work still there."""
    path = str(tmp_path / "broker.log")

    async def body():
        b1 = Broker(persist_path=path)
        port = await b1.start()
        addr = f"127.0.0.1:{port}"

        drt = DistributedRuntime(cplane_address=addr)
        await drt.connect()
        drt.cplane.reconnect_window = 15.0
        died = []
        drt.runtime.shutdown = lambda: died.append(True)  # observe give-up

        async def echo(req):
            yield {"echo": req}

        ep = drt.namespace("dur").component("svc").endpoint("run")
        served = await ep.serve_endpoint(echo)
        client = await drt.endpoint_client("dyn://dur.svc.run")
        await client.wait_for_instances(timeout=10)

        async def call():
            outs = []
            async for out in await client.random({"x": 1}):
                outs.append(out)
            return outs

        assert (await call())[0]["echo"] == {"x": 1}
        lease_id_before = drt.primary_lease.lease_id

        watcher = await drt.cplane.kv_get_and_watch_prefix("cfg/")
        await drt.cplane.kv_put("cfg/one", b"1")
        await drt.cplane.queue_push("dur.jobs", {"job": 7})

        # ---- kill the broker, restart on the SAME port with the same log ----
        await b1.stop()
        await asyncio.sleep(0.5)
        b2 = Broker(port=port, persist_path=path)
        await b2.start()

        # the client heals in the background; the endpoint must come back
        deadline = asyncio.get_running_loop().time() + 20
        ok = False
        while asyncio.get_running_loop().time() < deadline:
            try:
                outs = await asyncio.wait_for(call(), 3)
                if outs and outs[0].get("echo") == {"x": 1}:
                    ok = True
                    break
            except Exception:
                await asyncio.sleep(0.3)
        assert ok, "endpoint did not heal after broker restart"
        assert not died, "client gave up despite successful restart"
        assert drt.primary_lease.lease_id == lease_id_before  # identity kept

        # watch healed: resync replayed the durable key, and new events flow
        seen = {}
        async def drain_watch():
            async for ev in watcher.events():
                seen[ev.key] = (ev.kind, ev.value)
                if "cfg/two" in seen:
                    return
        drain = asyncio.create_task(drain_watch())
        await drt.cplane.kv_put("cfg/two", b"2")
        await asyncio.wait_for(drain, 10)
        assert seen["cfg/one"] == ("put", b"1")  # synthetic resync event
        assert seen["cfg/two"] == ("put", b"2")  # live post-heal event

        # queued work survived the restart
        m = await drt.cplane.queue_pull("dur.jobs", timeout=3)
        assert m.payload == {"job": 7}

        await served.stop()
        await drt._shutdown_hook()
        await b2.stop()

    asyncio.new_event_loop().run_until_complete(asyncio.wait_for(body(), 90))


def test_client_gives_up_when_broker_stays_dead():
    async def body():
        b = Broker()
        port = await b.start()
        c = await CplaneClient(f"127.0.0.1:{port}", reconnect_window=1.0).connect()
        gave_up = asyncio.Event()
        c.on_disconnect = gave_up.set
        await c.kv_put("k", b"v")
        await b.stop()
        await asyncio.wait_for(gave_up.wait(), 15)
        with pytest.raises(ConnectionError):
            await c.kv_put("k2", b"v2")
        await c.close()

    asyncio.new_event_loop().run_until_complete(asyncio.wait_for(body(), 30))

def test_lease_readoption_requires_secret():
    """Lease ids are broadcast to every watcher, so re-adopting one must
    require the owner's secret — a peer that only knows the id can neither
    hijack the lease nor force-close the owner's connection (ADVICE r2)."""
    import asyncio

    from dynamo_tpu.cplane.broker import Broker
    from dynamo_tpu.cplane.client import CplaneClient

    async def run():
        broker = Broker()
        port = await broker.start()
        owner = await CplaneClient(f"127.0.0.1:{port}").connect()
        attacker = await CplaneClient(f"127.0.0.1:{port}").connect()
        try:
            lease = await owner.lease_create(ttl=5.0)
            await owner.kv_put("k/own", b"v", lease_id=lease.lease_id)

            # hijack attempt: correct id, wrong secret
            try:
                await attacker._request({
                    "op": "lease_create", "ttl": 5.0,
                    "lease_id": lease.lease_id, "secret": "not-the-secret",
                })
                raise AssertionError("hijack with wrong secret succeeded")
            except Exception as e:
                assert "secret" in str(e)

            # owner's lease and key are untouched, connection still live
            r = await owner._request({"op": "kv_get", "key": "k/own"})
            assert r["found"]

            # the owner itself re-adopts fine (its secret travels along)
            await owner._request({
                "op": "lease_create", "ttl": 5.0,
                "lease_id": lease.lease_id, "secret": lease.secret,
            })
        finally:
            await owner.close()
            await attacker.close()
            await broker.stop()

    asyncio.run(run())
