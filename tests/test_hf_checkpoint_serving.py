"""End-to-end HTTP serving of a real HF-FORMAT checkpoint directory: config +
safetensors weights + a genuine trained BPE tokenizer with a chat template —
the full OpenAI-frontend path a user of the reference exercises (reference:
docs/architecture.md serving-stack numbers are HTTP-level, not engine-level).
"""

import asyncio
import sys

import pytest

sys.path.insert(0, ".")

pytestmark = pytest.mark.slow


def test_serve_synthetic_hf_checkpoint(tmp_path):
    from tools.make_hf_checkpoint import TINY_GEOMETRY, make_checkpoint

    ckpt = make_checkpoint(str(tmp_path / "ckpt"), TINY_GEOMETRY)

    async def run():
        import aiohttp

        from dynamo_tpu.engine.config import EngineConfig
        from dynamo_tpu.engine.engine import AsyncJaxEngine
        from dynamo_tpu.frontends.pipeline import build_pipeline
        from dynamo_tpu.llm.http.service import HttpService
        from dynamo_tpu.llm.model_card import ModelDeploymentCard

        card = ModelDeploymentCard.from_local_path(str(ckpt), name="synth")
        engine = AsyncJaxEngine(EngineConfig.for_model(
            str(ckpt), page_size=16, num_pages=64, max_seqs=4,
            max_model_len=256, prefill_buckets=(32, 64),
        ))
        await engine.start()
        svc = HttpService(host="127.0.0.1", port=0)
        svc.manager.add(build_pipeline(engine, card))
        port = await svc.start()
        base = f"http://127.0.0.1:{port}/v1"
        try:
            async with aiohttp.ClientSession() as s:
                async with s.get(f"http://127.0.0.1:{port}/v1/models") as r:
                    models = await r.json()
                assert any(m["id"] == "synth" for m in models["data"])
                body = {
                    "model": "synth",
                    "messages": [{"role": "user", "content": "hello there friend"}],
                    "max_tokens": 8,
                    "temperature": 0.0,
                    "ext": {"ignore_eos": True, "annotations": ["token_ids"]},
                }
                async with s.post(f"{base}/chat/completions", json=body) as r:
                    assert r.status == 200, await r.text()
                    out = await r.json()
                text = out["choices"][0]["message"]["content"]
                assert out["usage"]["completion_tokens"] == 8
                # the trained BPE tokenizer round-trips: decoded text is
                # re-encodable and non-empty for 8 real sampled tokens
                assert isinstance(text, str) and len(text) > 0
                # deterministic greedy: same request, same answer
                async with s.post(f"{base}/chat/completions", json=body) as r:
                    assert (await r.json())["choices"][0]["message"]["content"] == text
        finally:
            await svc.stop()
            await engine.shutdown()

    asyncio.new_event_loop().run_until_complete(run())
