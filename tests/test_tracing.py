"""End-to-end request tracing + per-stage latency attribution.

Covers the observability plane: the span recorder (utils/tracing.py), trace-id
propagation across runtime hops via the RequestContext metadata bag, the
serving-stack Prometheus histograms (TTFT / inter-token latency / queue wait),
promtool-style exposition conformance of every /metrics producer, the /trace
debug endpoint, request-id stamping in log records, and the stitched two-hop
disagg trace (decode worker + prefill worker sharing one trace id).
"""

import asyncio
import json
import logging

import pytest

from dynamo_tpu.runtime.context import RequestContext, new_context, use_context
from dynamo_tpu.utils import tracing
from dynamo_tpu.utils.prometheus import (
    Histogram,
    check_exposition,
    fmt_value,
    render_family,
)


@pytest.fixture(autouse=True)
def clean_tracer():
    """Each test sees an empty ring and leaves the recorder disabled."""
    tracing.clear()
    yield
    tracing.disable()
    tracing.clear()


# ---------------- span recorder ----------------


def test_recorder_disabled_is_noop():
    assert not tracing.enabled()
    with tracing.span("x"):
        pass
    tracing.record_span("y", 0.0, duration=1.0)
    assert tracing.events() == []


def test_span_records_chrome_events(tmp_path):
    path = tmp_path / "trace.jsonl"
    tracing.enable(str(path))
    with tracing.span("stage.a", foo=7):
        pass
    tracing.record_span("stage.b", 1.0, duration=0.5, request_id="r1", trace_id="t1")
    evs = tracing.events()
    assert [e["name"] for e in evs] == ["stage.a", "stage.b"]
    a, b = evs
    assert a["ph"] == "X" and a["cat"] == "dyntpu"
    assert a["args"]["foo"] == 7
    assert isinstance(a["ts"], int) and isinstance(a["dur"], int)
    assert b["dur"] == 500_000  # µs
    assert b["args"]["trace_id"] == "t1" and b["args"]["request_id"] == "r1"
    # filtering
    assert [e["name"] for e in tracing.events(trace_id="t1")] == ["stage.b"]
    assert tracing.events(request_id="nope") == []
    # the JSONL file carries the same events, one parseable object per line
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert [e["name"] for e in lines] == ["stage.a", "stage.b"]
    # the export document is Perfetto-shaped
    doc = tracing.export(trace_id="t1")
    assert [e["name"] for e in doc["traceEvents"]] == ["stage.b"]


def test_span_ids_default_to_ambient_context():
    tracing.enable()
    ctx = new_context(request_id="req-9", metadata={"trace_id": "trace-9"})
    with use_context(ctx):
        with tracing.span("inside"):
            pass
    with tracing.span("outside"):
        pass
    inside, outside = tracing.events()
    assert inside["args"]["request_id"] == "req-9"
    assert inside["args"]["trace_id"] == "trace-9"
    assert outside["args"]["request_id"] is None


def test_context_trace_id_helpers():
    ctx = new_context(request_id="rid")
    assert ctx.trace_id == "rid"  # falls back to the request id
    assert ctx.ensure_trace_id() == "rid"
    assert ctx.metadata["trace_id"] == "rid"
    ctx2 = RequestContext.from_wire(ctx.to_wire())
    assert ctx2.trace_id == "rid"  # survives the wire round trip
    ctx3 = new_context(metadata={"trace_id": "edge"})
    assert ctx3.trace_id == "edge"
    ctx3.ensure_trace_id()
    assert ctx3.metadata["trace_id"] == "edge"  # idempotent, edge stamp wins


# ---------------- prometheus helpers ----------------


def test_fmt_value_canonical():
    assert fmt_value(0.005) == "0.005"
    assert fmt_value(1.0) == "1"
    assert fmt_value(60) == "60"
    assert fmt_value(float("inf")) == "+Inf"
    # a computed bucket bound must not render as repr() noise
    assert fmt_value(0.1 + 0.2) == "0.3"


def test_histogram_render_conformant():
    h = Histogram("t_seconds", "a test histogram", (0.1, 1.0), ("model",))
    h.observe(0.05, ("m1",))
    h.observe(0.5, ("m1",))
    h.observe(5.0, ("m2",))
    text = h.render()
    assert check_exposition(text) == []
    assert 't_seconds_bucket{le="0.1",model="m1"} 1' in text
    assert 't_seconds_bucket{le="+Inf",model="m1"} 2' in text
    assert 't_seconds_count{model="m2"} 1' in text
    assert h.count == 3


def test_check_exposition_catches_violations():
    # sample with no HELP/TYPE
    assert check_exposition("foo 1\n")
    # duplicate TYPE
    bad = "# HELP f h\n# TYPE f gauge\n# TYPE f gauge\nf 1\n"
    assert any("duplicate TYPE" in p for p in check_exposition(bad))
    # unparseable le
    bad = (
        "# HELP h x\n# TYPE h histogram\n"
        'h_bucket{le="abc"} 1\nh_sum 1\nh_count 1\n'
    )
    assert any("le" in p for p in check_exposition(bad))
    # conformant family passes
    good = render_family("g_total", "counter", "help", [({"a": "b"}, 2)])
    assert check_exposition(good) == []


def test_http_metrics_render_conformant():
    from dynamo_tpu.llm.http.metrics import Metrics

    m = Metrics()
    m.inc_request("m", "chat_completions", "stream", "200")
    m.inflight("m", 1)
    m.observe_duration("m", "chat_completions", 0.25)
    m.observe_ttft("m", 0.03)
    m.observe_itl("m", 0.004)
    text = m.render()
    assert check_exposition(text) == []
    assert "llm_http_service_time_to_first_token_seconds_bucket" in text
    assert "llm_http_service_inter_token_latency_seconds_count" in text
    # le labels are canonical floats, not repr() output
    assert 'le="0.005"' in text


def test_metrics_component_render_conformant():
    """Satellite: components/metrics.py must emit one HELP/TYPE pair per
    family (the old render had a single free-text comment for everything)."""
    import time

    from dynamo_tpu.components.metrics import MetricsService
    from dynamo_tpu.llm.kv_router.metrics_aggregator import WorkerView
    from dynamo_tpu.llm.kv_router.scheduler import WorkerLoad

    class _Drt:
        cplane = None

    svc = MetricsService(_Drt(), "ns", "backend")
    kv = {
        "request_active_slots": 1, "request_total_slots": 8,
        "kv_active_blocks": 5, "kv_total_blocks": 100,
        "num_requests_waiting": 0, "gpu_cache_usage_perc": 0.05,
        "gpu_prefix_cache_hit_rate": 0.5,
    }
    stage = {
        "queue_wait_s": 0.5, "prefill_s": 1.25, "decode_dispatch_s": 3.0,
        "reconcile_wait_s": 0.1, "queue_wait_n": 4,
    }
    svc.aggregator._workers[0xAB] = WorkerView(
        0xAB,
        data={"kv_metrics": kv, "stage_seconds": stage},
        load=WorkerLoad.from_wire(0xAB, kv),
        last_seen=time.monotonic(),
    )
    svc._isl_blocks, svc._overlap_blocks = 10, 4
    text = svc.render()
    assert check_exposition(text) == [], check_exposition(text)
    # every family got its own HELP/TYPE
    assert text.count("# TYPE llm_kv_kv_active_blocks ") == 1
    assert "# TYPE llm_kv_kv_active_blocks_avg gauge" in text
    assert "llm_kv_hit_rate_percent" in text and "40.0" in text
    # per-stage engine seconds aggregated from worker stats
    assert 'llm_engine_stage_seconds_total{' in text
    assert 'stage="prefill"' in text and 'worker_id="ab"' in text
    # counts (_n fields) don't leak into the seconds family
    assert 'stage="queue_wait_n"' not in text


# ---------------- logging ----------------


def test_log_records_stamp_request_id():
    from dynamo_tpu.utils.logging import JsonlFormatter, PlainFormatter

    rec = logging.LogRecord("dynamo_tpu.t", logging.INFO, __file__, 1, "hello", (), None)
    ctx = new_context(request_id="log-rid", metadata={"trace_id": "log-tid"})
    with use_context(ctx):
        entry = json.loads(JsonlFormatter().format(rec))
        plain = PlainFormatter("%(message)s").format(rec)
    assert entry["request_id"] == "log-rid"
    assert entry["trace_id"] == "log-tid"
    assert "[rid=log-rid]" in plain
    # outside a request: no stamping
    entry = json.loads(JsonlFormatter().format(rec))
    assert "request_id" not in entry
    assert PlainFormatter("%(message)s").format(rec) == "hello"


# ---------------- cross-hop propagation (runtime, no JAX) ----------------


def test_trace_id_propagates_across_runtime_hop():
    """The edge-stamped trace id rides the RPC envelope: the server-side
    handler's spans (recorded inside the replayed context) land on the same
    trace as the caller's."""
    from dynamo_tpu.cplane.broker import Broker
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    tracing.enable()

    async def body():
        broker = Broker()
        port = await broker.start()
        addr = f"127.0.0.1:{port}"
        server_rt = DistributedRuntime(cplane_address=addr)
        await server_rt.connect()
        client_rt = DistributedRuntime(cplane_address=addr)
        await client_rt.connect()

        async def handler(req):
            with tracing.span("server.work"):
                yield {"ok": True}

        ep = server_rt.namespace("tr").component("c").endpoint("e")
        served = await ep.serve_endpoint(handler)
        client = await client_rt.client("tr", "c", "e")
        await client.wait_for_instances(timeout=10)
        try:
            ctx = new_context(request_id="hop-1", metadata={"trace_id": "trace-hop"})
            with use_context(ctx):
                stream = await client.random({"x": 1})
                items = [item async for item in stream]
            assert items == [{"ok": True}]
        finally:
            await served.stop()
            await client.stop()
            await client_rt._shutdown_hook()
            await server_rt._shutdown_hook()
            await broker.stop()

    asyncio.run(body())
    evs = tracing.events(trace_id="trace-hop")
    names = {e["name"] for e in evs}
    # caller-side hop span + server-side handler spans, one trace id
    assert "rpc.push.c.e" in names
    assert "rpc.handle.e" in names
    assert "server.work" in names
    assert all(e["args"]["request_id"] == "hop-1" for e in evs)


# ---------------- HTTP service (echo backend, no JAX) ----------------


def test_http_service_ttft_metrics_and_trace_endpoint():
    import aiohttp

    from dynamo_tpu.frontends.pipeline import build_pipeline, card_for_model
    from dynamo_tpu.llm.echo import EchoEngine
    from dynamo_tpu.llm.http.service import HttpService

    tracing.enable()

    async def body():
        service = HttpService(host="127.0.0.1", port=0)
        card = card_for_model("tiny")
        card.display_name = "echo"
        service.manager.add(build_pipeline(EchoEngine(), card))
        port = await service.start()
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as s:
                body = {
                    "model": "echo",
                    "messages": [{"role": "user", "content": "hello tracing"}],
                    "max_tokens": 8, "temperature": 0.0,
                    "ext": {"ignore_eos": True},
                }
                async with s.post(f"{base}/v1/chat/completions", json=body) as resp:
                    assert resp.status == 200
                    await resp.json()
                async with s.get(f"{base}/metrics") as resp:
                    metrics_text = await resp.text()
                async with s.get(f"{base}/trace") as resp:
                    trace_doc = await resp.json()
        finally:
            await service.stop()
        return metrics_text, trace_doc

    metrics_text, trace_doc = asyncio.run(body())
    assert check_exposition(metrics_text) == [], check_exposition(metrics_text)
    # TTFT histogram is non-empty after one served request
    assert 'llm_http_service_time_to_first_token_seconds_count{model="echo"} 1' in metrics_text
    # /trace serves a Perfetto-loadable document with the request's spans
    names = {e["name"] for e in trace_doc["traceEvents"]}
    assert "http.request" in names and "http.preprocess" in names
    tids = {e["args"]["trace_id"] for e in trace_doc["traceEvents"]}
    assert len(tids) == 1  # one request, one stitched trace


# ---------------- two-hop disagg trace (JAX, full matrix tier) ----------------


@pytest.mark.slow
def test_disagg_two_hop_trace_and_stage_histograms():
    """Satellite: a single request through the disaggregated prefill->decode
    path yields spans from BOTH workers under one trace id, and the decode
    engine's TTFT/queue-wait histograms are non-empty afterwards."""
    from dynamo_tpu.cplane.broker import Broker
    from dynamo_tpu.disagg.decode_worker import DisaggDecodeEngine
    from dynamo_tpu.disagg.prefill_worker import PrefillWorker
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.llm.disagg_router import DisaggregatedRouter, DisaggRouterConf
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    from tests.test_disagg import LONG_PROMPT, collect, req_for
    from tests.test_engine import tiny_engine_config

    tracing.enable()

    async def body():
        broker = Broker()
        port = await broker.start()
        addr = f"127.0.0.1:{port}"
        decode_rt = DistributedRuntime(cplane_address=addr)
        await decode_rt.connect()
        prefill_rt = DistributedRuntime(cplane_address=addr)
        await prefill_rt.connect()
        decode_inner = AsyncJaxEngine(tiny_engine_config())
        await decode_inner.start()
        prefill_engine = AsyncJaxEngine(tiny_engine_config())
        await prefill_engine.start()
        router = DisaggregatedRouter(
            "tiny", conf=DisaggRouterConf(max_local_prefill_length=6)
        )
        decode = DisaggDecodeEngine(
            decode_inner, decode_rt, "nst", "decoder", "tiny", disagg_router=router
        )
        await decode.start()
        prefill_worker = PrefillWorker(prefill_engine, prefill_rt, "nst", "tiny")
        await prefill_worker.start()
        try:
            # the edge stamp: what an HTTP frontend would put on the context
            ctx = new_context(request_id="d1", metadata={"trace_id": "trace-2hop"})
            with use_context(ctx):
                toks, _ = await collect(decode, req_for("d1", LONG_PROMPT))
            assert len(toks) == 6
            assert decode.remote_prefills == 1
            return decode_inner, prefill_engine
        finally:
            await prefill_worker.stop()
            await decode.shutdown()
            await prefill_engine.shutdown()
            await decode_rt._shutdown_hook()
            await prefill_rt._shutdown_hook()
            await broker.stop()

    decode_inner, prefill_engine = asyncio.run(body())

    evs = tracing.events(trace_id="trace-2hop")
    names = {e["name"] for e in evs}
    # decode-worker side of the hop
    assert "disagg.remote_prefill" in names
    # prefill-worker side: the queue message carried the trace id across
    assert "disagg.prefill" in names
    assert "disagg.kv_extract" in names
    # engine spans from the prefill worker's engine thread stitched too
    assert "engine.prefill" in names
    # both hops agree on the stitching keys
    by_name = {e["name"]: e["args"] for e in evs}
    assert by_name["disagg.prefill"]["request_id"] == "d1"
    assert by_name["disagg.remote_prefill"]["request_id"] == "d1"

    # stage histograms on the decode engine are non-empty after the request
    sched = decode_inner.scheduler
    assert sched.stage_hist["ttft"].count >= 1
    assert sched.stage_hist["queue_wait"].count >= 1
    assert sched.stage.ttft_n >= 1
    text = decode_inner.render_stage_metrics()
    assert check_exposition(text) == [], check_exposition(text)
    assert "dynamo_engine_ttft_seconds_bucket" in text
    snap = decode_inner.stage_snapshot()
    assert snap["queue_wait_n"] >= 1 and snap["decode_windows"] >= 1


# ---------------- post-PR-1 subsystem spans (tracing gap fix) ----------------
# Subsystems added after the tracing PR emitted no spans: draft-model
# speculation, LoRA slot loads, and the pressure-driven offload drain. These
# tests pin their spans so a future subsystem can't silently regress the
# per-request timeline again.


def test_lora_slot_load_span_and_anatomy():
    """A cold adapter's device-slot scatter emits lora.slot_load and records
    a lora_slot_load step-anatomy dispatch."""
    from types import SimpleNamespace

    from dynamo_tpu.lora.store import LoraStore
    from dynamo_tpu.utils.step_anatomy import StepAnatomy

    tracing.enable()
    cfg = SimpleNamespace(max_loras=2, lora_rank=2, lora_adapters=("a1",))
    store = LoraStore(cfg, SimpleNamespace(config=None),
                      scatter_fn=lambda slot, tree, scale: None)
    store.anatomy = StepAnatomy()
    store._host["a1"] = ({}, 1.0)  # host weights already cached
    slot = store.acquire("a1")
    assert slot is not None
    evs = [e for e in tracing.events() if e["name"] == "lora.slot_load"]
    assert len(evs) == 1
    assert evs[0]["args"]["adapter"] == "a1"
    assert evs[0]["args"]["slot"] == slot
    assert store.anatomy.dispatch_counts.get("lora_slot_load") == 1
    # a warm re-acquire pins the resident slot: no second scatter span
    store.release("a1")
    assert store.acquire("a1") == slot
    assert len([e for e in tracing.events() if e["name"] == "lora.slot_load"]) == 1


def test_offload_drain_span_and_anatomy():
    """The watermark-driven cold-block drain emits engine.offload.drain with
    the drained block count and records an offload_drain dispatch."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.scheduler import Scheduler

    tracing.enable()

    class _Alloc:  # page pool past the watermark with drainable cold blocks
        offload = object()

        def __init__(self):
            self.used_pages = 14
            self._reusable = [1, 2, 3]

        def drain_to_host(self, batch):
            self.used_pages -= 8
            return 3

    cfg = EngineConfig(model_id="tiny", page_size=4, num_pages=16, max_seqs=2,
                       prefill_buckets=(16,), offload_watermark=0.5)
    sched = Scheduler(cfg, None, _Alloc())
    sched._drain_cold_to_host()
    evs = [e for e in tracing.events() if e["name"] == "engine.offload.drain"]
    assert len(evs) == 1
    assert evs[0]["args"]["blocks"] == 3
    assert sched.offload_pressure_blocks == 3
    assert sched.anatomy.dispatch_counts.get("offload_drain") == 1
    # below the watermark: no span, no record
    tracing.clear()
    sched._drain_cold_to_host()
    assert tracing.events() == []


def test_spec_draft_span_emitted():
    """A draft-model engine's drafting dispatch emits engine.spec.draft
    (alongside the verify pass's engine.spec.verify) — the draft phase was
    invisible in traces before this."""
    import numpy as np

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import EngineRequest

    tracing.enable()

    async def body():
        eng = AsyncJaxEngine(EngineConfig(
            model_id="tiny", page_size=4, num_pages=128, max_seqs=2,
            max_model_len=96, prefill_buckets=(16, 32),
            speculative="draft:tiny:1",
        ))
        await eng.start()
        try:
            rng = np.random.default_rng(0)
            req = EngineRequest(
                request_id="sd-1", token_ids=rng.integers(1, 200, 12).tolist(),
                sampling=SamplingParams(temperature=0.0, max_tokens=6,
                                        ignore_eos=True),
            )
            async for _ in eng.generate(req):
                pass
            return eng.scheduler.anatomy.snapshot()
        finally:
            await eng.shutdown()

    snap = asyncio.run(body())
    names = {e["name"] for e in tracing.events()}
    assert "engine.spec.draft" in names
    assert "engine.spec.verify" in names
    # the step-anatomy plane saw the same dispatches
    assert snap["dispatches"].get("spec_draft", 0) >= 1
    assert snap["dispatches"].get("spec_verify", 0) >= 1
