"""The dedicated preprocessing executor bounds duplicate tokenizer loads.

HfTokenizer keeps one underlying tokenizer per THREAD (PyO3 "Already
borrowed"), so the number of AutoTokenizer.from_pretrained calls equals the
number of distinct threads preprocessing ever runs on. The HTTP service now
runs preprocessing on llm.tokenizer.preprocessing_executor() — a small fixed
pool — instead of the default executor's unbounded thread set (ADVICE r5)."""

import sys
import threading
import types
from concurrent.futures import wait

from dynamo_tpu.llm.tokenizer import HfTokenizer, preprocessing_executor


def test_preprocessing_executor_is_small_and_shared():
    pool = preprocessing_executor()
    assert pool is preprocessing_executor()  # one process-wide pool
    assert pool._max_workers <= 4

    names = set()
    barrier_done = threading.Event()

    def job(_):
        names.add(threading.current_thread().name)
        return 1

    futs = [pool.submit(job, i) for i in range(64)]
    wait(futs)
    barrier_done.set()
    assert len(names) <= 4
    assert all(n.startswith("dyntpu-preproc") for n in names)


def test_thread_local_tokenizer_loads_bounded_by_pool(monkeypatch):
    """Drive an HfTokenizer from the preprocessing pool with a stubbed
    transformers module and count from_pretrained calls: at most one per pool
    worker (+1 for the construction-time instance's thread)."""
    loads = []

    class _FakeTok:
        eos_token_id = 2

        def __len__(self):
            return 100

        def encode(self, text, add_special_tokens=False):
            return [1, 2, 3]

    class _AutoTokenizer:
        @staticmethod
        def from_pretrained(path):
            loads.append(threading.current_thread().name)
            return _FakeTok()

    fake = types.ModuleType("transformers")
    fake.AutoTokenizer = _AutoTokenizer
    monkeypatch.setitem(sys.modules, "transformers", fake)

    tok = HfTokenizer("/does/not/matter")
    pool = preprocessing_executor()
    futs = [pool.submit(tok.encode, "hello") for _ in range(64)]
    wait(futs)
    for f in futs:
        assert f.result() == [1, 2, 3]
    # construction thread + at most one load per pool worker
    assert len(loads) <= 1 + pool._max_workers
