"""KV router: radix indexer, cost scheduler, and the full routed path over the
broker (engine allocator events -> indexer -> schedule)."""

import asyncio

import pytest

from dynamo_tpu.engine.page_table import PageAllocator
from dynamo_tpu.llm.kv_events import KvCacheEvent, StoredBlock
from dynamo_tpu.llm.kv_router.indexer import KvIndexer, OverlapScores, RouterEvent
from dynamo_tpu.llm.kv_router.scheduler import (
    AllWorkersBusyError,
    KvScheduler,
    ProcessedEndpoints,
    WorkerLoad,
    select_worker,
)

BS = 4  # kv block size


@pytest.fixture(params=["python", "native"])
def make_indexer(request):
    if request.param == "native":
        from dynamo_tpu.llm.kv_router.native_indexer import native_available

        if not native_available():
            pytest.skip("native library not buildable")

    def make():
        return KvIndexer(BS, use_native=request.param == "native")

    return make


def stored(worker, indexer, parent, blocks):
    """blocks: list of (block_hash, tokens_hash)."""
    indexer.apply_event(
        RouterEvent(
            worker_id=worker,
            event=KvCacheEvent.stored(
                parent_hash=parent,
                blocks=[StoredBlock(block_hash=b, tokens_hash=t) for b, t in blocks],
            ),
        )
    )


def test_indexer_basic_match_and_removal(make_indexer):
    idx = make_indexer()
    # worker 1 caches blocks A->B; worker 2 caches A only
    stored(1, idx, None, [(100, 10), (101, 11)])
    stored(2, idx, None, [(200, 10)])

    scores = idx.find_matches([10, 11])
    assert scores.scores == {1: 2, 2: 1}
    scores = idx.find_matches([10, 99])
    assert scores.scores == {1: 1, 2: 1}
    scores = idx.find_matches([99])
    assert scores.scores == {}

    # removed event drops only that worker's claim
    idx.apply_event(RouterEvent(worker_id=1, event=KvCacheEvent.removed([100])))
    scores = idx.find_matches([10, 11])
    assert scores.scores == {2: 1, 1: 1}  # worker 1 still owns depth-2 block

    idx.remove_worker(2)
    assert idx.find_matches([10]).scores == {}


def test_indexer_parent_chaining_mid_tree(make_indexer):
    idx = make_indexer()
    stored(1, idx, None, [(100, 10)])
    # attach at depth 1 via parent block_hash
    stored(1, idx, 100, [(101, 11)])
    assert idx.find_matches([10, 11]).scores == {1: 2}
    # a different worker with same content hashes shares nodes
    stored(2, idx, None, [(300, 10)])
    stored(2, idx, 300, [(301, 11)])
    assert idx.find_matches([10, 11]).scores == {1: 2, 2: 2}


def test_indexer_from_allocator_events(make_indexer):
    """Engine-side PageAllocator events drive the router index end-to-end."""
    events = []
    alloc = PageAllocator(32, BS, event_sink=events.append)
    prompt = list(range(12))  # 3 full blocks
    alloc.allocate_sequence("s1", prompt)
    alloc.commit_prefilled("s1", 12)

    idx = make_indexer()
    for ev in events:
        idx.apply_event(RouterEvent(worker_id=7, event=ev))

    scores = idx.find_matches_for_request(prompt)
    assert scores.scores == {7: 3}
    # a longer prompt sharing 2 blocks
    scores = idx.find_matches_for_request(prompt[:8] + [99, 98, 97, 96])
    assert scores.scores == {7: 2}


def load(worker_id, active=0, total=10, kv_active=0, kv_total=100):
    return WorkerLoad(
        worker_id=worker_id,
        request_active_slots=active,
        request_total_slots=total,
        kv_active_blocks=kv_active,
        kv_total_blocks=kv_total,
    )


def test_scheduler_prefers_overlap():
    eps = ProcessedEndpoints.new([load(1), load(2)])
    overlap = OverlapScores(scores={2: 8})  # 8 blocks cached on worker 2
    picked = select_worker(eps, isl_tokens=64, overlap=overlap, kv_block_size=BS)
    assert picked == 2


def test_scheduler_balance_mode_avoids_loaded_worker():
    # worker 1 has the overlap but is heavily loaded; balance mode weighs load
    eps = ProcessedEndpoints.new(
        [load(1, kv_active=90), load(2, kv_active=5)]
    )
    overlap = OverlapScores(scores={1: 2})  # small overlap on the loaded one
    picked = select_worker(eps, isl_tokens=64, overlap=overlap, kv_block_size=BS)
    assert picked == 2


def test_scheduler_excludes_full_workers():
    eps = ProcessedEndpoints.new([load(1, active=10), load(2)])
    picked = select_worker(eps, 16, OverlapScores(scores={1: 4}), BS)
    assert picked == 2
    eps = ProcessedEndpoints.new([load(1, active=10), load(2, kv_active=100)])
    with pytest.raises(AllWorkersBusyError):
        select_worker(eps, 16, OverlapScores(), BS)


def test_scheduler_optimistic_bump():
    sched = KvScheduler(BS)
    sched.update_endpoints([load(1, total=2), load(2, total=2)])
    first = sched.schedule(16, OverlapScores(scores={1: 4}))
    assert first == 1
    # after two more schedules worker 1 fills up (bumped to 2 slots), so 2 wins
    sched.schedule(16, OverlapScores(scores={1: 4}))
    third = sched.schedule(16, OverlapScores(scores={1: 4}))
    assert third == 2


def test_kv_router_over_broker():
    from dynamo_tpu.cplane.broker import Broker
    from dynamo_tpu.llm.kv_router.publisher import KvEventPublisher, KvMetricsPublisher
    from dynamo_tpu.llm.kv_router.router import KvRouter
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    async def body():
        broker = Broker()
        port = await broker.start()
        worker = DistributedRuntime(cplane_address=f"127.0.0.1:{port}")
        await worker.connect()
        router_rt = DistributedRuntime(cplane_address=f"127.0.0.1:{port}")
        await router_rt.connect()
        try:
            wid = worker.primary_lease.lease_id

            # worker serves an endpoint exposing kv metrics via stats handler
            async def handler(req):
                yield {"ok": True}

            metrics = KvMetricsPublisher(
                lambda: {
                    "request_active_slots": 0,
                    "request_total_slots": 4,
                    "kv_active_blocks": 0,
                    "kv_total_blocks": 100,
                }
            )
            ep = worker.namespace("ns").component("backend").endpoint("generate")
            await ep.serve_endpoint(handler, metrics=metrics.stats_handler)

            router = KvRouter(router_rt, "ns", "backend", kv_block_size=BS)
            await router.start()

            # engine-side: allocator events flow through the publisher
            pub = KvEventPublisher(
                worker.cplane, "ns|backend.kv_events", wid, loop=asyncio.get_running_loop()
            )
            alloc = PageAllocator(32, BS, event_sink=lambda e: asyncio.ensure_future(
                pub.publish_async(e)
            ))
            prompt = list(range(16))
            alloc.allocate_sequence("s1", prompt)
            alloc.commit_prefilled("s1", 16)
            await asyncio.sleep(0.2)  # let events propagate

            assert router.indexer.find_matches_for_request(prompt).scores == {wid: 4}
            picked = await router.schedule(prompt)
            assert picked == wid
            assert router.prefix_hit_tokens(prompt, wid) == 16

            # worker death prunes the index
            await worker._shutdown_hook()
            for _ in range(100):
                if not router.indexer.find_matches_for_request(prompt).scores:
                    break
                await asyncio.sleep(0.02)
            assert router.indexer.find_matches_for_request(prompt).scores == {}
            await router.stop()
        finally:
            await router_rt._shutdown_hook()
            await broker.stop()

    asyncio.run(body())
