"""KV router: radix indexer, cost scheduler, and the full routed path over the
broker (engine allocator events -> indexer -> schedule), plus the bounded/
sharded index plane (LRU eviction, leak pruning, shard determinism, and the
eviction-truthful overlap memo)."""

import asyncio
import os
import subprocess
import sys

import pytest

from dynamo_tpu.engine.page_table import PageAllocator
from dynamo_tpu.llm.kv_events import KvCacheEvent, StoredBlock
from dynamo_tpu.llm.kv_router.indexer import KvIndexer, OverlapScores, RouterEvent
from dynamo_tpu.llm.kv_router.scheduler import (
    AllWorkersBusyError,
    KvScheduler,
    ProcessedEndpoints,
    WorkerLoad,
    select_worker,
)

BS = 4  # kv block size


@pytest.fixture(params=["python", "native"])
def make_indexer(request):
    if request.param == "native":
        from dynamo_tpu.llm.kv_router.native_indexer import native_available

        if not native_available():
            pytest.skip("native library not buildable")

    def make():
        return KvIndexer(BS, use_native=request.param == "native")

    return make


def stored(worker, indexer, parent, blocks):
    """blocks: list of (block_hash, tokens_hash)."""
    indexer.apply_event(
        RouterEvent(
            worker_id=worker,
            event=KvCacheEvent.stored(
                parent_hash=parent,
                blocks=[StoredBlock(block_hash=b, tokens_hash=t) for b, t in blocks],
            ),
        )
    )


def test_indexer_basic_match_and_removal(make_indexer):
    idx = make_indexer()
    # worker 1 caches blocks A->B; worker 2 caches A only
    stored(1, idx, None, [(100, 10), (101, 11)])
    stored(2, idx, None, [(200, 10)])

    scores = idx.find_matches([10, 11])
    assert scores.scores == {1: 2, 2: 1}
    scores = idx.find_matches([10, 99])
    assert scores.scores == {1: 1, 2: 1}
    scores = idx.find_matches([99])
    assert scores.scores == {}

    # removed event drops only that worker's claim
    idx.apply_event(RouterEvent(worker_id=1, event=KvCacheEvent.removed([100])))
    scores = idx.find_matches([10, 11])
    assert scores.scores == {2: 1, 1: 1}  # worker 1 still owns depth-2 block

    idx.remove_worker(2)
    assert idx.find_matches([10]).scores == {}


def test_indexer_parent_chaining_mid_tree(make_indexer):
    idx = make_indexer()
    stored(1, idx, None, [(100, 10)])
    # attach at depth 1 via parent block_hash
    stored(1, idx, 100, [(101, 11)])
    assert idx.find_matches([10, 11]).scores == {1: 2}
    # a different worker with same content hashes shares nodes
    stored(2, idx, None, [(300, 10)])
    stored(2, idx, 300, [(301, 11)])
    assert idx.find_matches([10, 11]).scores == {1: 2, 2: 2}


def test_indexer_from_allocator_events(make_indexer):
    """Engine-side PageAllocator events drive the router index end-to-end."""
    events = []
    alloc = PageAllocator(32, BS, event_sink=events.append)
    prompt = list(range(12))  # 3 full blocks
    alloc.allocate_sequence("s1", prompt)
    alloc.commit_prefilled("s1", 12)

    idx = make_indexer()
    for ev in events:
        idx.apply_event(RouterEvent(worker_id=7, event=ev))

    scores = idx.find_matches_for_request(prompt)
    assert scores.scores == {7: 3}
    # a longer prompt sharing 2 blocks
    scores = idx.find_matches_for_request(prompt[:8] + [99, 98, 97, 96])
    assert scores.scores == {7: 2}


def load(worker_id, active=0, total=10, kv_active=0, kv_total=100):
    return WorkerLoad(
        worker_id=worker_id,
        request_active_slots=active,
        request_total_slots=total,
        kv_active_blocks=kv_active,
        kv_total_blocks=kv_total,
    )


def test_scheduler_prefers_overlap():
    eps = ProcessedEndpoints.new([load(1), load(2)])
    overlap = OverlapScores(scores={2: 8})  # 8 blocks cached on worker 2
    picked = select_worker(eps, isl_tokens=64, overlap=overlap, kv_block_size=BS)
    assert picked == 2


def test_scheduler_balance_mode_avoids_loaded_worker():
    # worker 1 has the overlap but is heavily loaded; balance mode weighs load
    eps = ProcessedEndpoints.new(
        [load(1, kv_active=90), load(2, kv_active=5)]
    )
    overlap = OverlapScores(scores={1: 2})  # small overlap on the loaded one
    picked = select_worker(eps, isl_tokens=64, overlap=overlap, kv_block_size=BS)
    assert picked == 2


def test_scheduler_excludes_full_workers():
    eps = ProcessedEndpoints.new([load(1, active=10), load(2)])
    picked = select_worker(eps, 16, OverlapScores(scores={1: 4}), BS)
    assert picked == 2
    eps = ProcessedEndpoints.new([load(1, active=10), load(2, kv_active=100)])
    with pytest.raises(AllWorkersBusyError):
        select_worker(eps, 16, OverlapScores(), BS)


def test_scheduler_optimistic_bump():
    sched = KvScheduler(BS)
    sched.update_endpoints([load(1, total=2), load(2, total=2)])
    first = sched.schedule(16, OverlapScores(scores={1: 4}))
    assert first == 1
    # after two more schedules worker 1 fills up (bumped to 2 slots), so 2 wins
    sched.schedule(16, OverlapScores(scores={1: 4}))
    third = sched.schedule(16, OverlapScores(scores={1: 4}))
    assert third == 2


def test_kv_router_over_broker():
    from dynamo_tpu.cplane.broker import Broker
    from dynamo_tpu.llm.kv_router.publisher import KvEventPublisher, KvMetricsPublisher
    from dynamo_tpu.llm.kv_router.router import KvRouter
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    async def body():
        broker = Broker()
        port = await broker.start()
        worker = DistributedRuntime(cplane_address=f"127.0.0.1:{port}")
        await worker.connect()
        router_rt = DistributedRuntime(cplane_address=f"127.0.0.1:{port}")
        await router_rt.connect()
        try:
            wid = worker.primary_lease.lease_id

            # worker serves an endpoint exposing kv metrics via stats handler
            async def handler(req):
                yield {"ok": True}

            metrics = KvMetricsPublisher(
                lambda: {
                    "request_active_slots": 0,
                    "request_total_slots": 4,
                    "kv_active_blocks": 0,
                    "kv_total_blocks": 100,
                }
            )
            ep = worker.namespace("ns").component("backend").endpoint("generate")
            await ep.serve_endpoint(handler, metrics=metrics.stats_handler)

            router = KvRouter(router_rt, "ns", "backend", kv_block_size=BS)
            await router.start()

            # engine-side: allocator events flow through the publisher
            pub = KvEventPublisher(
                worker.cplane, "ns|backend.kv_events", wid, loop=asyncio.get_running_loop()
            )
            alloc = PageAllocator(32, BS, event_sink=lambda e: asyncio.ensure_future(
                pub.publish_async(e)
            ))
            prompt = list(range(16))
            alloc.allocate_sequence("s1", prompt)
            alloc.commit_prefilled("s1", 16)
            await asyncio.sleep(0.2)  # let events propagate

            assert router.indexer.find_matches_for_request(prompt).scores == {wid: 4}
            picked = await router.schedule(prompt)
            assert picked == wid
            assert router.prefix_hit_tokens(prompt, wid) == 16

            # worker death prunes the index
            await worker._shutdown_hook()
            for _ in range(100):
                if not router.indexer.find_matches_for_request(prompt).scores:
                    break
                await asyncio.sleep(0.02)
            assert router.indexer.find_matches_for_request(prompt).scores == {}
            await router.stop()
        finally:
            await router_rt._shutdown_hook()
            await broker.stop()

    asyncio.run(body())

# ---------------- bounded / sharded index plane ----------------


def py_indexer(**kw):
    return KvIndexer(BS, use_native=False, **kw)


def test_radix_removed_event_prunes_leaked_nodes():
    """Regression for the node leak: a full store -> remove cycle must leave
    the node count at baseline (the unbounded ancestor only discarded worker
    ids, so childless worker-less chains accumulated forever)."""
    idx = py_indexer()
    assert idx.radix_stats()["nodes"] == 0
    stored(1, idx, None, [(100, 10), (101, 11), (102, 12)])
    assert idx.radix_stats()["nodes"] == 3
    idx.apply_event(RouterEvent(worker_id=1, event=KvCacheEvent.removed([102, 101, 100])))
    s = idx.radix_stats()
    assert s["nodes"] == 0 and s["entries"] == 0
    # interior removal must NOT prune: a deeper block another claim still
    # owns has to stay reachable from the root
    stored(1, idx, None, [(100, 10), (101, 11)])
    idx.apply_event(RouterEvent(worker_id=1, event=KvCacheEvent.removed([100])))
    assert idx.find_matches([10, 11]).scores == {1: 1}
    assert idx.radix_stats()["nodes"] == 2
    # removing the deep block drains the whole chain
    idx.apply_event(RouterEvent(worker_id=1, event=KvCacheEvent.removed([101])))
    assert idx.radix_stats()["nodes"] == 0


def test_radix_remove_worker_prunes_unshared_chains():
    idx = py_indexer()
    stored(1, idx, None, [(100, 10), (101, 11)])
    stored(2, idx, None, [(200, 10)])  # shares the depth-1 node
    idx.remove_worker(1)
    s = idx.radix_stats()
    # the shared depth-1 node survives (worker 2 claims it); worker 1's
    # private depth-2 node is gone
    assert s["nodes"] == 1 and s["workers"] == 1
    assert idx.find_matches([10, 11]).scores == {2: 1}
    idx.remove_worker(2)
    s = idx.radix_stats()
    assert s["nodes"] == 0 and s["workers"] == 0 and s["entries"] == 0


def test_radix_bounded_lru_eviction_keeps_hot_prefix():
    idx = py_indexer(max_nodes=8)
    stored(1, idx, None, [(1000, 500), (1001, 501)])  # the hot chain
    for i in range(50):
        stored(1, idx, None, [(2000 + i, 9000 + i)])
        idx.find_matches([500, 501])  # keep the hot chain recently-hit
        assert idx.radix_stats()["nodes"] <= 8
    s = idx.radix_stats()
    assert s["evictions_total"] >= 40
    assert s["bytes"] > 0
    # the hot chain survived arbitrary churn; cold churn nodes were evicted
    assert idx.find_matches([500, 501]).scores == {1: 2}
    assert s["generation"] > 0


def test_radix_byte_cap_bounds_resident_bytes():
    idx = py_indexer(max_bytes=8 * 1024)
    for i in range(200):
        stored(1, idx, None, [(3000 + i, 7000 + i)])
    s = idx.radix_stats()
    assert s["bytes"] <= 8 * 1024
    assert s["evictions_total"] > 0


def test_stats_incremental_counters_match_recount():
    """stats() is O(1) off incremental counters; they must agree with a full
    recount of the lookup tables after a mixed store/remove/evict workload."""
    idx = py_indexer(max_nodes=64)
    for i in range(100):
        stored(1 + i % 3, idx, None, [(i * 10, 5000 + i), (i * 10 + 1, 6000 + i)])
        if i % 7 == 0:
            idx.apply_event(RouterEvent(
                worker_id=1 + i % 3, event=KvCacheEvent.removed([i * 10])))
    idx.remove_worker(2)
    entries, workers = idx.stats()
    recount_entries = sum(
        len(d) for t in idx.shards for d in t.lookup.values()
    )
    recount_workers = len({w for t in idx.shards for w in t.lookup})
    assert entries == recount_entries
    assert workers == recount_workers
    # node counter agrees with an actual tree walk too
    def count(node):
        return 1 + sum(count(c) for c in node.children.values())
    assert idx.radix_stats()["nodes"] == sum(count(t.root) - 1 for t in idx.shards)


@pytest.mark.parametrize("shards", [1, 3])
def test_sharded_indexer_matches_single_shard_semantics(shards):
    """The sharded facade must answer exactly like one tree: parent chaining
    lands in the owning shard, removed events fan out by owning shard, and
    remove_worker drops the worker everywhere."""
    idx = py_indexer(num_shards=shards)
    assert idx.radix_stats()["shards"] == shards
    stored(1, idx, None, [(100, 10)])
    stored(1, idx, 100, [(101, 11)])  # chained via parent block_hash
    stored(2, idx, None, [(300, 10)])
    stored(2, idx, 300, [(301, 11)])
    stored(3, idx, None, [(400, 77), (401, 78)])
    assert idx.find_matches([10, 11]).scores == {1: 2, 2: 2}
    assert idx.find_matches([77, 78]).scores == {3: 2}
    assert idx.stats() == (6, 3)
    idx.apply_event(RouterEvent(worker_id=3, event=KvCacheEvent.removed([401, 400])))
    assert idx.find_matches([77, 78]).scores == {}
    idx.remove_worker(1)
    assert idx.find_matches([10, 11]).scores == {2: 2}
    assert idx.stats() == (2, 1)


def test_shard_routing_is_deterministic_across_processes():
    """Same request -> same shard, in every process: the first-block hash is
    a seeded xxh3 of the token bytes, so shard routing needs no coordination
    between frontends (and must not depend on PYTHONHASHSEED)."""
    from dynamo_tpu.llm.kv_router.indexer import shard_index
    from dynamo_tpu.llm.tokens import compute_block_hash_for_seq

    prompt = list(range(32))
    local = shard_index(compute_block_hash_for_seq(prompt, BS)[0], 8)
    code = (
        "from dynamo_tpu.llm.tokens import compute_block_hash_for_seq\n"
        "from dynamo_tpu.llm.kv_router.indexer import shard_index\n"
        f"print(shard_index(compute_block_hash_for_seq(list(range(32)), {BS})[0], 8))\n"
    )
    for seed in ("0", "1"):
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "PYTHONHASHSEED": seed, "JAX_PLATFORMS": "cpu"},
        )
        assert out.returncode == 0, out.stderr
        assert int(out.stdout.strip()) == local


def _bare_router(**indexer_kw):
    """A KvRouter with no control plane: only the indexer/memo paths run."""
    from dynamo_tpu.llm.kv_router.router import KvRouter

    class _Drt:
        cplane = None

    router = KvRouter(_Drt(), "ns", "backend", kv_block_size=BS)
    router.indexer = KvIndexer(BS, use_native=False, **indexer_kw)
    return router


def test_overlap_memo_invalidated_by_eviction():
    """The one-entry overlap memo must never return a score for an evicted
    subtree — even when the eviction happened OUTSIDE _on_kv_event (direct
    indexer traffic bypasses the explicit invalidation sites; the generation
    key in _overlap_key is what catches it)."""
    from dynamo_tpu.llm.tokens import compute_block_hash_for_seq

    router = _bare_router(max_nodes=4)
    prompt = list(range(BS * 2))  # 2 blocks
    hashes = compute_block_hash_for_seq(prompt, BS)
    stored(1, router.indexer, None, [(900 + i, h) for i, h in enumerate(hashes)])
    ov1 = router._find_overlap(prompt)
    assert ov1.scores == {1: 2}
    assert router._find_overlap(prompt) is ov1  # memo reuse while unchanged
    # churn unrelated prefixes straight into the indexer until the prompt's
    # nodes evict (no KV event reaches the router, so only generation works)
    for i in range(10):
        stored(1, router.indexer, None, [(5000 + i, 8000 + i)])
    ov2 = router._find_overlap(prompt)
    assert ov2 is not ov1
    assert ov2.scores == {}


def test_overlap_memo_invalidated_by_direct_remove_worker():
    from dynamo_tpu.llm.tokens import compute_block_hash_for_seq

    router = _bare_router()
    prompt = list(range(BS * 2))
    hashes = compute_block_hash_for_seq(prompt, BS)
    stored(7, router.indexer, None, [(900 + i, h) for i, h in enumerate(hashes)])
    ov1 = router._find_overlap(prompt)
    assert ov1.scores == {7: 2}
    router.indexer.remove_worker(7)  # bypasses _watch_instances
    ov2 = router._find_overlap(prompt)
    assert ov2 is not ov1
    assert ov2.scores == {}
