"""Multi-tenant QoS (utils/qos.py): priority classes, admission control, and
overload protection.

Correctness bar: priority classes ride the wire end-to-end and change exactly
three scheduler decisions (admission order, fairness-cap weight, preemption
victim order); an exhausted tenant token budget or engine backpressure is
ALWAYS a structured retriable 429 + Retry-After before any SSE bytes (never a
drop mid-stream); Retry-After derives from the measured queue drain rate,
clamped to [1, 30] s; and the slow isolation replay proves a tenant-A burst
cannot blow tenant B's ITL-p99 budget with QoS on while the identical trace
with QoS off violates it.
"""

import asyncio
import time

import pytest

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.page_table import PageAllocator
from dynamo_tpu.engine.scheduler import EngineRequest, RunningSeq, Scheduler
from dynamo_tpu.utils.qos import (
    AdmissionController,
    DrainRateEstimator,
    QosPolicy,
    TokenBucket,
    parse_priority,
    priority_rank,
    priority_weight,
    retry_after_from_queue,
)


# ---------------- priority classes (fast) ----------------


def test_parse_priority_and_ordering():
    assert parse_priority(None) == "standard"
    assert parse_priority("") == "standard"
    assert parse_priority(" Critical ") == "critical"
    assert parse_priority("BATCH") == "batch"
    with pytest.raises(ValueError):
        parse_priority("urgent")
    # rank orders scheduling; unknown/empty ranks as standard (wire peers
    # predating the plane keep today's order)
    assert priority_rank("critical") < priority_rank("standard") < priority_rank("batch")
    assert priority_rank("") == priority_rank("standard") == priority_rank(None)
    assert priority_weight("critical") > priority_weight("standard") > priority_weight("batch")
    assert priority_weight("") == 1.0


def test_priority_rides_the_wire():
    from dynamo_tpu.llm.protocols.common import PreprocessedRequest

    pre = PreprocessedRequest(request_id="r1", token_ids=[1, 2], priority="batch")
    assert PreprocessedRequest.from_wire(pre.to_wire()).priority == "batch"
    # absent on the wire = standard-by-default downstream ("" sentinel)
    bare = PreprocessedRequest(request_id="r2", token_ids=[1])
    assert "priority" not in bare.to_wire()
    assert PreprocessedRequest.from_wire(bare.to_wire()).priority == ""

    from dynamo_tpu.disagg.migrate import SequenceManifest

    m = SequenceManifest(request_id="m1", prompt_tokens=[1, 2], generated=[5],
                         sampling={"max_tokens": 8}, priority="critical")
    m2 = SequenceManifest.from_wire(m.to_wire())
    assert m2.priority == "critical"
    assert m2.to_engine_request(now=10.0).priority == "critical"
    assert m.to_resume_request([7], now=10.0).priority == "critical"


# ---------------- token buckets (fast) ----------------


def test_token_bucket_arithmetic():
    clock = {"t": 0.0}
    b = TokenBucket(rate=10.0, burst=30.0, clock=lambda: clock["t"])
    # starts full; consumes down to empty
    assert b.fill_fraction() == pytest.approx(1.0)
    assert b.try_consume(20)
    assert b.try_consume(10)
    assert not b.try_consume(1)
    # refills at rate, capped at burst
    clock["t"] = 1.0
    assert b.fill_fraction() == pytest.approx(10.0 / 30.0)
    assert b.try_consume(10)
    clock["t"] = 100.0
    assert b.fill_fraction() == pytest.approx(1.0)
    # a request larger than the whole burst admits when FULL (drains to 0)
    # instead of deadlocking forever
    assert b.try_consume(10_000)
    assert not b.try_consume(1)
    # seconds_until prices the deficit at the refill rate
    assert b.seconds_until(20) == pytest.approx(2.0)
    clock["t"] = 101.0
    assert b.seconds_until(20) == pytest.approx(1.0)
    with pytest.raises(ValueError):
        TokenBucket(rate=0.0)


def test_retry_after_from_queue_clamps():
    assert retry_after_from_queue(100, 10.0) == 10
    assert retry_after_from_queue(1000, 1.0) == 30  # clamped high
    assert retry_after_from_queue(0, 10.0) == 1  # clamped low
    assert retry_after_from_queue(2, 10.0) == 1
    # no measured rate: the clamped default (never a fake instant retry)
    assert retry_after_from_queue(5, None) == 10
    assert retry_after_from_queue(5, 0.0) == 10


def test_drain_rate_estimator():
    clock = {"t": 0.0}
    est = DrainRateEstimator(window_s=60.0, clock=lambda: clock["t"])
    assert est.rate_rps() is None  # cold: no fake rate
    assert est.retry_after_s(50) == 10  # default, clamped
    for i in range(10):
        clock["t"] = float(i)
        est.note_finish()
    clock["t"] = 10.0
    assert est.rate_rps() == pytest.approx(1.0)
    assert est.retry_after_s(15) == 15
    assert est.retry_after_s(500) == 30
    # old samples age out of the window
    clock["t"] = 200.0
    assert est.rate_rps() is None


# ---------------- policy + admission controller (fast) ----------------


def test_qos_policy_specs():
    p = QosPolicy.from_specs(
        "tenant-a=500,tenant-b=4000:8000,*=1000",
        "tenant-a=batch,tenant-b=critical,adapter:a1=batch",
    )
    assert p.budgets["tenant-a"] == (500.0, None)
    assert p.budgets["tenant-b"] == (4000.0, 8000.0)
    assert p.default_budget == (1000.0, None)
    assert p.priority_for("tenant-a") == "batch"
    assert p.priority_for("tenant-b") == "critical"
    assert p.priority_for("unknown") == "standard"
    # adapter mapping wins when the tenant has no explicit class
    assert p.priority_for("unknown", adapter="a1") == "batch"
    with pytest.raises(ValueError):
        QosPolicy.from_specs("tenant-a", "")
    with pytest.raises(ValueError):
        QosPolicy.from_specs("", "tenant-a=urgent")


def test_qos_policy_from_env(monkeypatch):
    monkeypatch.delenv("DYNTPU_QOS_BUDGETS", raising=False)
    monkeypatch.delenv("DYNTPU_QOS_PRIORITIES", raising=False)
    assert QosPolicy.from_env() is None
    monkeypatch.setenv("DYNTPU_QOS_BUDGETS", "t1=100")
    monkeypatch.setenv("DYNTPU_QOS_SHED_WAIT_S", "3.5")
    p = QosPolicy.from_env()
    assert p.budgets["t1"] == (100.0, None)
    assert p.shed_wait_s == 3.5


def test_admission_controller_throttles_and_renders():
    from dynamo_tpu.utils.prometheus import DECLARED_METRIC_FAMILIES, check_exposition

    clock = {"t": 0.0}
    ctl = AdmissionController(
        QosPolicy.from_specs("t1=10:40", ""), clock=lambda: clock["t"]
    )
    d = ctl.admit("t1", "batch", 30)
    assert d.admitted and d.action == "admitted"
    d = ctl.admit("t1", "batch", 30)
    assert not d.admitted and d.action == "throttled"
    assert 1 <= d.retry_after_s <= 30
    assert d.retry_after_s == 2  # deficit 20 tokens at 10/s
    # unbudgeted tenants (no "*" default) never throttle
    assert ctl.admit("other", "standard", 10 ** 6).admitted
    ctl.record_shed("t1", "batch")
    snap = ctl.snapshot()
    assert snap["classes"]["batch"]["t1"] == {
        "admitted": 1, "throttled": 1, "shed": 1,
    }
    assert 0.0 <= snap["budget_fill"]["t1"] <= 1.0
    text = ctl.render_metrics()
    assert check_exposition(text) == []
    assert "dynamo_qos_requests_total" in DECLARED_METRIC_FAMILIES
    assert 'dynamo_qos_requests_total{action="throttled",class="batch",tenant="t1"} 1' in text
    assert "dynamo_qos_budget_fill" in text


# ---------------- admission fault knob (fast) ----------------


def test_admission_fault_plan_parsing_and_determinism(monkeypatch):
    from dynamo_tpu.disagg import faults

    with pytest.raises(ValueError):
        faults.AdmissionFaultPlan("blackhole:1")
    with pytest.raises(ValueError):
        faults.AdmissionFaultPlan("reject-rate")  # arg required
    plan = faults.AdmissionFaultPlan("reject-rate:0.5,delay-ms:20", seed=7)
    assert plan.delay_s() == pytest.approx(0.02)
    seq = [plan.should_reject() for _ in range(32)]
    assert any(seq) and not all(seq)
    # same (spec, seed) -> identical reject sequence: replayable chaos
    again = faults.AdmissionFaultPlan("reject-rate:0.5,delay-ms:20", seed=7)
    assert [again.should_reject() for _ in range(32)] == seq
    assert faults.AdmissionFaultPlan("reject-rate:1.0").should_reject()
    assert not faults.AdmissionFaultPlan("delay-ms:5").should_reject()

    monkeypatch.delenv(faults.ENV_ADMISSION, raising=False)
    assert faults.admission_plan() is None
    monkeypatch.setenv(faults.ENV_ADMISSION, "reject-rate:1.0")
    assert faults.admission_plan().should_reject()


# ---------------- scheduler: priority order / weights / victims ----------------


class _StubRunner:
    packed_prefill_mode = False
    lora_store = None

    def write_token_slots(self, slots, tokens):  # pragma: no cover
        pass

    def set_slot_lora(self, slot, lora_slot):  # pragma: no cover
        pass


def _scheduler(qos=True, max_seqs=4, cap=2, **over):
    cfg = EngineConfig(
        model_id="tiny", page_size=4, num_pages=64, max_seqs=max_seqs,
        max_model_len=64, prefill_batches_per_step=cap, qos=qos,
        qos_preempt_wait_ms=0.0, **over,
    )
    alloc = PageAllocator(cfg.num_pages, cfg.page_size)
    return Scheduler(cfg, _StubRunner(), alloc)


def _fake_start(sched, started):
    def start(req, slot, lora_slot=0):
        sched.slots[slot] = RunningSeq(
            req=req, slot=slot, prompt_len=len(req.token_ids), cached_len=0,
            prefill_pos=None, admitted_order=sched._admit_counter,
        )
        sched._admit_counter += 1
        started.append(req.request_id)

    return start


def _running(sched, rid, slot, priority="standard", generated=(9,)):
    """A running decode sequence with REAL allocator state (preemption walks
    free_sequence)."""
    _, state = sched.allocator.allocate_sequence(rid, [1, 2, 3, 4])
    sched.allocator.commit_prefilled(rid, 4)
    seq = RunningSeq(
        req=EngineRequest(rid, [1, 2, 3, 4], priority=priority,
                          tenant=f"tn-{rid}"),
        slot=slot, prompt_len=4, cached_len=0, prefill_pos=None,
        generated=list(generated), admitted_order=sched._admit_counter,
    )
    sched._admit_counter += 1
    sched.slots[slot] = seq
    return seq


def test_priority_admission_order(monkeypatch):
    sched = _scheduler()
    started = []
    monkeypatch.setattr(sched, "_start_sequence", _fake_start(sched, started))
    sched.add_request(EngineRequest("b", [1] * 4, priority="batch"))
    sched.add_request(EngineRequest("s", [1] * 4))  # standard by default
    sched.add_request(EngineRequest("c", [1] * 4, priority="critical"))
    sched._admit()
    assert started == ["c", "s", "b"]  # class order, not arrival order

    # QoS off: plain FIFO (the pre-QoS contract, and the bench's off arm)
    sched_off = _scheduler(qos=False)
    started_off = []
    monkeypatch.setattr(
        sched_off, "_start_sequence", _fake_start(sched_off, started_off)
    )
    sched_off.add_request(EngineRequest("b", [1] * 4, priority="batch"))
    sched_off.add_request(EngineRequest("s", [1] * 4))
    sched_off.add_request(EngineRequest("c", [1] * 4, priority="critical"))
    sched_off._admit()
    assert started_off == ["b", "s", "c"]


def test_priority_weights_compose_with_fairness_cap(monkeypatch):
    # cap = 1 with a running decode slot: standard admits exactly one start
    # per step (the pre-QoS contract), critical's 2.0 weight admits two
    # (each consumes 0.5 cap units), batch's 0.5 weight still admits its
    # first (the cap check runs before the start) but saturates the step
    for classes, expect in (
        (["standard", "standard"], 1),
        (["critical", "critical", "critical"], 2),
        (["batch", "batch"], 1),
    ):
        sched = _scheduler(cap=1)
        _running(sched, "dec", 0)
        started = []
        monkeypatch.setattr(sched, "_start_sequence", _fake_start(sched, started))
        for i, cls in enumerate(classes):
            sched.add_request(EngineRequest(f"r{i}", [1] * 4, priority=cls))
        sched._admit()
        assert len(started) == expect, (classes, started)


def test_priority_victim_ordering():
    sched = _scheduler()
    crit = _running(sched, "crit", 0, priority="critical")
    std = _running(sched, "std", 1, priority="standard")
    batch_old = _running(sched, "b-old", 2, priority="batch")
    batch_new = _running(sched, "b-new", 3, priority="batch")
    # batch first (most recent within the class), critical only as a last
    # resort — regardless of admission recency
    assert sched._pick_victim(exclude=crit) is batch_new
    sched.slots[3] = None
    assert sched._pick_victim(exclude=crit) is batch_old
    sched.slots[2] = None
    assert sched._pick_victim(exclude=crit) is std
    sched.slots[1] = None
    assert sched._pick_victim(exclude=crit) is None

    # QoS off: pure recency (the pre-QoS contract)
    sched_off = _scheduler(qos=False)
    crit2 = _running(sched_off, "crit", 0, priority="critical", generated=(9,))
    _running(sched_off, "b", 1, priority="batch")
    newest = _running(sched_off, "new-crit", 2, priority="critical")
    assert sched_off._pick_victim(exclude=crit2) is newest


def test_preempt_carries_qos_tags():
    sched = _scheduler()
    seq = _running(sched, "v1", 0, priority="batch")
    sched._preempt(seq)
    requeued = sched.waiting[0]
    assert requeued.priority == "batch"
    assert requeued.tenant == "tn-v1"
    assert sched.qos_preempted == {"batch": 1}


def test_critical_shed_prefers_migration_then_preempts(monkeypatch):
    # all slots held by batch lanes; a waiting critical request must evict
    # one — via the migration hook when it accepts, else preempt+requeue
    sched = _scheduler(max_seqs=2)
    _running(sched, "b1", 0, priority="batch")
    _running(sched, "b2", 1, priority="batch")
    started = []
    monkeypatch.setattr(sched, "_start_sequence", _fake_start(sched, started))
    crit = EngineRequest("crit", [1] * 4, priority="critical",
                         enqueue_ts=time.monotonic() - 1.0)

    # migration hook accepts: NO local preempt, slot frees asynchronously —
    # the critical request keeps waiting this step
    shed_requests = []
    sched.migrate_shed = lambda rid: shed_requests.append(rid) or True
    sched.add_request(crit)
    sched._admit()
    assert shed_requests == ["b2"]  # most recent batch lane
    assert started == []
    assert sched.qos_sheds == 1 and sched.qos_shed_migrations == 1
    assert sched.preempt_count == 0

    # hook gone (no peer): preempt+requeue frees the slot NOW and the
    # critical request admits in the same step
    sched.migrate_shed = None
    sched._admit()
    assert started == ["crit"]
    assert sched.preempt_count == 1
    assert sched.qos_preempted.get("batch") == 1
    assert [r.request_id for r in sched.waiting] == ["b2"]
    assert sched.waiting[0].priority == "batch"

    # never critical-for-critical: a second critical waits instead of
    # evicting the first
    started.clear()
    sched2 = _scheduler(max_seqs=1)
    _running(sched2, "c1", 0, priority="critical")
    sched2.add_request(EngineRequest(
        "c2", [1] * 4, priority="critical",
        enqueue_ts=time.monotonic() - 1.0,
    ))
    sched2._admit()
    assert sched2.qos_sheds == 0 and sched2.preempt_count == 0
    assert [r.request_id for r in sched2.waiting] == ["c2"]


# ---------------- frontend: 429 before SSE (fast, real sockets) ----------------


def _echo_service(qos=None):
    from dynamo_tpu.frontends.pipeline import build_pipeline, card_for_model
    from dynamo_tpu.llm.echo import EchoEngine
    from dynamo_tpu.llm.http.service import HttpService

    service = HttpService(host="127.0.0.1", port=0, qos=qos)
    card = card_for_model("tiny")
    engine = EchoEngine()
    service.manager.add(build_pipeline(engine, card))
    return service, engine


CHAT_BODY = {
    "model": "tiny",
    "messages": [{"role": "user", "content": "hello"}],
    "max_tokens": 64,
    "temperature": 0,
}


def test_429_budget_exhausted_before_sse_unary_and_stream():
    """An exhausted tenant token budget answers a structured retriable 429 +
    Retry-After on BOTH unary and stream paths — the stream path gets plain
    JSON, never SSE bytes."""
    import aiohttp

    async def body():
        # burst 80 tokens at 1 token/s: the first request (prompt +
        # max_tokens 64) drains it; the second must throttle
        qos = AdmissionController(QosPolicy.from_specs("t1=1:80", ""))
        service, _ = _echo_service(qos=qos)
        port = await service.start()
        url = f"http://127.0.0.1:{port}/v1/chat/completions"
        hdrs = {"x-tenant": "t1"}
        try:
            async with aiohttp.ClientSession() as s:
                async with s.post(url, json=CHAT_BODY, headers=hdrs) as r:
                    assert r.status == 200
                async with s.post(url, json=CHAT_BODY, headers=hdrs) as r:
                    assert r.status == 429
                    ra = int(r.headers["Retry-After"])
                    assert 1 <= ra <= 30
                    doc = await r.json()
                    assert doc["error"]["code"] == "rate_limited"
                async with s.post(
                    url, json={**CHAT_BODY, "stream": True}, headers=hdrs
                ) as r:
                    assert r.status == 429
                    assert r.content_type == "application/json"
                    raw = await r.read()
                    assert not raw.startswith(b"data:")
                    import json as _json

                    assert _json.loads(raw)["error"]["code"] == "rate_limited"
            snap = qos.snapshot()
            assert snap["classes"]["standard"]["t1"]["throttled"] == 2
        finally:
            await service.stop()

    asyncio.run(body())


def test_backpressure_sheds_batch_class_first():
    """Engine backpressure (queue depth x drain rate past the TTFT budget)
    sheds batch-class requests with a retriable 429 whose Retry-After comes
    from the measured drain rate; standard/critical requests still serve."""
    import aiohttp

    async def body():
        service, engine = _echo_service()
        # duck-typed engine backpressure surface (what AsyncJaxEngine
        # exposes): 40 queued at 0.5 rps -> est 80 s wait, retry in 30 s
        engine.backpressure_snapshot = lambda: {
            "queue_depth": 40, "drain_rps": 0.5, "est_wait_s": 80.0,
            "retry_after_s": 30,
        }
        port = await service.start()
        url = f"http://127.0.0.1:{port}/v1/chat/completions"
        try:
            async with aiohttp.ClientSession() as s:
                async with s.post(
                    url, json=CHAT_BODY, headers={"x-priority": "batch"}
                ) as r:
                    assert r.status == 429
                    assert r.headers["Retry-After"] == "30"
                    assert (await r.json())["error"]["code"] == "overloaded"
                async with s.post(url, json=CHAT_BODY) as r:  # standard
                    assert r.status == 200
                async with s.post(
                    url, json=CHAT_BODY, headers={"x-priority": "critical"}
                ) as r:
                    assert r.status == 200
                # unknown class: structured 400, not a silent downgrade
                async with s.post(
                    url, json=CHAT_BODY, headers={"x-priority": "urgent"}
                ) as r:
                    assert r.status == 400
                    assert (await r.json())["error"]["code"] == "invalid_priority"
        finally:
            await service.stop()

    asyncio.run(body())


def test_admission_fault_knob_rejects_deterministically(monkeypatch):
    """DYNTPU_FAULT_ADMISSION=reject-rate:1.0 turns every admission into the
    structured retriable 429 — the client-backoff test hook."""
    import aiohttp

    monkeypatch.setenv("DYNTPU_FAULT_ADMISSION", "reject-rate:1.0")

    async def body():
        service, _ = _echo_service()
        port = await service.start()
        url = f"http://127.0.0.1:{port}/v1/chat/completions"
        try:
            async with aiohttp.ClientSession() as s:
                for _ in range(3):
                    async with s.post(url, json=CHAT_BODY) as r:
                        assert r.status == 429
                        assert "Retry-After" in r.headers
                        assert (await r.json())["error"]["code"] == "rate_limited"
        finally:
            await service.stop()

    asyncio.run(body())


def test_drain_503_retry_after_uses_measured_rate():
    """The draining-503 path shares the drain-rate estimator with the 429
    path instead of sending a constant."""
    from dynamo_tpu.llm.backend import Backend
    from dynamo_tpu.utils.health import HealthMonitor

    class _Cfg:
        migration = False

    class _Eng:
        health = HealthMonitor("t")
        config = _Cfg()

        def backpressure_snapshot(self):
            return {"queue_depth": 34, "drain_rps": 2.0, "est_wait_s": 17.0,
                    "retry_after_s": 17}

    b = Backend(_Eng(), tokenizer=None)
    _Eng.health.set_state("draining", "drain")
    a = b.availability()
    assert not a["servable"] and a["retry_after_s"] == 17
    assert b.backpressure()["est_wait_s"] == 17.0


# ---------------- planner executes rebalance decisions (fast) ----------------


def test_planner_executes_rebalance_with_cooldown():
    from types import SimpleNamespace

    from aiohttp import web

    from dynamo_tpu.components.planner import PlannerService, RebalanceDecision

    class _Drt:
        cplane = None

    async def body():
        drains = []

        async def _drain(request):
            drains.append(await request.json())
            return web.json_response({"migrated": 2, "migration": "done"})

        app = web.Application()
        app.router.add_post("/admin/drain", _drain)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]

        svc = PlannerService(_Drt(), "ns", execute_cooldown_s=120.0)
        view = SimpleNamespace(
            instance_id=0xAB,
            data={"admin": {"address": f"127.0.0.1:{port}"}},
        )
        svc.aggregator.worker_views = lambda: [view]
        decision = RebalanceDecision(source="ab", target="cd", reason="hot")
        try:
            await svc._execute(decision)
            assert drains == [{"target": "cd"}]
            assert svc.rebalance_executed == 1
            # cooldown: a republished decision does not re-drain
            await svc._execute(decision)
            assert len(drains) == 1 and svc.rebalance_executed == 1

            # a source with no admin surface is skipped (stays published for
            # an operator), not an error
            svc2 = PlannerService(_Drt(), "ns")
            svc2.aggregator.worker_views = lambda: [
                SimpleNamespace(instance_id=0xAB, data={})
            ]
            await svc2._execute(decision)
            assert svc2.rebalance_executed == 0
            assert svc2.rebalance_execute_failures == 0

            # a failing drain counts as an execute failure (and respects its
            # own attempt cooldown)
            svc3 = PlannerService(_Drt(), "ns")
            svc3.aggregator.worker_views = lambda: [SimpleNamespace(
                instance_id=0xAB, data={"admin": {"address": "127.0.0.1:1"}},
            )]
            await svc3._execute(decision)
            assert svc3.rebalance_execute_failures == 1
        finally:
            await runner.cleanup()

        from dynamo_tpu.utils.prometheus import check_exposition

        text = svc.render_metrics()
        assert check_exposition(text) == []
        assert 'dynamo_planner_rebalance_executed_total{result="ok"} 1' in text

    asyncio.run(body())


# ---------------- surfaces: metrics + dynotop (fast) ----------------


def test_qos_metric_families_and_resource_snapshot():
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.utils.prometheus import check_exposition

    cfg = EngineConfig(model_id="tiny", page_size=4, num_pages=8, max_seqs=2,
                       prefill_buckets=(16,))
    eng = AsyncJaxEngine(cfg)
    eng.allocator = PageAllocator(cfg.num_pages, cfg.page_size)
    eng.scheduler = Scheduler(cfg, None, eng.allocator)
    eng.runner = None
    eng.scheduler.qos_preempted = {"batch": 4, "standard": 1}
    eng.scheduler.qos_sheds = 3
    eng.scheduler.qos_shed_migrations = 2
    text = eng.render_stage_metrics()
    assert check_exposition(text) == []
    assert 'dynamo_qos_preemptions_total{class="batch",result="preempted"} 4' in text
    assert 'dynamo_qos_preemptions_total{class="any",result="migrated"} 2' in text
    snap = eng.resource_snapshot()
    assert snap["qos"]["enabled"] is True
    assert snap["qos"]["preempted"] == {"batch": 4, "standard": 1}
    assert snap["qos"]["sheds"] == 3


def test_dynotop_qos_column():
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "dynotop", Path(__file__).resolve().parent.parent / "tools" / "dynotop.py"
    )
    dynotop = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(dynotop)

    doc = {
        "namespace": "ns", "component": "backend", "summary": {"workers": 1},
        "workers": [{
            "worker_id": "ab", "last_seen_s": 0.1, "missed_scrapes": 0,
            "health": {"state": "ready", "heartbeat_age_s": 0.01},
            "kv_metrics": {"request_active_slots": 1, "request_total_slots": 4,
                           "kv_active_blocks": 1, "kv_total_blocks": 10},
            "resources": {"qos": {
                "enabled": True,
                "running": {"critical": 1, "batch": 2},
                "preempted": {"batch": 3}, "sheds": 3,
            }},
        }],
    }
    text = dynotop.render_status(doc)
    assert "QOS" in text
    assert "1c/0s/2b!3" in text
    doc["workers"][0]["resources"] = {}
    assert "1c/0s/2b" not in dynotop.render_status(doc)  # pre-plane: "-"


# ---------------- shed-via-migration e2e (slow) ----------------


@pytest.mark.slow
def test_critical_shed_migrates_batch_lane_to_peer():
    """End-to-end graceful shed: a critical request arrives at a full engine
    whose lanes are batch-class; the shed hook hands the most recent batch
    lane to a peer via live migration — the critical request admits on the
    source, and the shed batch request finishes TOKEN-IDENTICALLY through
    the relayed stream (it survives, it does not rejoin the queue)."""
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.sampling import SamplingParams

    from tests.test_engine import tiny_engine_config
    from tests.test_migration import _wire_pair

    PROMPT = [5, 9, 2, 77, 31, 8, 100, 42, 17, 3, 60, 61]

    def _req(rid, n, priority):
        return EngineRequest(
            request_id=rid, token_ids=list(PROMPT),
            sampling=SamplingParams(temperature=0.0, max_tokens=n,
                                    ignore_eos=True),
            priority=priority,
        )

    async def collect(eng, req):
        toks = []
        async for out in eng.generate(req):
            if out.token is not None:
                toks.append(out.token)
        return toks

    async def wait_generated(eng, rid, n, timeout=60.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            seq = next((s for s in eng.scheduler.slots
                        if s is not None and s.req.request_id == rid), None)
            if seq is not None and not seq.finished and len(seq.generated) >= n:
                return True
            await asyncio.sleep(0.005)
        return False

    async def body():
        cfg = dict(decode_steps=2, pipeline_depth=1, num_pages=96, max_seqs=2,
                   qos_preempt_wait_ms=20.0)
        src = AsyncJaxEngine(tiny_engine_config(**cfg))
        dst = AsyncJaxEngine(tiny_engine_config(**cfg))
        await src.start()
        await dst.start()
        srv = await _wire_pair(src, dst)
        loop = asyncio.get_running_loop()
        src.scheduler.migrate_shed = lambda rid: bool(
            asyncio.run_coroutine_threadsafe(
                src.migrate_out(rid, dst.adopt_migrated), loop
            )
        )
        try:
            t_b1 = asyncio.ensure_future(collect(src, _req("b1", 48, "batch")))
            assert await wait_generated(src, "b1", 2)
            t_b2 = asyncio.ensure_future(collect(src, _req("b2", 48, "batch")))
            assert await wait_generated(src, "b2", 2)
            t_crit = asyncio.ensure_future(collect(src, _req("crit", 8, "critical")))
            crit_toks = await asyncio.wait_for(t_crit, 90.0)
            b1_toks = await asyncio.wait_for(t_b1, 90.0)
            b2_toks = await asyncio.wait_for(t_b2, 90.0)
            assert len(crit_toks) == 8
            # the shed went via migration, and the victim was the MOST
            # RECENT batch lane
            assert src.scheduler.qos_shed_migrations >= 1
            assert src.scheduler.migration_out >= 1
            assert dst.scheduler.migration_in >= 1
            # token-identical survival: b1 (never migrated) and b2 (migrated
            # mid-decode) share the prompt — greedy decode must agree
            assert b2_toks == b1_toks
            # critical was never a victim
            assert src.scheduler.qos_preempted.get("critical", 0) == 0
        finally:
            await srv.stop()
            await src.shutdown()
            await dst.shutdown()

    asyncio.run(body())


# ---------------- the isolation experiment (slow) ----------------


@pytest.mark.slow
def test_multi_tenant_isolation_replay():
    """Tenant A bursts batch-class long-output traffic through ONE engine
    while tenant B streams steadily at critical class. With QoS on (priority
    victims + the token-budget shed), B's per-request ITL-p99 stays within
    budget and B is NEVER a preemption victim; the identical trace with QoS
    off lets A's page-pressure churn preempt B mid-stream past the budget."""
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.loadgen.replay import replay_engine
    from dynamo_tpu.loadgen.scenarios import load_scenario
    from dynamo_tpu.loadgen.trace import compile_trace

    itl_budget_ms = 250.0
    eng_kw = dict(
        model_id="tiny", page_size=4, num_pages=64, max_seqs=3,
        max_model_len=256, prefill_buckets=(16, 32, 64), decode_steps=2,
        pipeline_depth=1, prefill_batches_per_step=1,
        qos_preempt_wait_ms=50.0,
    )
    spec_a = load_scenario("bursty_chat", seed=5, num_requests=10).replace(
        name="qos_a", tenants=("tenant-a",), isl_mean=32, isl_max=64,
        osl_dist="fixed", osl_mean=96, osl_max=96, vocab=256, rate_rps=24.0,
        burst_factor=6.0, slo_ttft_ms=30000.0, slo_itl_ms=itl_budget_ms,
    )
    spec_b = load_scenario("bursty_chat", seed=6, num_requests=5).replace(
        name="qos_b", arrival="poisson", tenants=("tenant-b",), isl_mean=12,
        isl_max=24, osl_dist="fixed", osl_mean=48, osl_max=48, vocab=256,
        rate_rps=0.8, slo_ttft_ms=30000.0, slo_itl_ms=itl_budget_ms,
    )
    merged = sorted(
        compile_trace(spec_a) + compile_trace(spec_b), key=lambda tr: tr.at_s
    )

    # the frontend bucket decision replayed at trace timestamps (the 429
    # path's semantics, deterministic): most of A's burst sheds
    clock = {"t": 0.0}
    ctl = AdmissionController(
        QosPolicy.from_specs("tenant-a=20:300", ""), clock=lambda: clock["t"]
    )
    admitted, shed = [], 0
    for tr in merged:
        clock["t"] = tr.at_s
        if tr.tenant == "tenant-a":
            if not ctl.admit("tenant-a", "batch",
                             len(tr.token_ids) + tr.max_tokens).admitted:
                shed += 1
                continue
        admitted.append(tr)
    assert shed > 0

    def stamp(req, tr):
        req.priority = "critical" if tr.tenant == "tenant-b" else "batch"

    def b_itl_p99(report):
        vals = [
            o["itl_p99_ms"] for o in report["outcomes"]
            if o.get("tenant") == "tenant-b" and o.get("itl_p99_ms") is not None
        ]
        assert vals
        return max(vals)

    async def arm(qos_on, trace, hook):
        eng = AsyncJaxEngine(EngineConfig(qos=qos_on, **eng_kw))
        await eng.start()
        try:
            for wspec in (spec_a.replace(seed=98, num_requests=3),
                          spec_b.replace(seed=99, num_requests=3)):
                await replay_engine(eng, compile_trace(wspec), spec=wspec,
                                    speed=100.0)
            eng.scheduler.qos_preempted.clear()
            report = await replay_engine(eng, trace, spec=spec_b, speed=2.0,
                                         request_hook=hook)
            return report, dict(eng.scheduler.qos_preempted)
        finally:
            await eng.shutdown()

    async def body():
        rep_on, preempted_on = await arm(True, admitted, stamp)
        rep_off, _ = await arm(False, merged, None)
        errors_b = [
            o for o in rep_on["outcomes"]
            if o.get("tenant") == "tenant-b" and o.get("error")
        ]
        assert not errors_b
        # enforcement: B (critical) never a victim with QoS on
        assert preempted_on.get("critical", 0) == 0, preempted_on
        on, off = b_itl_p99(rep_on), b_itl_p99(rep_off)
        assert on <= itl_budget_ms, (on, itl_budget_ms)
        assert off > itl_budget_ms, (off, itl_budget_ms)

    asyncio.run(body())


# ---------------- fleet-shared admission (r17) ----------------


def test_fleet_replica_budget_split(monkeypatch):
    """fleet_replicas=N splits every tenant budget deterministically: two
    replica controllers at N=2 jointly admit the SAME token volume one
    shared controller would, while two naive N=1 controllers leak 2x — the
    multi-frontend hole this knob closes. Refill splits by the same
    arithmetic, and the admitted-token audit trail rides the snapshot."""

    def mk(n):
        clock = {"t": 0.0}
        ctl = AdmissionController(
            QosPolicy.from_specs("t=10:100", "", fleet_replicas=n),
            clock=lambda: clock["t"],
        )
        return ctl, clock

    def drain(ctl):
        admitted = 0
        while ctl.admit("t", "batch", 5).admitted:
            admitted += 5
            assert admitted <= 10_000  # runaway guard
        return admitted

    shared, _ = mk(1)
    assert drain(shared) == 100

    split = [mk(2) for _ in range(2)]
    assert sum(drain(c) for c, _ in split) == 100  # no fleet-wide leakage
    assert sum(drain(mk(1)[0]) for _ in range(2)) == 200  # the naive leak

    # refill splits too: 5s at 10 tok/s = 50 fleet-wide, 25 per replica
    for _, clock in split:
        clock["t"] = 5.0
    assert sum(drain(c) for c, _ in split) == 50

    snap = split[0][0].snapshot()
    assert snap["fleet_replicas"] == 2
    assert snap["admitted_tokens"]["t"] == pytest.approx(75.0)

    # env + validation surfaces
    monkeypatch.setenv("DYNTPU_QOS_BUDGETS", "t=10:100")
    monkeypatch.setenv("DYNTPU_QOS_FLEET_REPLICAS", "4")
    p = QosPolicy.from_env()
    assert p is not None and p.fleet_replicas == 4
    with pytest.raises(ValueError):
        QosPolicy.from_specs("t=10:100", "", fleet_replicas=0)


@pytest.mark.slow
def test_fleet_shared_admission_two_frontends():
    """TWO HTTP front doors over ONE engine, each holding HALF the tenant-a
    token budget (fleet_replicas=2). A merged bursty trace round-robined
    across both doors (replay_http multi-URL) sheds tenant-a down to ONE
    fleet-wide budget envelope — no 2x leakage from running two replicas —
    while tenant-b (critical, unbudgeted) streams inside its ITL budget."""
    import aiohttp

    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.frontends.pipeline import build_pipeline, card_for_model
    from dynamo_tpu.llm.http.service import HttpService
    from dynamo_tpu.loadgen.replay import replay_engine, replay_http
    from dynamo_tpu.loadgen.scenarios import load_scenario
    from dynamo_tpu.loadgen.trace import compile_trace

    itl_budget_ms = 250.0
    rate, burst = 20.0, 300.0
    eng_kw = dict(
        model_id="tiny", page_size=4, num_pages=64, max_seqs=3,
        max_model_len=256, prefill_buckets=(16, 32, 64), decode_steps=2,
        pipeline_depth=1, prefill_batches_per_step=1,
        qos_preempt_wait_ms=50.0,
    )
    spec_a = load_scenario("bursty_chat", seed=5, num_requests=10).replace(
        name="fleet_a", tenants=("tenant-a",), isl_mean=32, isl_max=64,
        osl_dist="fixed", osl_mean=96, osl_max=96, vocab=256, rate_rps=24.0,
        burst_factor=6.0, slo_ttft_ms=30000.0, slo_itl_ms=itl_budget_ms,
    )
    spec_b = load_scenario("bursty_chat", seed=6, num_requests=5).replace(
        name="fleet_b", arrival="poisson", tenants=("tenant-b",), isl_mean=12,
        isl_max=24, osl_dist="fixed", osl_mean=48, osl_max=48, vocab=256,
        rate_rps=0.8, slo_ttft_ms=30000.0, slo_itl_ms=itl_budget_ms,
    )
    merged = sorted(
        compile_trace(spec_a) + compile_trace(spec_b), key=lambda tr: tr.at_s
    )

    def mk_ctl():
        # priorities ride the POLICY here (replay_http sends no x-priority
        # header): tenant-b lands critical at BOTH doors
        return AdmissionController(QosPolicy.from_specs(
            "tenant-a=20:300", "tenant-a=batch,tenant-b=critical",
            fleet_replicas=2,
        ))

    async def body():
        eng = AsyncJaxEngine(EngineConfig(qos=True, **eng_kw))
        await eng.start()
        ctls = [mk_ctl(), mk_ctl()]
        services = []
        try:
            for wspec in (spec_a.replace(seed=98, num_requests=3),
                          spec_b.replace(seed=99, num_requests=3)):
                await replay_engine(eng, compile_trace(wspec), spec=wspec,
                                    speed=100.0)
            urls = []
            for ctl in ctls:
                svc = HttpService(host="127.0.0.1", port=0, qos=ctl)
                svc.manager.add(build_pipeline(eng, card_for_model("tiny")))
                port = await svc.start()
                services.append(svc)
                urls.append(f"http://127.0.0.1:{port}")

            async with aiohttp.ClientSession() as s:
                async with s.get(urls[0] + "/ready") as r:
                    assert (await r.json())["qos_fleet_replicas"] == 2

            t0 = time.monotonic()
            report = await replay_http(urls, "tiny", merged, spec=spec_b,
                                       speed=2.0)
            wall = time.monotonic() - t0

            b_out = [o for o in report["outcomes"]
                     if o.get("tenant") == "tenant-b"]
            assert len(b_out) == 5
            assert not any(o.get("error") for o in b_out), b_out
            vals = [o["itl_p99_ms"] for o in b_out
                    if o.get("itl_p99_ms") is not None]
            assert vals and max(vals) <= itl_budget_ms, vals

            snaps = [c.snapshot() for c in ctls]
            throttled = sum(
                s["classes"].get("batch", {}).get("tenant-a", {})
                .get("throttled", 0) for s in snaps
            )
            assert throttled > 0, snaps
            # the fleet-wide proof: both doors TOGETHER admitted at most one
            # shared budget envelope (each holds burst/2 and refills at
            # rate/2, so the sum telescopes to burst + rate*wall; a naive
            # per-door policy would allow double)
            admitted_a = sum(
                s["admitted_tokens"].get("tenant-a", 0.0) for s in snaps
            )
            envelope = burst + rate * wall
            assert 0.0 < admitted_a <= envelope + 1e-6, (admitted_a, envelope)
        finally:
            for svc in services:
                await svc.stop()
            await eng.shutdown()

    asyncio.run(body())
