"""Sequence-parallel (ring attention) prefill integrated into the engine:
whole prompts are sharded across the sp mesh axis, K/V shards rotate via
ppermute, and the paged pool ends up byte-identical — so SP is transparent to
the decode path and the prefix cache.

The reference has no long-context sequence parallelism (SURVEY.md §2.8);
this is the TPU-native long-context path, tested on the virtual CPU mesh.
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import EngineRequest
from dynamo_tpu.models.llama import LlamaConfig, LlamaModel

from tests.test_engine import _collect, tiny_engine_config


# compile-heavy JAX e2e: runs in the full matrix, not the <2-min default tier
pytestmark = pytest.mark.slow

PROMPT = [5, 9, 2, 77, 31, 8, 100, 42, 17, 3, 60, 61, 7, 21, 90, 4]  # 16 tokens


def test_prefill_sp_matches_prefill():
    """Model level: sp=4 ring prefill produces the same logits AND the same
    paged-pool contents as the single-device paged prefill."""
    from jax.sharding import Mesh

    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.key(0))
    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))

    NUM_PAGES, PAGE_SIZE = 16, 4
    pt = np.array([3, 5, 7, 9, 0, 0, 0, 0], np.int32)
    T = len(PROMPT)
    tokens = jnp.asarray(PROMPT, jnp.int32)
    positions = jnp.arange(T, dtype=jnp.int32)
    valid = jnp.ones(T, bool)

    kv_a = model.init_kv_cache(NUM_PAGES, PAGE_SIZE)
    logits_a, kv_a = model.prefill(
        params, kv_a, tokens, positions, jnp.asarray(pt), valid, jnp.asarray(T - 1)
    )
    kv_b = model.init_kv_cache(NUM_PAGES, PAGE_SIZE)
    logits_b, kv_b = jax.jit(
        lambda *a: model.prefill_sp(*a, mesh=mesh)
    )(params, kv_b, tokens, positions, jnp.asarray(pt), valid, jnp.asarray(T - 1))

    np.testing.assert_allclose(np.asarray(logits_a), np.asarray(logits_b), atol=1e-4)
    owned = pt[:4]
    flat = (owned[None, :] + np.arange(cfg.num_layers)[:, None] * NUM_PAGES).ravel()
    for leaf in ("k", "v"):
        np.testing.assert_allclose(
            np.asarray(kv_a[leaf][flat]), np.asarray(kv_b[leaf][flat]), atol=1e-4
        )


def test_prefill_sp_composes_with_tp():
    """Composed (sp, tp) mesh: each tp head shard runs its own sp ring;
    logits and pool contents must match the single-device prefill."""
    from jax.sharding import Mesh

    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.key(0))
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2), ("sp", "tp"))

    NUM_PAGES, PAGE_SIZE = 16, 4
    pt = np.array([3, 5, 7, 9, 0, 0, 0, 0], np.int32)
    T = len(PROMPT)
    tokens = jnp.asarray(PROMPT, jnp.int32)
    positions = jnp.arange(T, dtype=jnp.int32)
    valid = jnp.ones(T, bool)

    kv_a = model.init_kv_cache(NUM_PAGES, PAGE_SIZE)
    logits_a, kv_a = model.prefill(
        params, kv_a, tokens, positions, jnp.asarray(pt), valid, jnp.asarray(T - 1)
    )
    params_tp = jax.device_put(params, model.param_shardings(mesh))
    kv_b = jax.device_put(
        model.init_kv_cache(NUM_PAGES, PAGE_SIZE), model.kv_cache_sharding(mesh)
    )
    logits_b, kv_b = jax.jit(
        lambda *a: model.prefill_sp(*a, mesh=mesh)
    )(params_tp, kv_b, tokens, positions, jnp.asarray(pt), valid, jnp.asarray(T - 1))

    np.testing.assert_allclose(np.asarray(logits_a), np.asarray(logits_b), atol=1e-4)
    owned = pt[:4]
    flat = (owned[None, :] + np.arange(cfg.num_layers)[:, None] * NUM_PAGES).ravel()
    for leaf in ("k", "v"):
        np.testing.assert_allclose(
            np.asarray(kv_a[leaf][flat]), np.asarray(kv_b[leaf][flat]), atol=1e-4
        )


def test_engine_sp_tp_token_exact():
    """Engine e2e on the composed sp=2 x tp=2 mesh matches sp=1/tp=1 greedy
    tokens (SP ring prefill + tp-sharded decode in one engine)."""

    def run(sp, tp):
        async def body():
            eng = AsyncJaxEngine(
                tiny_engine_config(sp=sp, tp=tp, page_size=4, num_pages=32,
                                   max_seqs=2, prefill_buckets=(8, 16, 32))
            )
            await eng.start()
            try:
                toks, _, _ = await _collect(
                    eng,
                    EngineRequest(
                        request_id="s1",
                        token_ids=list(PROMPT),
                        sampling=SamplingParams(temperature=0.0, max_tokens=6),
                    ),
                )
                return toks
            finally:
                await eng.shutdown()

        return asyncio.run(body())

    assert run(2, 2) == run(1, 1)


def test_engine_sp_prefill_token_exact():
    """Engine level: an sp=4 engine generates the same greedy tokens as sp=1,
    including a second request that hits the prefix cache written by the SP
    prefill (proving the pool contents are real, not just the logits)."""

    def run(sp):
        async def body():
            eng = AsyncJaxEngine(
                tiny_engine_config(sp=sp, page_size=4, num_pages=32, max_seqs=2)
            )
            await eng.start()
            try:
                toks1, _, cached1 = await _collect(
                    eng,
                    EngineRequest(
                        request_id="s1",
                        token_ids=list(PROMPT),
                        sampling=SamplingParams(temperature=0.0, max_tokens=6),
                    ),
                )
                # longer prompt sharing the prefix: exercises cache + the
                # chunked (non-SP) follow-up path for the uncached tail
                toks2, _, cached2 = await _collect(
                    eng,
                    EngineRequest(
                        request_id="s2",
                        token_ids=list(PROMPT) + [33, 44, 55, 66],
                        sampling=SamplingParams(temperature=0.0, max_tokens=6),
                    ),
                )
                return toks1, cached1, toks2, cached2
            finally:
                await eng.shutdown()

        return asyncio.run(body())

    t1_sp, c1_sp, t2_sp, c2_sp = run(4)
    t1_ref, c1_ref, t2_ref, c2_ref = run(1)
    assert t1_sp == t1_ref, f"sp {t1_sp} != ref {t1_ref}"
    assert t2_sp == t2_ref, f"sp {t2_sp} != ref {t2_ref}"
    assert c1_sp == c1_ref == 0
    assert c2_sp == c2_ref > 0  # prefix written by SP prefill is reusable
