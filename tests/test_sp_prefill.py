"""Sequence-parallel (ring attention) prefill integrated into the engine:
whole prompts are sharded across the sp mesh axis, K/V shards rotate via
ppermute, and the paged pool ends up byte-identical — so SP is transparent to
the decode path and the prefix cache.

The reference has no long-context sequence parallelism (SURVEY.md §2.8);
this is the TPU-native long-context path, tested on the virtual CPU mesh.
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import EngineRequest
from dynamo_tpu.models.llama import LlamaConfig, LlamaModel

from tests.test_engine import _collect, tiny_engine_config


# compile-heavy JAX e2e: runs in the full matrix, not the <2-min default tier
pytestmark = pytest.mark.slow

PROMPT = [5, 9, 2, 77, 31, 8, 100, 42, 17, 3, 60, 61, 7, 21, 90, 4]  # 16 tokens


def test_prefill_sp_matches_prefill():
    """Model level: sp=4 ring prefill produces the same logits AND the same
    paged-pool contents as the single-device paged prefill."""
    from jax.sharding import Mesh

    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.key(0))
    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))

    NUM_PAGES, PAGE_SIZE = 16, 4
    pt = np.array([3, 5, 7, 9, 0, 0, 0, 0], np.int32)
    T = len(PROMPT)
    tokens = jnp.asarray(PROMPT, jnp.int32)
    positions = jnp.arange(T, dtype=jnp.int32)
    valid = jnp.ones(T, bool)

    kv_a = model.init_kv_cache(NUM_PAGES, PAGE_SIZE)
    logits_a, kv_a = model.prefill(
        params, kv_a, tokens, positions, jnp.asarray(pt), valid, jnp.asarray(T - 1)
    )
    kv_b = model.init_kv_cache(NUM_PAGES, PAGE_SIZE)
    logits_b, kv_b = jax.jit(
        lambda *a: model.prefill_sp(*a, mesh=mesh)
    )(params, kv_b, tokens, positions, jnp.asarray(pt), valid, jnp.asarray(T - 1))

    np.testing.assert_allclose(np.asarray(logits_a), np.asarray(logits_b), atol=1e-4)
    owned = pt[:4]
    flat = (owned[None, :] + np.arange(cfg.num_layers)[:, None] * NUM_PAGES).ravel()
    for leaf in ("k", "v"):
        np.testing.assert_allclose(
            np.asarray(kv_a[leaf][flat]), np.asarray(kv_b[leaf][flat]), atol=1e-4
        )


def test_prefill_sp_composes_with_tp():
    """Composed (sp, tp) mesh: each tp head shard runs its own sp ring;
    logits and pool contents must match the single-device prefill."""
    from jax.sharding import Mesh

    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.key(0))
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2), ("sp", "tp"))

    NUM_PAGES, PAGE_SIZE = 16, 4
    pt = np.array([3, 5, 7, 9, 0, 0, 0, 0], np.int32)
    T = len(PROMPT)
    tokens = jnp.asarray(PROMPT, jnp.int32)
    positions = jnp.arange(T, dtype=jnp.int32)
    valid = jnp.ones(T, bool)

    kv_a = model.init_kv_cache(NUM_PAGES, PAGE_SIZE)
    logits_a, kv_a = model.prefill(
        params, kv_a, tokens, positions, jnp.asarray(pt), valid, jnp.asarray(T - 1)
    )
    params_tp = jax.device_put(params, model.param_shardings(mesh))
    kv_b = jax.device_put(
        model.init_kv_cache(NUM_PAGES, PAGE_SIZE), model.kv_cache_sharding(mesh)
    )
    logits_b, kv_b = jax.jit(
        lambda *a: model.prefill_sp(*a, mesh=mesh)
    )(params_tp, kv_b, tokens, positions, jnp.asarray(pt), valid, jnp.asarray(T - 1))

    np.testing.assert_allclose(np.asarray(logits_a), np.asarray(logits_b), atol=1e-4)
    owned = pt[:4]
    flat = (owned[None, :] + np.arange(cfg.num_layers)[:, None] * NUM_PAGES).ravel()
    for leaf in ("k", "v"):
        np.testing.assert_allclose(
            np.asarray(kv_a[leaf][flat]), np.asarray(kv_b[leaf][flat]), atol=1e-4
        )


def test_engine_sp_tp_token_exact():
    """Engine e2e on the composed sp=2 x tp=2 mesh matches sp=1/tp=1 greedy
    tokens (SP ring prefill + tp-sharded decode in one engine)."""

    def run(sp, tp):
        async def body():
            eng = AsyncJaxEngine(
                tiny_engine_config(sp=sp, tp=tp, page_size=4, num_pages=32,
                                   max_seqs=2, prefill_buckets=(8, 16, 32))
            )
            await eng.start()
            try:
                toks, _, _ = await _collect(
                    eng,
                    EngineRequest(
                        request_id="s1",
                        token_ids=list(PROMPT),
                        sampling=SamplingParams(temperature=0.0, max_tokens=6),
                    ),
                )
                return toks
            finally:
                await eng.shutdown()

        return asyncio.run(body())

    assert run(2, 2) == run(1, 1)


def test_engine_sp_prefill_token_exact():
    """Engine level: an sp=4 engine generates the same greedy tokens as sp=1,
    including a second request that hits the prefix cache written by the SP
    prefill (proving the pool contents are real, not just the logits)."""

    def run(sp):
        async def body():
            eng = AsyncJaxEngine(
                tiny_engine_config(sp=sp, page_size=4, num_pages=32, max_seqs=2)
            )
            await eng.start()
            try:
                toks1, _, cached1 = await _collect(
                    eng,
                    EngineRequest(
                        request_id="s1",
                        token_ids=list(PROMPT),
                        sampling=SamplingParams(temperature=0.0, max_tokens=6),
                    ),
                )
                # longer prompt sharing the prefix: exercises cache + the
                # chunked (non-SP) follow-up path for the uncached tail
                toks2, _, cached2 = await _collect(
                    eng,
                    EngineRequest(
                        request_id="s2",
                        token_ids=list(PROMPT) + [33, 44, 55, 66],
                        sampling=SamplingParams(temperature=0.0, max_tokens=6),
                    ),
                )
                return toks1, cached1, toks2, cached2
            finally:
                await eng.shutdown()

        return asyncio.run(body())

    t1_sp, c1_sp, t2_sp, c2_sp = run(4)
    t1_ref, c1_ref, t2_ref, c2_ref = run(1)
    assert t1_sp == t1_ref, f"sp {t1_sp} != ref {t1_ref}"
    assert t2_sp == t2_ref, f"sp {t2_sp} != ref {t2_ref}"
    assert c1_sp == c1_ref == 0
    assert c2_sp == c2_ref > 0  # prefix written by SP prefill is reusable


def test_prefill_sp_deep_context_parity_T1024():
    """T=1024 ring parity (ISSUE 8: ring/sp prefill was only ever exercised
    at T=64): sp=4 whole-prompt ring prefill matches the paged prefill on
    logits AND pool contents at real long-context depth."""
    from jax.sharding import Mesh

    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.key(0))
    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))

    T, PAGE_SIZE = 1024, 16
    NUM_PAGES = T // PAGE_SIZE + 8
    rng = np.random.default_rng(7)
    prompt = rng.integers(1, 200, T).tolist()
    pt = np.arange(1, T // PAGE_SIZE + 1, dtype=np.int32)
    pt_full = np.zeros(T // PAGE_SIZE + 4, np.int32)
    pt_full[: len(pt)] = pt
    tokens = jnp.asarray(prompt, jnp.int32)
    positions = jnp.arange(T, dtype=jnp.int32)
    valid = jnp.ones(T, bool)

    kv_a = model.init_kv_cache(NUM_PAGES, PAGE_SIZE)
    logits_a, kv_a = model.prefill(
        params, kv_a, tokens, positions, jnp.asarray(pt_full), valid,
        jnp.asarray(T - 1),
    )
    kv_b = model.init_kv_cache(NUM_PAGES, PAGE_SIZE)
    logits_b, kv_b = jax.jit(lambda *a: model.prefill_sp(*a, mesh=mesh))(
        params, kv_b, tokens, positions, jnp.asarray(pt_full), valid,
        jnp.asarray(T - 1),
    )
    np.testing.assert_allclose(
        np.asarray(logits_a), np.asarray(logits_b), atol=2e-3, rtol=2e-3
    )
    flat = (pt[None, :] + np.arange(cfg.num_layers)[:, None] * NUM_PAGES).ravel()
    for leaf in ("k", "v"):
        np.testing.assert_allclose(
            np.asarray(kv_a[leaf][flat]), np.asarray(kv_b[leaf][flat]),
            atol=2e-3, rtol=2e-3,
        )


def test_engine_sp_deep_prompt_token_exact():
    """Engine e2e at T=1025 — deliberately NOT bucket-aligned, so the ring
    runs with padded rows AND a 1-token paged follow-up chunk rides behind
    the sp whole-prefix chunk — sp=4 greedy tokens match sp=1."""

    def run(sp):
        async def body():
            eng = AsyncJaxEngine(EngineConfig(
                model_id="tiny", page_size=16, num_pages=200, max_seqs=2,
                max_model_len=4096, prefill_buckets=(256, 512, 1024), sp=sp,
            ))
            await eng.start()
            try:
                rng = np.random.default_rng(5)
                prompt = [int(x) for x in rng.integers(1, 200, 1025)]
                toks, _, _ = await _collect(
                    eng,
                    EngineRequest(
                        request_id="deep",
                        token_ids=prompt,
                        sampling=SamplingParams(temperature=0.0, max_tokens=6),
                    ),
                )
                return toks
            finally:
                await eng.shutdown()

        return asyncio.run(body())

    assert run(4) == run(1)


def test_sp_prefill_composes_with_kv_stream():
    """An sp=2 prefill engine streams its KV per chunk (the kv_stream export
    path: sync_remote_prefill(on_part=...)), a plain decode engine scatters
    the parts and adopts — the adopted decode must be token-identical to a
    local sp=1 engine serving the same prompt."""
    from dynamo_tpu.llm.remote_prefill import RemotePrefillRequest

    def cfg(sp):
        return EngineConfig(
            model_id="tiny", page_size=16, num_pages=160, max_seqs=2,
            max_model_len=2048, prefill_buckets=(256, 512, 1024), sp=sp,
        )

    rng = np.random.default_rng(3)
    prompt = [int(x) for x in rng.integers(1, 200, 1024)]

    async def local():
        eng = AsyncJaxEngine(cfg(1))
        await eng.start()
        try:
            toks, _, _ = await _collect(
                eng,
                EngineRequest(
                    request_id="local", token_ids=prompt,
                    sampling=SamplingParams(temperature=0.0, max_tokens=8),
                ),
            )
            return toks
        finally:
            await eng.shutdown()

    async def disagg():
        pre = AsyncJaxEngine(cfg(2))
        await pre.start()
        dec = AsyncJaxEngine(cfg(1))
        await dec.start()
        try:
            rp = RemotePrefillRequest(
                request_id="x", token_ids=prompt, temperature=0.0,
                top_k=0, top_p=1.0, decode_worker_id="w",
            )
            parts = []
            result, _ = await pre.run_on_engine(
                lambda: pre.sync_remote_prefill(
                    rp, mode="socket",
                    on_part=lambda *a: parts.append(a),
                )
            )
            assert result.kv_parts == len(parts) and parts, \
                "sp prefill produced no streamed parts"
            cached, _, pages = await dec.run_on_engine(
                lambda: dec.sync_allocate_remote("x", prompt)
            )
            injected = 0
            for _seq, _total, pf, pt, fut in parts:
                data = fut.result()
                ids = np.asarray(pages[pf:pt], np.int32)
                await dec.run_on_engine(
                    lambda ids=ids, data=data:
                        dec.runner.inject_pages_bucketed(ids, data)
                )
                injected += len(ids)
            req = EngineRequest(
                request_id="x", token_ids=prompt,
                sampling=SamplingParams(temperature=0.0, max_tokens=8),
            )
            dec._register_stream("x")
            await dec.run_on_engine(
                lambda: dec.sync_adopt_prefilled(
                    req, result, cached, injected_pages=injected
                )
            )
            toks = []
            async for out in dec._drain_stream("x"):
                if out.token is not None:
                    toks.append(out.token)
            return toks
        finally:
            await pre.shutdown()
            await dec.shutdown()

    expected = asyncio.run(local())
    got = asyncio.run(disagg())
    assert got == expected, f"sp x kv_stream {got} != local {expected}"


def test_prefill_pipelined_ring_matches_prefill():
    """Composed pp=2 x sp=2 (VERDICT r4 item 6): ring prefill inside the
    GPipe shard_map matches the single-device paged prefill — logits AND
    pool contents (decode reads the pool, so replicas must be real)."""
    from jax.sharding import Mesh

    from dynamo_tpu.parallel.pipeline import (
        decode_pipelined,
        prefill_pipelined_ring,
        stage_kv_sharding,
        stage_param_shardings,
    )

    cfg = LlamaConfig.tiny(num_layers=4)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.key(0))
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("pp", "sp"))
    params_pp = jax.device_put(params, stage_param_shardings(model, mesh))
    NUM_PAGES, PAGE_SIZE = 16, 4
    kv_pp = jax.device_put(
        model.init_kv_cache(NUM_PAGES, PAGE_SIZE),
        stage_kv_sharding(mesh, folded=cfg.kv_folded),
    )

    T = len(PROMPT)
    pt = np.array([3, 5, 7, 9, 0, 0, 0, 0], np.int32)
    pos = np.arange(T, dtype=np.int32)
    valid = np.ones(T, bool)

    ref_logits, ref_kv = model.prefill(
        params, model.init_kv_cache(NUM_PAGES, PAGE_SIZE),
        jnp.asarray(PROMPT, jnp.int32), jnp.asarray(pos), jnp.asarray(pt),
        jnp.asarray(valid), jnp.asarray(T - 1),
    )
    ring_logits, kv_ring = jax.jit(
        lambda p, kv: prefill_pipelined_ring(
            model, p, kv, jnp.asarray(PROMPT, jnp.int32), jnp.asarray(pos),
            jnp.asarray(pt), jnp.asarray(valid), jnp.asarray(T - 1), mesh,
        ),
        donate_argnums=(1,),
    )(params_pp, kv_pp)
    np.testing.assert_allclose(
        np.asarray(ring_logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
    )
    # pool parity on the written pages (all sp replicas must hold ALL rows)
    owned = pt[:4]
    flat = (owned[None, :] + np.arange(cfg.num_layers)[:, None] * NUM_PAGES).ravel()
    for leaf in ("k", "v"):
        np.testing.assert_allclose(
            np.asarray(kv_ring[leaf])[flat], np.asarray(ref_kv[leaf])[flat],
            rtol=2e-4, atol=2e-4,
        )

    # decode step over the ring-written pool on the same composed mesh
    B = 4
    toks = np.zeros(B, np.int32); toks[0] = 42
    dpos = np.zeros(B, np.int32); dpos[0] = T
    pts = np.zeros((B, 8), np.int32); pts[0] = pt
    act = np.zeros(B, bool); act[0] = True
    ref_dlog, _ = model.decode(
        params, ref_kv, jnp.asarray(toks), jnp.asarray(dpos),
        jnp.asarray(pts), jnp.asarray(act),
    )
    ring_dlog, _ = jax.jit(
        lambda p, kv: decode_pipelined(
            model, p, kv, jnp.asarray(toks), jnp.asarray(dpos), jnp.asarray(pts),
            jnp.asarray(act), mesh, num_microbatches=2,
        ),
        donate_argnums=(1,),
    )(params_pp, kv_ring)
    np.testing.assert_allclose(
        np.asarray(ring_dlog)[0], np.asarray(ref_dlog)[0], rtol=2e-4, atol=2e-4
    )


def test_engine_pp_sp_token_exact():
    """Engine e2e on the composed pp=2 x sp=2 mesh: greedy tokens match the
    single-device engine, including a prefix-cache revisit (the long-context
    mesh — depth over pp, length over sp — lifted from mutual exclusivity)."""

    def run(pp, sp):
        async def body():
            eng = AsyncJaxEngine(
                tiny_engine_config(pp=pp, sp=sp, page_size=4, num_pages=32,
                                   max_seqs=2, prefill_buckets=(8, 16, 32))
            )
            await eng.start()
            try:
                toks1, _, _ = await _collect(
                    eng,
                    EngineRequest(
                        request_id="s1",
                        token_ids=list(PROMPT),
                        sampling=SamplingParams(temperature=0.0, max_tokens=6),
                    ),
                )
                toks2, _, cached2 = await _collect(
                    eng,
                    EngineRequest(
                        request_id="s2",
                        token_ids=list(PROMPT) + [33, 44, 55, 66],
                        sampling=SamplingParams(temperature=0.0, max_tokens=6),
                    ),
                )
                return toks1, toks2, cached2
            finally:
                await eng.shutdown()

        return asyncio.run(body())

    t1, t2, c2 = run(2, 2)
    r1, r2, rc2 = run(1, 1)
    assert t1 == r1, f"pp x sp {t1} != ref {r1}"
    assert t2 == r2, f"pp x sp {t2} != ref {r2}"
    assert c2 == rc2 > 0  # ring-written prefix reusable through the pool
