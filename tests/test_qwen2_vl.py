"""Qwen2-VL multimodal family: vision tower, mm prefill, preprocessor content
parts, and engine end-to-end with images."""

import asyncio
import base64
import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.llm.multimodal import ImageInput, image_content_hash, mrope_positions, patchify, smart_resize, virtual_token_ids
from dynamo_tpu.models.qwen2_vl import Qwen2VLConfig, Qwen2VLModel
from dynamo_tpu.ops.norms import rms_norm
from dynamo_tpu.ops.rotary import apply_mrope, apply_rope


# compile-heavy JAX e2e: runs in the full matrix, not the <2-min default tier
pytestmark = pytest.mark.slow


def rng_image(seed=0, h=24, w=16):
    return np.random.default_rng(seed).random((h, w, 3)).astype(np.float32)


def npy_data_uri(arr: np.ndarray) -> str:
    buf = io.BytesIO()
    np.save(buf, arr)
    return "data:application/x-npy;base64," + base64.b64encode(buf.getvalue()).decode()


# ---------------- vision tower ----------------


def test_smart_resize_multiples():
    h, w = smart_resize(123, 77, factor=8)
    assert h % 8 == 0 and w % 8 == 0
    assert h * w >= 56 * 56


def test_patchify_merge_group_order():
    cfg = Qwen2VLConfig.tiny_vl()
    ps, m = cfg.vision.patch_size, cfg.vision.spatial_merge_size
    img = rng_image()
    patches, rows, cols, (gh, gw) = patchify(img, ps, m)
    assert patches.shape == (gh * gw, 3 * ps * ps)
    # each consecutive group of m*m patches covers one m x m merged cell
    for g in range(0, len(rows), m * m):
        rr, cc = rows[g : g + m * m], cols[g : g + m * m]
        assert rr.max() - rr.min() == m - 1
        assert cc.max() - cc.min() == m - 1
        assert rr.min() % m == 0 and cc.min() % m == 0


def test_vision_padding_invariance():
    """Padded patches (valid=False) must not change the real embeddings."""
    cfg = Qwen2VLConfig.tiny_vl()
    model = Qwen2VLModel(cfg)
    params = model.init_params(jax.random.key(0))
    img = rng_image()
    patches, rows, cols, _ = patchify(img, cfg.vision.patch_size, cfg.vision.spatial_merge_size)
    n = patches.shape[0]
    m2 = cfg.vision.spatial_merge_size**2

    emb = model.encode_images(
        params, jnp.asarray(patches), jnp.asarray(rows), jnp.asarray(cols),
        jnp.ones(n, bool),
    )
    pad = 3 * m2  # keep N divisible by merge^2
    patches_p = np.concatenate([patches, np.ones((pad, patches.shape[1]), np.float32)])
    rows_p = np.concatenate([rows, np.zeros(pad, np.int32)])
    cols_p = np.concatenate([cols, np.zeros(pad, np.int32)])
    valid = np.concatenate([np.ones(n, bool), np.zeros(pad, bool)])
    emb_p = model.encode_images(
        params, jnp.asarray(patches_p), jnp.asarray(rows_p), jnp.asarray(cols_p),
        jnp.asarray(valid),
    )
    np.testing.assert_allclose(
        np.asarray(emb), np.asarray(emb_p)[: n // m2], rtol=2e-4, atol=2e-4
    )


# ---------------- mm prefill vs naive dense reference ----------------


def naive_mm_forward(cfg, params, tokens, embeds, mask, pos3=None):
    """Dense causal transformer with qkv biases + embedding override; applies
    M-RoPE when the config has mrope_section and pos3 is given."""
    T = len(tokens)
    pos = jnp.arange(T)
    h = params["embed"][jnp.array(tokens)].astype(cfg.dtype)
    h = jnp.where(jnp.asarray(mask)[:, None], jnp.asarray(embeds, cfg.dtype), h)
    for l in range(cfg.num_layers):
        lp = jax.tree.map(lambda x: x[l], params["layers"])
        x = rms_norm(h, lp["input_norm"], cfg.rms_norm_eps)
        q = (x @ lp["wq"] + lp["bq"]).reshape(T, cfg.num_heads, cfg.head_dim)
        k = (x @ lp["wk"] + lp["bk"]).reshape(T, cfg.num_kv_heads, cfg.head_dim)
        v = (x @ lp["wv"] + lp["bv"]).reshape(T, cfg.num_kv_heads, cfg.head_dim)
        if cfg.mrope_section is not None and pos3 is not None:
            q = apply_mrope(q, jnp.asarray(pos3), tuple(cfg.mrope_section), cfg.rope_theta)
            k = apply_mrope(k, jnp.asarray(pos3), tuple(cfg.mrope_section), cfg.rope_theta)
        else:
            q = apply_rope(q, pos, cfg.rope_theta)
            k = apply_rope(k, pos, cfg.rope_theta)
        g = cfg.num_heads // cfg.num_kv_heads
        kr = jnp.repeat(k, g, axis=1)
        vr = jnp.repeat(v, g, axis=1)
        s = jnp.einsum("thd,shd->hts", q.astype(jnp.float32), kr.astype(jnp.float32))
        s = s / np.sqrt(cfg.head_dim)
        s = jnp.where(jnp.tril(jnp.ones((T, T), bool))[None], s, -1e30)
        a = jnp.einsum("hts,shd->thd", jax.nn.softmax(s, -1), vr.astype(jnp.float32)).astype(cfg.dtype)
        h = h + a.reshape(T, -1) @ lp["wo"]
        x = rms_norm(h, lp["post_norm"], cfg.rms_norm_eps)
        h = h + (jax.nn.silu(x @ lp["gate"]) * (x @ lp["up"])) @ lp["down"]
    x = rms_norm(h, params["final_norm"], cfg.rms_norm_eps)
    head = params["embed"] if cfg.tie_word_embeddings else params["lm_head"]
    return jnp.einsum("td,vd->tv", x.astype(jnp.float32), head.astype(jnp.float32))


def test_mm_prefill_matches_naive():
    cfg = Qwen2VLConfig.tiny_vl()
    model = Qwen2VLModel(cfg)
    params = model.init_params(jax.random.key(1))
    img = rng_image(3)
    patches, rows, cols, _ = patchify(img, cfg.vision.patch_size, cfg.vision.spatial_merge_size)
    n_img = patches.shape[0] // cfg.vision.spatial_merge_size**2
    emb = np.asarray(
        model.encode_images(
            params, jnp.asarray(patches), jnp.asarray(rows), jnp.asarray(cols),
            jnp.ones(len(rows), bool),
        ),
        np.float32,
    )
    vids = virtual_token_ids(image_content_hash(img), n_img, cfg.vocab_size)
    toks = [7, 11] + vids + [13]
    T = len(toks)
    embeds = np.zeros((T, cfg.hidden_size), np.float32)
    embeds[2 : 2 + n_img] = emb
    mask = np.zeros(T, bool)
    mask[2 : 2 + n_img] = True

    ref = naive_mm_forward(cfg, params, toks, embeds, mask)[-1]

    T_pad = 64
    tokens = np.zeros(T_pad, np.int32)
    tokens[:T] = toks
    embeds_pad = np.zeros((T_pad, cfg.hidden_size), np.float32)
    embeds_pad[:T] = embeds
    mask_pad = np.zeros(T_pad, bool)
    mask_pad[:T] = mask
    positions = np.arange(T_pad, dtype=np.int32)
    num_pages = 32
    kv = model.init_kv_cache(num_pages, 16)
    page_table = np.array([1, 2, 3, 4], np.int32)
    logits, _ = model.prefill(
        params, kv, jnp.asarray(tokens), jnp.asarray(positions),
        jnp.asarray(page_table), jnp.asarray(positions < T), jnp.asarray(T - 1),
        input_embeds=jnp.asarray(embeds_pad), embeds_mask=jnp.asarray(mask_pad),
    )
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref), rtol=2e-3, atol=2e-3)


# ---------------- preprocessor content parts ----------------


def test_preprocessor_content_parts():
    from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
    from dynamo_tpu.llm.protocols.openai import ChatCompletionRequest
    from dynamo_tpu.llm.tokenizer import get_tokenizer

    tok = get_tokenizer("byte")
    pre = OpenAIPreprocessor(
        tok, "tiny-vl", max_model_len=512,
        mm={"patch_size": 4, "merge_size": 2, "vocab_size": 256},
    )
    img = rng_image(5, h=16, w=16)
    req = ChatCompletionRequest.from_dict(
        {
            "model": "tiny-vl",
            "messages": [
                {
                    "role": "user",
                    "content": [
                        {"type": "text", "text": "look: "},
                        {"type": "image_url", "image_url": {"url": npy_data_uri(img)}},
                        {"type": "text", "text": " describe"},
                    ],
                }
            ],
        }
    )
    p, _ = pre.preprocess_chat(req)
    assert len(p.images) == 1
    im = p.images[0]
    assert im.num_tokens >= 1
    run = p.token_ids[im.offset : im.offset + im.num_tokens]
    assert run == virtual_token_ids(im.content_hash, im.num_tokens, 256)
    # same image again -> same virtual ids (prefix-cache identity)
    p2, _ = pre.preprocess_chat(req)
    assert p2.token_ids == p.token_ids
    # different image -> different ids
    req2 = ChatCompletionRequest.from_dict(
        {
            "model": "tiny-vl",
            "messages": [
                {
                    "role": "user",
                    "content": [
                        {"type": "text", "text": "look: "},
                        {"type": "image_url", "image_url": {"url": npy_data_uri(img + 0.05)}},
                        {"type": "text", "text": " describe"},
                    ],
                }
            ],
        }
    )
    p3, _ = pre.preprocess_chat(req2)
    assert p3.token_ids != p.token_ids


def test_preprocessor_wraps_runs_with_vision_delimiters():
    """When the checkpoint defines vision delimiter tokens (Qwen2-VL
    vision_start/end), every image's virtual-token run must be wrapped with
    them — real trained tokens the model sees around image content."""
    from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
    from dynamo_tpu.llm.protocols.openai import ChatCompletionRequest
    from dynamo_tpu.llm.tokenizer import get_tokenizer

    VS, VE = 250, 251
    pre = OpenAIPreprocessor(
        get_tokenizer("byte"), "tiny-vl", max_model_len=512,
        mm={"patch_size": 4, "merge_size": 2, "vocab_size": 256,
            "vision_start_id": VS, "vision_end_id": VE},
    )
    img = rng_image(7, h=16, w=16)
    req = ChatCompletionRequest.from_dict({
        "model": "tiny-vl",
        "messages": [{
            "role": "user",
            "content": [
                {"type": "text", "text": "a: "},
                {"type": "image_url", "image_url": {"url": npy_data_uri(img)}},
                {"type": "image_url", "image_url": {"url": npy_data_uri(img + 0.1)}},
            ],
        }],
    })
    p, _ = pre.preprocess_chat(req)
    assert len(p.images) == 2
    for im in p.images:
        run = p.token_ids[im.offset : im.offset + im.num_tokens]
        assert run == virtual_token_ids(im.content_hash, im.num_tokens, 256)
        assert p.token_ids[im.offset - 1] == VS
        assert p.token_ids[im.offset + im.num_tokens] == VE


def test_model_card_captures_vision_delimiters(tmp_path):
    import json

    from dynamo_tpu.llm.model_card import ModelDeploymentCard

    (tmp_path / "config.json").write_text(json.dumps({
        "architectures": ["Qwen2VLForConditionalGeneration"],
        "model_type": "qwen2_vl",
        "vocab_size": 152064,
        "vision_config": {"patch_size": 14, "spatial_merge_size": 2},
        "vision_start_token_id": 151652,
        "vision_end_token_id": 151653,
        "max_position_embeddings": 32768,
    }))
    card = ModelDeploymentCard.from_local_path(str(tmp_path))
    assert card.mm is not None
    assert card.mm["vision_start_id"] == 151652
    assert card.mm["vision_end_id"] == 151653
    # wire roundtrip keeps the mm block
    card2 = ModelDeploymentCard.from_wire(card.to_wire())
    assert card2.mm == card.mm


def test_preprocessor_rejects_images_for_text_model():
    from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
    from dynamo_tpu.llm.protocols.openai import ChatCompletionRequest, ProtocolError
    from dynamo_tpu.llm.tokenizer import get_tokenizer

    pre = OpenAIPreprocessor(get_tokenizer("byte"), "tiny", max_model_len=512)
    req = ChatCompletionRequest.from_dict(
        {
            "model": "tiny",
            "messages": [
                {
                    "role": "user",
                    "content": [
                        {"type": "image_url", "image_url": {"url": npy_data_uri(rng_image())}}
                    ],
                }
            ],
        }
    )
    with pytest.raises(ProtocolError):
        pre.preprocess_chat(req)


def test_image_input_wire_roundtrip():
    img = rng_image(9)
    patches, rows, cols, grid = patchify(img, 4, 2)
    im = ImageInput(
        offset=5, patches=patches, rows=rows, cols=cols, grid=grid,
        num_tokens=patches.shape[0] // 4, content_hash=image_content_hash(img),
    )
    im2 = ImageInput.from_wire(im.to_wire())
    np.testing.assert_array_equal(im.patches, im2.patches)
    np.testing.assert_array_equal(im.rows, im2.rows)
    assert (im.offset, im.grid, im.num_tokens, im.content_hash) == (
        im2.offset, im2.grid, im2.num_tokens, im2.content_hash,
    )


# ---------------- engine end-to-end ----------------


@pytest.fixture(scope="module")
def vl_engine():
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine

    cfg = EngineConfig(
        model_id="tiny-vl",
        page_size=4,
        num_pages=128,
        max_seqs=4,
        max_model_len=256,
        prefill_buckets=(32, 64, 128),
        tp=1,
    )
    engine = AsyncJaxEngine(cfg)
    loop = asyncio.new_event_loop()
    loop.run_until_complete(engine.start())
    yield engine, loop
    loop.run_until_complete(engine.shutdown())
    loop.close()


def _mm_request(engine, rid, img, max_tokens=6):
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import EngineRequest

    cfg = engine.model.config
    patches, rows, cols, grid = patchify(
        img, cfg.vision.patch_size, cfg.vision.spatial_merge_size
    )
    n_tok = patches.shape[0] // cfg.vision.spatial_merge_size**2
    chash = image_content_hash(img)
    toks = [1, 2] + virtual_token_ids(chash, n_tok, cfg.vocab_size) + [3]
    im = ImageInput(
        offset=2, patches=patches, rows=rows, cols=cols, grid=grid,
        num_tokens=n_tok, content_hash=chash,
    )
    return EngineRequest(
        request_id=rid,
        token_ids=toks,
        sampling=SamplingParams(temperature=0.0, max_tokens=max_tokens, ignore_eos=True),
        images=[im],
    )


async def _collect(engine, req):
    toks, cached = [], 0
    async for out in engine.generate(req):
        if out.token is not None:
            toks.append(out.token)
        cached = max(cached, out.cached_tokens)
    return toks, cached


def test_engine_mm_generate(vl_engine):
    engine, loop = vl_engine
    # structurally distinct images (solid dark vs bright gradient): the tiny
    # random model must not be allowed to coincidentally produce the same
    # greedy chain for both
    img_a = np.zeros((16, 16, 3), np.float32) + 0.05
    img_b = np.linspace(0, 1, 16 * 16 * 3, dtype=np.float32).reshape(16, 16, 3)

    toks_a, _ = loop.run_until_complete(_collect(engine, _mm_request(engine, "a", img_a)))
    toks_b, _ = loop.run_until_complete(_collect(engine, _mm_request(engine, "b", img_b)))
    assert len(toks_a) == 6 and len(toks_b) == 6
    # greedy decode must be image-dependent
    assert toks_a != toks_b

    # same image again: deterministic AND served from the prefix cache
    toks_a2, cached = loop.run_until_complete(
        _collect(engine, _mm_request(engine, "a2", img_a))
    )
    assert toks_a2 == toks_a
    assert cached > 0


def test_engine_mm_matches_naive(vl_engine):
    """Greedy engine output == dense-reference greedy continuation."""
    engine, loop = vl_engine
    cfg = engine.model.config
    img = rng_image(31, h=16, w=16)
    req = _mm_request(engine, "naive", img, max_tokens=4)
    engine_toks, _ = loop.run_until_complete(_collect(engine, req))

    params = jax.device_get(engine.runner.params)
    model = engine.model
    patches, rows, cols, _ = patchify(img, cfg.vision.patch_size, cfg.vision.spatial_merge_size)
    emb = np.asarray(
        model.encode_images(
            jax.device_put(params), jnp.asarray(patches), jnp.asarray(rows),
            jnp.asarray(cols), jnp.ones(len(rows), bool),
        ),
        np.float32,
    )
    toks = list(req.token_ids)
    n_img = req.images[0].num_tokens
    T0 = len(toks)
    pos3_prompt, delta = mrope_positions(
        T0, req.images, cfg.vision.spatial_merge_size
    )
    out = []
    for _ in range(4):
        T = len(toks)
        embeds = np.zeros((T, cfg.hidden_size), np.float32)
        embeds[2 : 2 + n_img] = emb
        mask = np.zeros(T, bool)
        mask[2 : 2 + n_img] = True
        # generated tail: all components advance together from the delta
        tail = np.array([[t + delta] * 3 for t in range(T0, T)], np.int32).reshape(-1, 3)
        pos3 = np.concatenate([pos3_prompt, tail]) if T > T0 else pos3_prompt
        logits = naive_mm_forward(cfg, params, toks, embeds, mask, pos3=pos3)
        nxt = int(jnp.argmax(logits[-1]))
        toks.append(nxt)
        out.append(nxt)
    assert engine_toks == out


# ---------------- M-RoPE ----------------


def test_mrope_config_from_hf():
    d = {
        "architectures": ["Qwen2VLForConditionalGeneration"],
        "model_type": "qwen2_vl",
        "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "num_key_value_heads": 2, "head_dim": 8,
        "rope_scaling": {"type": "mrope", "mrope_section": [1, 1, 2]},
        "vision_config": {"patch_size": 4, "embed_dim": 16, "depth": 1, "num_heads": 2},
    }
    cfg = Qwen2VLConfig.from_hf_config(d)
    assert cfg.mrope_section == (1, 1, 2)
    import pytest as _pytest

    bad = dict(d, rope_scaling={"type": "mrope", "mrope_section": [1, 1, 1]})
    with _pytest.raises(ValueError, match="mrope_section"):
        Qwen2VLConfig.from_hf_config(bad)


def test_mrope_text_only_reduces_to_1d_rope():
    """Same weights, text-only prompt: the mrope model must match a plain-rope
    control bit-for-bit (equal position components reduce M-RoPE to RoPE)."""
    from dataclasses import replace as _replace

    cfg_m = Qwen2VLConfig.tiny_vl()
    cfg_1d = _replace(cfg_m, mrope_section=None)
    model_m, model_1 = Qwen2VLModel(cfg_m), Qwen2VLModel(cfg_1d)
    params = model_m.init_params(jax.random.key(5))

    T = 8
    toks = np.array([3, 9, 1, 44, 7, 2, 60, 12], np.int32)
    pos = np.arange(T, dtype=np.int32)
    pt = np.array([1, 2, 0, 0], np.int32)
    valid = np.ones(T, bool)
    la, _ = model_m.prefill(
        params, model_m.init_kv_cache(8, 16), jnp.asarray(toks), jnp.asarray(pos),
        jnp.asarray(pt), jnp.asarray(valid), jnp.asarray(T - 1),
    )
    lb, _ = model_1.prefill(
        params, model_1.init_kv_cache(8, 16), jnp.asarray(toks), jnp.asarray(pos),
        jnp.asarray(pt), jnp.asarray(valid), jnp.asarray(T - 1),
    )
    np.testing.assert_allclose(np.asarray(la), np.asarray(lb), rtol=1e-6, atol=1e-6)


def test_batched_multi_image_encode_matches_per_image():
    """Runner packs a request's images into ONE segment-masked vision call;
    results must equal per-image encodes."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.model_runner import ModelRunner
    from dynamo_tpu.models.registry import load_model

    model, params = load_model("tiny-vl")
    cfg = EngineConfig(
        model_id="tiny-vl", page_size=4, num_pages=64, max_seqs=2,
        max_model_len=256, prefill_buckets=(32, 64, 128),
    )
    runner = ModelRunner(cfg, model, params)

    imgs = [rng_image(40 + i, h=16 + 8 * i, w=16) for i in range(3)]
    inputs = []
    off = 0
    for img in imgs:
        patches, rows, cols, grid = patchify(
            img, model.config.vision.patch_size, model.config.vision.spatial_merge_size
        )
        n_tok = patches.shape[0] // model.config.vision.spatial_merge_size**2
        inputs.append(ImageInput(
            offset=off, patches=patches, rows=rows, cols=cols, grid=grid,
            num_tokens=n_tok, content_hash=image_content_hash(img),
        ))
        off += n_tok

    batched = runner.encode_images(inputs)
    singles = [runner.encode_images([im])[0] for im in inputs]
    assert len(batched) == 3
    for b, s, im in zip(batched, singles, inputs):
        assert b.shape == (im.num_tokens, model.config.hidden_size)
        np.testing.assert_allclose(b, s, rtol=2e-4, atol=2e-4)


def test_engine_two_image_prompt(vl_engine):
    """A prompt with two images generates (both runs spliced)."""
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import EngineRequest

    engine, loop = vl_engine
    cfg = engine.model.config
    ims = []
    toks = [1]
    for i, img in enumerate([rng_image(61, h=16, w=16), rng_image(62, h=16, w=16)]):
        patches, rows, cols, grid = patchify(
            img, cfg.vision.patch_size, cfg.vision.spatial_merge_size
        )
        n_tok = patches.shape[0] // cfg.vision.spatial_merge_size**2
        chash = image_content_hash(img)
        ims.append(ImageInput(
            offset=len(toks), patches=patches, rows=rows, cols=cols, grid=grid,
            num_tokens=n_tok, content_hash=chash,
        ))
        toks += virtual_token_ids(chash, n_tok, cfg.vocab_size)
        toks.append(2)
    req = EngineRequest(
        request_id="two-img", token_ids=toks,
        sampling=SamplingParams(temperature=0.0, max_tokens=4, ignore_eos=True),
        images=ims,
    )
    out, _ = loop.run_until_complete(_collect(engine, req))
    assert len(out) == 4
