"""External-engine adapter (`out=pytok:module:fn`): an arbitrary user
async-generator engine hosted behind the full serving stack.

Mirrors the reference's generic Python engine tests (reference:
lib/llm/src/engines/python.rs:105-146 — pystr/pytok schemes hosting a
user module behind the same frontend/router machinery)."""

import asyncio
import json

import aiohttp
import pytest

from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import EngineRequest
from dynamo_tpu.llm.external import ExternalTokenEngine, resolve_spec


# ---- user engines (resolved by module:fn spec in the tests below) ----

async def ext_echo(token_ids, sampling, request_id):
    """Echo the prompt tokens back, one per step (pytok contract demo)."""
    for tok in token_ids:
        yield tok


async def ext_batched_stop(token_ids, sampling, request_id):
    yield [101, 102]
    yield {"token_ids": [103], "finish_reason": "stop"}
    yield 999  # must never be reached


async def ext_multi_stop(token_ids, sampling, request_id):
    """One multi-token item that also declares the natural stop."""
    yield {"token_ids": [201, 202, 203], "finish_reason": "stop"}


async def ext_empty(token_ids, sampling, request_id):
    if False:
        yield 0


def not_an_async_gen(token_ids, sampling, request_id):
    return []


async def collect(engine, token_ids, max_tokens=16):
    req = EngineRequest(
        request_id="r1", token_ids=token_ids,
        sampling=SamplingParams(max_tokens=max_tokens, ignore_eos=True),
    )
    outs = []
    async for out in engine.generate(req):
        outs.append(out)
    return outs


def test_adapter_echo_and_max_tokens():
    eng = ExternalTokenEngine("tests.test_external_engine:ext_echo")

    async def run():
        outs = await collect(eng, [5, 6, 7, 8], max_tokens=16)
        assert [o.token for o in outs] == [5, 6, 7, 8, None]
        assert outs[-1].finished and outs[-1].finish_reason == "stop"
        # max_tokens truncates and reports length
        outs = await collect(eng, [5, 6, 7, 8], max_tokens=2)
        assert [o.token for o in outs] == [5, 6]
        assert outs[-1].finished and outs[-1].finish_reason == "length"

    asyncio.run(run())


def test_adapter_batched_yield_and_finish_reason():
    eng = ExternalTokenEngine("tests.test_external_engine:ext_batched_stop")

    async def run():
        outs = await collect(eng, [1])
        assert [o.token for o in outs] == [101, 102, 103]
        assert outs[-1].finished and outs[-1].finish_reason == "stop"
        # an engine that never yields still terminates the stream cleanly
        outs = await collect(ExternalTokenEngine(ext_empty), [1])
        assert [o.token for o in outs] == [None]
        assert outs[-1].finished

    asyncio.run(run())


def test_adapter_truncation_overrides_user_stop_reason():
    """max_tokens cutting an item MID-delivery is a truncation: the stream
    must report finish_reason="length" even though the truncated item carried
    a user finish_reason="stop" (ADVICE r5 regression)."""
    eng = ExternalTokenEngine("tests.test_external_engine:ext_multi_stop")

    async def run():
        # the engine yields ONE item {[201, 202, 203], stop}; max_tokens=2
        # truncates it mid-delivery -> "length", not the item's "stop"
        outs = await collect(eng, [1], max_tokens=2)
        assert [o.token for o in outs] == [201, 202]
        assert outs[-1].finished and outs[-1].finish_reason == "length"

        # max_tokens=3 lands exactly on the item's final token: the item was
        # fully delivered, so the user's "stop" stands
        outs = await collect(eng, [1], max_tokens=3)
        assert [o.token for o in outs] == [201, 202, 203]
        assert outs[-1].finished and outs[-1].finish_reason == "stop"

    asyncio.run(run())


def test_spec_validation():
    with pytest.raises(ValueError, match="module:function"):
        resolve_spec("no-colon")
    with pytest.raises(ModuleNotFoundError):
        ExternalTokenEngine("definitely_not_a_module:fn")
    with pytest.raises(TypeError, match="async generator"):
        ExternalTokenEngine("tests.test_external_engine:not_an_async_gen")


def test_cli_dispatch_builds_external_engine():
    from types import SimpleNamespace

    from dynamo_tpu.launch._run_impl import _build_engine

    args = SimpleNamespace(
        output="pytok:tests.test_external_engine:ext_echo", model=None,
    )
    eng = asyncio.run(_build_engine(args))
    assert isinstance(eng, ExternalTokenEngine)

    async def run():
        outs = await collect(eng, [9, 10])
        assert [o.token for o in outs] == [9, 10, None]

    asyncio.run(run())


def test_external_engine_behind_full_serving_graph():
    """The full distributed graph — HTTP frontend -> processor (router) ->
    worker — with the EXTERNAL engine in the worker slot: the engine-agnostic
    serving identity of the reference, proven end-to-end."""
    from dynamo_tpu.cplane.broker import Broker
    from dynamo_tpu.components.frontend import FrontendService
    from dynamo_tpu.components.processor import ProcessorService
    from dynamo_tpu.components.worker import WorkerService
    from dynamo_tpu.frontends.pipeline import card_for_model
    from dynamo_tpu.llm.model_registry import ModelEntry, register_model
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    from tests.test_engine import tiny_engine_config

    NS = "ext"

    async def run():
        broker = Broker()
        bport = await broker.start()
        addr = f"127.0.0.1:{bport}"
        worker_rt = DistributedRuntime(cplane_address=addr)
        await worker_rt.connect()
        proc_rt = DistributedRuntime(cplane_address=addr)
        await proc_rt.connect()
        front_rt = DistributedRuntime(cplane_address=addr)
        await front_rt.connect()
        cleanups = []
        try:
            card = card_for_model("tiny")
            worker = WorkerService(
                worker_rt, NS, "backend", card, tiny_engine_config(),
                register=False,
                engine_factory=lambda sink: ExternalTokenEngine(
                    "tests.test_external_engine:ext_echo"
                ),
            )
            await worker.start()
            cleanups.append(worker.stop)
            processor = ProcessorService(
                proc_rt, NS, worker_component="backend", kv_block_size=4,
                routing="round_robin",
            )
            await processor.start()
            cleanups.append(processor.stop)
            entry = ModelEntry(
                name="tiny",
                endpoint=f"dyn://{NS}.processor.generate",
                model_type="chat",
                card=card,
            )
            await register_model(front_rt.cplane, entry)
            frontend = FrontendService(front_rt, host="127.0.0.1", port=0)
            port = await frontend.start()
            cleanups.append(frontend.stop)
            url = f"http://127.0.0.1:{port}"
            body = {
                "model": "tiny",
                "messages": [{"role": "user", "content": "external hello"}],
                "max_tokens": 6,
                "temperature": 0,
            }
            async with aiohttp.ClientSession() as s:
                async with s.post(url + "/v1/chat/completions", json=body) as resp:
                    assert resp.status == 200
                    out = await resp.json()
            assert out["usage"]["completion_tokens"] == 6
            assert out["choices"][0]["message"]["content"] != ""
            # streaming leg
            texts = []
            async with aiohttp.ClientSession() as s:
                async with s.post(
                    url + "/v1/chat/completions", json={**body, "stream": True}
                ) as resp:
                    assert resp.status == 200
                    async for line in resp.content:
                        line = line.decode().strip()
                        if line.startswith("data:"):
                            data = line[5:].strip()
                            if data == "[DONE]":
                                break
                            chunk = json.loads(data)
                            d = chunk["choices"][0]["delta"]
                            if d.get("content"):
                                texts.append(d["content"])
            assert "".join(texts) != ""
        finally:
            for stop in reversed(cleanups):
                await stop()
            for rt in (worker_rt, proc_rt, front_rt):
                await rt._shutdown_hook()
            await broker.stop()

    asyncio.run(run())
