"""Trace-replay harness + goodput plane: determinism contract, scenario
compilation, goodput/SLO accounting units, CLI smoke, and the engine
loopback (replay outcomes vs the scheduler's own StageStats)."""

import asyncio
import json

import pytest

from dynamo_tpu.loadgen import BUILTIN_SCENARIOS, ScenarioSpec, compile_trace, dumps_jsonl, load_scenario, load_scenarios_yaml, read_jsonl, trace_digest, write_jsonl
from dynamo_tpu.loadgen.__main__ import main as loadgen_main
from dynamo_tpu.loadgen.replay import ReplayMetrics
from dynamo_tpu.loadgen.report import render_report
from dynamo_tpu.utils.goodput import (
    GoodputTracker,
    RequestOutcome,
    outcome_meets,
    percentile,
    summarize_outcomes,
)
from dynamo_tpu.utils.prometheus import check_exposition
from dynamo_tpu.utils.slo import SloTracker


# ---------------- determinism contract ----------------


@pytest.mark.parametrize("name", sorted(BUILTIN_SCENARIOS))
def test_trace_byte_identical_for_same_seed(name):
    """Same scenario spec + seed -> byte-identical trace JSONL AND identical
    per-request schedule (the acceptance criterion's determinism contract)."""
    spec = load_scenario(name)
    t1, t2 = compile_trace(spec), compile_trace(spec)
    assert dumps_jsonl(t1) == dumps_jsonl(t2)
    assert [(r.at_s, r.request_id) for r in t1] == [(r.at_s, r.request_id) for r in t2]
    # a different seed perturbs the trace (the stream is actually seeded)
    assert dumps_jsonl(t1) != dumps_jsonl(compile_trace(spec.replace(seed=spec.seed + 1)))


def test_trace_jsonl_roundtrip(tmp_path):
    spec = load_scenario("lora_churn", num_requests=16)
    trace = compile_trace(spec)
    path = tmp_path / "t.jsonl"
    write_jsonl(trace, path)
    back = read_jsonl(path)
    assert trace_digest(back) == trace_digest(trace)
    assert back[0].adapter in spec.adapters or back[0].adapter == ""


def test_arrivals_sorted_and_positive():
    for name in BUILTIN_SCENARIOS:
        trace = compile_trace(load_scenario(name))
        ats = [r.at_s for r in trace]
        assert ats == sorted(ats)
        assert all(a >= 0 for a in ats)


def test_lengths_respect_bounds():
    spec = load_scenario("bursty_chat", num_requests=128)
    for r in compile_trace(spec):
        assert spec.isl_min <= len(r.token_ids) <= spec.isl_max
        assert spec.osl_min <= r.max_tokens <= spec.osl_max


def test_zipf_adapter_skew():
    """The zipf draw must actually make adapter 0 hot and the tail cold."""
    spec = load_scenario("lora_churn", num_requests=256, seed=3)
    counts: dict = {}
    for r in compile_trace(spec):
        if r.adapter:
            counts[r.adapter] = counts.get(r.adapter, 0) + 1
    assert counts[spec.adapters[0]] > counts[spec.adapters[-1]]


def test_shared_prefix_sessions():
    spec = load_scenario("long_context_sessions", num_requests=12)
    trace = compile_trace(spec)
    by_session: dict = {}
    for r in trace:
        assert r.session
        by_session.setdefault(r.session, []).append(r.token_ids)
    assert len(by_session) > 1
    for prompts in by_session.values():
        prefix = prompts[0][: spec.shared_prefix_len]
        assert all(p[: spec.shared_prefix_len] == prefix for p in prompts)
    # distinct sessions have distinct prefixes
    prefixes = {tuple(p[0][: spec.shared_prefix_len]) for p in by_session.values()}
    assert len(prefixes) == len(by_session)


def test_parked_sessions_turns_extend_history():
    """Multi-turn parked conversations: each turn's prompt strictly extends
    the previous turn's (the shape that makes resumes pure prefix hits down
    the KV tier ladder), and consecutive turns are park_s apart."""
    spec = load_scenario("parked_sessions", num_requests=4)
    assert spec.session_turns > 1 and spec.park_s > 0
    trace = compile_trace(spec)
    assert len(trace) == 4 * spec.session_turns
    by_conv: dict = {}
    for r in trace:
        assert r.session
        by_conv.setdefault(r.session, []).append(r)
    assert len(by_conv) == 4
    for turns in by_conv.values():
        turns.sort(key=lambda r: r.at_s)
        assert len(turns) == spec.session_turns
        for a, b in zip(turns, turns[1:]):
            assert b.token_ids[: len(a.token_ids)] == a.token_ids
            assert len(b.token_ids) > len(a.token_ids)
            assert b.at_s - a.at_s == pytest.approx(spec.park_s, abs=1e-5)
    # single-turn scenarios never take the parked branch
    single = compile_trace(load_scenario("bursty_chat", num_requests=4))
    assert all("-t" not in r.request_id for r in single)


def test_mm_trace_carries_image_specs():
    trace = compile_trace(load_scenario("mm_vl", num_requests=4))
    assert all(r.image is not None for r in trace)
    assert all(set(r.image) == {"seed", "h", "w"} for r in trace)


def test_scenario_validation():
    with pytest.raises(ValueError):
        ScenarioSpec(name="x", arrival="nope")
    with pytest.raises(ValueError):
        ScenarioSpec(name="x", session_groups=2)  # no shared_prefix_len
    with pytest.raises(ValueError):
        load_scenario("not-a-scenario")


def test_scenario_yaml(tmp_path):
    path = tmp_path / "scenarios.yaml"
    path.write_text(
        "scenarios:\n"
        "  - bursty_chat\n"
        "  - scenario: lora_churn\n"
        "    num_requests: 7\n"
        "    seed: 9\n"
    )
    specs = load_scenarios_yaml(path)
    assert [s.name for s in specs] == ["bursty_chat", "lora_churn"]
    assert specs[1].num_requests == 7 and specs[1].seed == 9


# ---------------- CLI (the tier-1 --dry-run smoke) ----------------


def test_cli_dry_run_smoke(capsys):
    assert loadgen_main(["--scenario", "bursty_chat", "--dry-run"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["scenario"] == "bursty_chat"
    assert doc["requests"] == BUILTIN_SCENARIOS["bursty_chat"].num_requests
    assert len(doc["digest"]) == 64


def test_cli_dry_run_all_scenarios(capsys):
    assert loadgen_main(["--dry-run", "--num-requests", "8"]) == 0
    out = capsys.readouterr().out
    for name in BUILTIN_SCENARIOS:
        assert name in out


def test_cli_list(capsys):
    assert loadgen_main(["--list"]) == 0
    assert "bursty_chat" in capsys.readouterr().out


def test_cli_out_writes_trace(tmp_path, capsys):
    path = tmp_path / "trace.jsonl"
    assert loadgen_main([
        "--scenario", "diurnal_chat", "--seed", "5", "--out", str(path),
        "--dry-run",
    ]) == 0
    trace = read_jsonl(path)
    assert trace_digest(trace) == trace_digest(
        compile_trace(load_scenario("diurnal_chat", seed=5))
    )


# ---------------- goodput plane units ----------------


def test_percentile_edge_cases():
    assert percentile([], 99) is None
    assert percentile([0.5], 50) == 0.5
    assert percentile([0.5], 99) == 0.5  # single sample IS every percentile


def test_outcome_meets_budgets():
    ok = RequestOutcome("r", ttft_s=0.1, itl_s=(0.01, 0.02), output_tokens=3)
    assert outcome_meets(ok, ttft_budget_s=0.5, itl_budget_s=0.05)
    assert not outcome_meets(ok, ttft_budget_s=0.05)  # ttft blown
    assert not outcome_meets(ok, itl_budget_s=0.015)  # itl p99 blown
    assert outcome_meets(ok)  # untargeted never fails
    assert not outcome_meets(RequestOutcome("e", error=True))
    # per-outcome budget overrides the default
    strict = RequestOutcome("s", ttft_s=0.1, ttft_budget_s=0.05)
    assert not outcome_meets(strict, ttft_budget_s=10.0)


def test_goodput_tracker_windows_and_totals():
    clock = [0.0]
    gp = GoodputTracker(ttft_budget_s=0.5, window_s=10.0, clock=lambda: clock[0])
    assert gp.snapshot()["goodput"] is None  # empty window: None, not 1.0
    gp.observe(RequestOutcome("a", scenario="s1", ttft_s=0.1, output_tokens=4))
    gp.observe(RequestOutcome("b", scenario="s1", ttft_s=0.9, output_tokens=4))
    gp.observe(RequestOutcome("c", scenario="s2", tenant="t1", error=True))
    snap = gp.snapshot()
    assert snap["goodput"] == pytest.approx(1 / 3, abs=1e-4)
    assert snap["scenarios"]["s1"]["goodput"] == 0.5
    assert snap["scenarios"]["s2"]["errors"] == 1
    assert snap["tenants"]["t1"]["goodput"] == 0.0
    # window expiry drops the samples but lifetime counters survive
    clock[0] = 100.0
    snap = gp.snapshot()
    assert snap["goodput"] is None
    assert snap["scenarios"]["s1"]["lifetime"] == {"met": 1, "missed": 1, "errors": 0}
    assert check_exposition(gp.render_metrics()) == []


def test_summarize_outcomes():
    outs = [
        RequestOutcome("a", ttft_s=0.1, itl_s=(0.01,), output_tokens=10),
        RequestOutcome("b", ttft_s=0.3, itl_s=(0.03,), output_tokens=10),
    ]
    s = summarize_outcomes(outs, wall_s=2.0, ttft_budget_s=0.2)
    assert s["requests"] == 2 and s["goodput"] == 0.5
    assert s["tok_s"] == 10.0
    assert s["ttft_p99_ms"] == pytest.approx(300.0)
    assert s["itl_p99_ms"] == pytest.approx(30.0)
    empty = summarize_outcomes([])
    assert empty["goodput"] is None and empty["ttft_p99_ms"] is None


# ---------------- SloTracker hardening ----------------


def test_slo_empty_window_percentiles_none():
    slo = SloTracker({"ttft": 0.5})
    s = slo.metric_state("ttft")
    assert s["count"] == 0
    assert s["p50_ms"] is None and s["p99_ms"] is None
    assert s["error_budget"] == 1.0 and s["ok"]
    # the render stays NaN-free and conformant with zero samples
    text = slo.render_metrics()
    assert "NaN" not in text and "None" not in text
    assert check_exposition(text) == []


def test_slo_single_sample_quantiles():
    slo = SloTracker({"ttft": 0.5})
    slo.observe("ttft", 0.2)
    s = slo.metric_state("ttft")
    assert s["p50_ms"] == s["p99_ms"] == pytest.approx(200.0)
    assert check_exposition(slo.render_metrics()) == []


def test_slo_window_expiry_renders_clean():
    clock = [0.0]
    slo = SloTracker({"ttft": 0.5}, window_s=10.0, clock=lambda: clock[0])
    slo.observe("ttft", 0.9)
    clock[0] = 100.0  # sample ages out of the window
    s = slo.metric_state("ttft")
    assert s["count"] == 0 and s["p99_ms"] is None
    assert s["violations_total"] == 1  # lifetime counter survives
    assert check_exposition(slo.render_metrics()) == []


def test_slo_tenant_series():
    slo = SloTracker({"ttft": 0.5})
    slo.observe("ttft", 0.1, tenant="a")
    slo.observe("ttft", 0.9, tenant="b")
    snap = slo.snapshot()
    # tenant observations also feed the aggregate
    assert snap["metrics"]["ttft"]["count"] == 2
    assert snap["tenants"]["a"]["ttft"]["violations"] == 0
    assert snap["tenants"]["b"]["ttft"]["violations"] == 1
    text = slo.render_metrics()
    assert 'tenant="b"' in text
    assert check_exposition(text) == []


# ---------------- replay metrics / report renderers ----------------


def test_replay_metrics_exposition():
    m = ReplayMetrics()
    m.submitted()
    m.observe_lag(0.003)
    m.finished("bursty_chat", 12, error=False)
    text = m.render_metrics()
    assert 'dynamo_replay_requests_total{result="ok",scenario="bursty_chat"} 1' in text
    assert check_exposition(text) == []
    assert m.max_lag_s == pytest.approx(0.003)


def test_render_report_pure():
    rep = {
        "scenario": "bursty_chat", "requests": 8, "errors": 0, "goodput": 0.875,
        "ttft_p50_ms": 120.0, "ttft_p99_ms": 480.0, "itl_p50_ms": 8.0,
        "itl_p99_ms": 35.0, "tok_s": 512.3, "schedule_lag_max_s": 0.004,
        "ttft_budget_ms": 2000.0, "itl_budget_ms": 200.0,
    }
    text = render_report([rep])
    assert "bursty_chat" in text and "87.5%" in text and "GOODPUT" in text
    assert "(no scenarios replayed)" in render_report([])


def test_dynotop_goodput_column():
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "dynotop_gp", Path(__file__).resolve().parent.parent / "tools" / "dynotop.py"
    )
    dynotop = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(dynotop)
    doc = {
        "summary": {"workers": 1, "servable": 1, "stale": 0, "unservable": 0},
        "workers": [{
            "worker_id": "ab", "health": {"state": "ready", "heartbeat_age_s": 0.1},
            "kv_metrics": {"request_active_slots": 1, "request_total_slots": 8,
                           "kv_active_blocks": 2, "kv_total_blocks": 10,
                           "num_requests_waiting": 0},
            "resources": {}, "last_seen_s": 0.2, "missed_scrapes": 0,
            "goodput": {"goodput": 0.98, "requests": 124},
        }],
    }
    text = dynotop.render_status(doc)
    assert "GOODPUT" in text
    assert "98% (124)" in text
    # a worker with an empty goodput window shows "-"
    doc["workers"][0]["goodput"] = {"goodput": None, "requests": 0}
    assert "98%" not in dynotop.render_status(doc)


# ---------------- engine loopback (CPU, tiny model) ----------------


@pytest.fixture(scope="module")
def replay_engine_fixture():
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine

    cfg = EngineConfig(
        model_id="tiny", page_size=4, num_pages=256, max_seqs=4,
        max_model_len=160, prefill_buckets=(16, 32, 64), decode_steps=4,
        pipeline_depth=2,
    )
    eng = AsyncJaxEngine(cfg)
    loop = asyncio.new_event_loop()
    loop.run_until_complete(eng.start())
    yield eng, loop
    loop.run_until_complete(eng.shutdown())
    loop.close()


@pytest.mark.slow
def test_replay_loopback_outcomes_match_stage_stats(replay_engine_fixture):
    """End-to-end acceptance leg: a seeded replay against a tiny engine
    produces client-side RequestOutcomes whose TTFT/queue-wait totals agree
    with the engine's own StageStats histograms within tolerance, and the
    engine-side goodput plane recorded the same request set."""
    from dynamo_tpu.loadgen.replay import replay_engine
    from dynamo_tpu.utils.goodput import GoodputTracker

    eng, loop = replay_engine_fixture
    spec = load_scenario(
        "bursty_chat", num_requests=8,
    ).replace(isl_max=48, osl_dist="fixed", osl_mean=10, osl_max=10,
              rate_rps=32.0, slo_ttft_ms=60000.0, slo_itl_ms=60000.0)
    trace = compile_trace(spec)
    # warm the executables out of the measurement (cold XLA compiles would
    # otherwise dominate the client-vs-engine agreement check)
    warm = compile_trace(spec.replace(seed=99, num_requests=2))
    loop.run_until_complete(replay_engine(eng, warm, spec=spec, speed=100.0))

    base_ttft_n = eng.scheduler.stage.ttft_n
    base_ttft_s = eng.scheduler.stage.ttft_s
    gp = GoodputTracker()
    report = loop.run_until_complete(
        replay_engine(eng, trace, spec=spec, speed=4.0, goodput=gp)
    )
    assert report["requests"] == 8 and report["errors"] == 0
    assert report["goodput"] == 1.0  # 60s budgets: everything meets
    assert report["output_tokens"] == 80  # fixed OSL, ignore_eos
    # client TTFT mean vs the engine's StageStats TTFT mean: same event,
    # measured from the two ends of the output queue — they must agree to
    # within a generous cross-thread-delivery tolerance
    outcomes = [o for o in report["outcomes"]]
    client_mean = sum(o["ttft_ms"] for o in outcomes) / len(outcomes)
    eng_n = eng.scheduler.stage.ttft_n - base_ttft_n
    eng_mean = (eng.scheduler.stage.ttft_s - base_ttft_s) / max(1, eng_n) * 1e3
    assert eng_n == 8
    assert client_mean == pytest.approx(eng_mean, rel=0.5, abs=50.0)
    # client TTFT can never lead the engine's (the engine materializes first)
    assert client_mean >= eng_mean * 0.95
    # the engine-side outcome plane saw the same scenario-tagged requests
    snap = eng.goodput.snapshot()
    assert snap["scenarios"]["bursty_chat"]["lifetime"]["met"] >= 8
    # queue-wait outcomes populated from the scheduler tap
    eng_outcomes = snap["scenarios"]["bursty_chat"]
    assert eng_outcomes["requests"] >= 8


@pytest.mark.slow
def test_replay_tenant_outcomes_reach_engine_slo(replay_engine_fixture):
    """Tenant tags on replayed requests flow scheduler -> SloTracker tenant
    series and the goodput tenant breakdown."""
    from dynamo_tpu.loadgen.replay import replay_engine

    eng, loop = replay_engine_fixture
    spec = load_scenario("lora_churn", num_requests=6).replace(
        adapters=(), base_model_share=1.0, isl_max=32,
        osl_dist="fixed", osl_mean=4, osl_max=4, rate_rps=64.0,
        slo_ttft_ms=None, slo_itl_ms=None,
    )
    trace = compile_trace(spec)
    assert any(t.tenant for t in trace)
    loop.run_until_complete(replay_engine(eng, trace, spec=spec, speed=10.0))
    slo_snap = eng.slo.snapshot()
    assert set(slo_snap.get("tenants", {})) >= {t.tenant for t in trace if t.tenant}
    gp_snap = eng.goodput.snapshot()
    assert set(gp_snap["tenants"]) >= {t.tenant for t in trace if t.tenant}


# ---------------- 128K deep-end arm (PR 8/11 follow-up) ----------------


def test_long_context_128k_builtin_depth():
    """The 128K builtin compiles to genuinely deep, session-grouped prompts
    that fit the 131072 serving ceiling with OSL headroom (the byte-identity
    determinism contract is covered by the parametrized builtin tests)."""
    spec = load_scenario("long_context_128k")
    trace = compile_trace(spec)
    lens = [len(r.token_ids) for r in trace]
    assert max(lens) + spec.osl_max <= 131072
    assert max(lens) >= 65536 + spec.isl_min  # the deep end is actually deep
    assert all(r.session for r in trace)
    by_session: dict = {}
    for r in trace:
        by_session.setdefault(r.session, []).append(r.token_ids)
    for prompts in by_session.values():
        prefix = prompts[0][: spec.shared_prefix_len]
        assert all(p[: spec.shared_prefix_len] == prefix for p in prompts)


@pytest.mark.slow
def test_long_context_128k_scaled_replay():
    """The deep end priced by the SAME goodput plane as every other
    scenario: a depth-scaled (1/32) replay of the 128K builtin against a
    tiny engine meets its budgets and engages the wide table rungs. The
    driver's TPU run replays the builtin at full depth via
    `python -m dynamo_tpu.loadgen --scenario long_context_128k`."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.loadgen.replay import replay_engine

    spec = load_scenario("long_context_128k", num_requests=3, rate_rps=2.0).replace(
        shared_prefix_len=2048, isl_mean=1024, isl_sigma=0.3, isl_min=128,
        isl_max=2032, vocab=256, slo_ttft_ms=60000.0, slo_itl_ms=60000.0,
    )
    trace = compile_trace(spec)
    assert max(len(r.token_ids) for r in trace) > 2048  # still the deep shape
    cfg = EngineConfig(
        model_id="tiny", page_size=4, num_pages=4096, max_seqs=2,
        max_model_len=4608, prefill_buckets=(16, 32, 64, 128, 256),
        decode_steps=4, pipeline_depth=2,
    )
    eng = AsyncJaxEngine(cfg)
    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(eng.start())
        gp = GoodputTracker()
        report = loop.run_until_complete(
            replay_engine(eng, trace, spec=spec, speed=8.0, goodput=gp)
        )
        assert report["requests"] == 3 and report["errors"] == 0
        assert report["goodput"] == 1.0
        # deep prompts dispatched on wide page-table ladder rungs
        assert max(eng.scheduler.table_dispatches) >= 512
        # priced under its own scenario key in the goodput plane
        assert "long_context_128k" in gp.snapshot()["scenarios"]
    finally:
        loop.run_until_complete(eng.shutdown())
        loop.close()
