"""Metrics component scraping mock workers (reference: components/metrics
tests with mock_worker.rs)."""

import asyncio

import aiohttp

from dynamo_tpu.cplane.broker import Broker
from dynamo_tpu.components.metrics import MetricsService
from dynamo_tpu.llm.kv_router.publisher import KvMetricsPublisher
from dynamo_tpu.llm.kv_router.router import KV_HIT_RATE_SUBJECT
from dynamo_tpu.runtime.distributed import DistributedRuntime


def test_metrics_component_scrape_and_prometheus():
    async def body():
        broker = Broker()
        port = await broker.start()
        addr = f"127.0.0.1:{port}"

        # two mock workers publishing ForwardPassMetrics via stats handlers
        workers = []
        for i in range(2):
            rt = DistributedRuntime(cplane_address=addr)
            await rt.connect()
            pub = KvMetricsPublisher(
                lambda i=i: {
                    "request_active_slots": i + 1,
                    "request_total_slots": 8,
                    "kv_active_blocks": 10 * (i + 1),
                    "kv_total_blocks": 100,
                    "gpu_prefix_cache_hit_rate": 0.5,
                }
            )

            async def handler(req):
                yield {"ok": True}

            ep = rt.namespace("m").component("backend").endpoint("generate")
            await ep.serve_endpoint(handler, metrics=pub.stats_handler)
            workers.append(rt)

        mon_rt = DistributedRuntime(cplane_address=addr)
        await mon_rt.connect()
        svc = MetricsService(mon_rt, "m", "backend", host="127.0.0.1", port=0, interval=0.2)
        mport = await svc.start()

        # emit a hit-rate event like the KV scheduler does
        await mon_rt.cplane.publish(
            f"m.{KV_HIT_RATE_SUBJECT}", {"isl_blocks": 10, "overlap_blocks": 4}
        )
        await asyncio.sleep(0.6)  # let a scrape cycle run

        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{mport}/metrics") as resp:
                assert resp.status == 200
                text = await resp.text()

        try:
            assert 'llm_kv_workers{component="backend",namespace="m"} 2' in text
            assert "llm_kv_kv_active_blocks_avg" in text
            assert "llm_kv_request_active_slots_max" in text
            assert "llm_kv_hit_rate_percent" in text and "40.0" in text
            assert text.count('worker_id="') >= 2
        finally:
            await svc.stop()
            for rt in workers:
                await rt._shutdown_hook()
            await mon_rt._shutdown_hook()
            await broker.stop()

    asyncio.run(body())
