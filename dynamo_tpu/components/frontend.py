"""Frontend service: OpenAI HTTP + model discovery.

Watches the control-plane ``models/`` prefix; for every registered ModelEntry
it builds a remote pipeline (local preprocessor from the model card + a remote
backend that streams from the entry's endpoint) and attaches it to the HTTP
service. Models detach when their registration disappears.

Mirrors the reference standalone http frontend + discovery watcher
(reference: components/http/src/main.rs:29-101, lib/llm/src/http/service/
discovery.rs:1-145).
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Optional


from dynamo_tpu.llm.http.service import HttpService, ModelPipeline
from dynamo_tpu.llm.model_registry import MODELS_PREFIX, ModelEntry
from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
from dynamo_tpu.llm.protocols.common import BackendOutput, PreprocessedRequest
from dynamo_tpu.llm.tokenizer import get_tokenizer
from dynamo_tpu.utils import get_logger

log = get_logger("components.frontend")


class RemoteBackend:
    """Backend facade that streams BackendOutputs from a runtime endpoint."""

    def __init__(self, drt, endpoint: str):
        self.drt = drt
        self.endpoint = endpoint
        self._client = None

    async def _ensure_client(self, wait: bool = True):
        if self._client is None:
            self._client = await self.drt.endpoint_client(self.endpoint)
        if wait and not self._client.instance_ids():
            await self._client.wait_for_instances(timeout=10)
        return self._client

    async def live_instances(self) -> int:
        """Instance count behind this backend's endpoint right now (starts
        the discovery watcher without blocking on instances appearing) — the
        frontend readiness probe's downstream-health signal."""
        client = await self._ensure_client(wait=False)
        return len(client.instance_ids())

    async def generate(self, request: PreprocessedRequest) -> AsyncIterator[BackendOutput]:
        client = await self._ensure_client()
        stream = await client.random(request.to_wire())
        async for item in stream:
            yield BackendOutput(
                request_id=item.get("request_id", request.request_id),
                text=item.get("text", ""),
                token_ids=list(item.get("token_ids", [])),
                finish_reason=item.get("finish_reason"),
                cumulative_tokens=item.get("cumulative_tokens", 0),
                cached_tokens=item.get("cached_tokens", 0),
                logprobs=item.get("logprobs"),
            )


class FrontendService:
    def __init__(self, drt, host: str = "0.0.0.0", port: int = 8080):
        self.drt = drt
        self.service = HttpService(host=host, port=port, readiness=self._readiness)
        self._watcher = None
        self._watch_task: Optional[asyncio.Task] = None
        self._entries: dict[str, ModelEntry] = {}
        self._backends: dict[str, RemoteBackend] = {}

    async def _readiness(self) -> tuple:
        """/ready provider: a frontend is ready when at least one attached
        model has a live worker instance behind its endpoint. /live stays a
        static 200 regardless — a frontend whose whole pool died is alive
        but must be pulled from rotation."""
        if not self._backends:
            return False, {"reason": "no models attached"}
        per_model = {}
        any_live = False
        for name, backend in sorted(self._backends.items()):
            try:
                n = await backend.live_instances()
            except Exception:
                n = 0
            per_model[name] = n
            any_live = any_live or n > 0
        detail = {"instances": per_model}
        if not any_live:
            detail["reason"] = "no live worker instances for any model"
        return any_live, detail

    async def start(self) -> int:
        port = await self.service.start()
        self._watcher = await self.drt.cplane.kv_get_and_watch_prefix(MODELS_PREFIX + "/")
        for item in self._watcher.initial:
            self._attach(ModelEntry.from_wire(item.value))
        self._watch_task = asyncio.create_task(self._watch_loop())
        return port

    async def stop(self) -> None:
        if self._watch_task:
            self._watch_task.cancel()
        if self._watcher:
            try:
                await self._watcher.stop()
            except Exception:
                pass
        await self.service.stop()

    async def _watch_loop(self) -> None:
        try:
            async for ev in self._watcher.events():
                if ev.kind == "put":
                    self._attach(ModelEntry.from_wire(ev.value))
                elif ev.kind == "delete":
                    name = ev.key.rsplit("/", 1)[1]
                    entry = self._entries.pop(name, None)
                    self._backends.pop(name, None)
                    if entry is not None:
                        self.service.manager.remove(entry.name)
                        log.info("model detached: %s", name)
        except asyncio.CancelledError:
            pass

    def _attach(self, entry: ModelEntry) -> None:
        card = entry.card
        if card is None:
            log.warning("model %s has no deployment card; skipping", entry.name)
            return
        tokenizer = get_tokenizer(card.tokenizer)
        preprocessor = OpenAIPreprocessor(
            tokenizer, model_name=entry.name, max_model_len=card.context_length,
            mm=card.mm,
        )
        backend = RemoteBackend(self.drt, entry.endpoint)
        self.service.manager.add(
            ModelPipeline(entry.name, preprocessor, backend, model_type="both")
        )
        self._entries[entry.name] = entry
        self._backends[entry.name] = backend
        log.info("model attached: %s -> %s", entry.name, entry.endpoint)


async def _main(args) -> None:
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    drt = DistributedRuntime(cplane_address=args.cplane)
    await drt.connect()
    frontend = FrontendService(drt, host=args.host, port=args.port)
    port = await frontend.start()
    log.info("standalone frontend on :%d", port)
    await drt.runtime.cancellation.cancelled()


def main(argv=None) -> None:
    """Standalone OpenAI frontend (reference: components/http/src/main.rs)."""
    import argparse

    p = argparse.ArgumentParser(description=main.__doc__)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--cplane", default=None)
    asyncio.run(_main(p.parse_args(argv)))


if __name__ == "__main__":
    main()
