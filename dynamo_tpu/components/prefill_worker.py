"""Standalone prefill worker: a JAX engine consuming the remote-prefill work
queue for a model (reference: examples/llm/components/prefill_worker.py — the
NATS-JetStream prefill consumer loop).

    python -m dynamo_tpu.components.prefill_worker /models/llama-3-8b \
        --namespace dynamo --tp 4

The SDK graph variant lives in examples/graphs/disagg.py; this module is the
plain-process deployment entry (helm: prefill-worker.yaml).
"""

from __future__ import annotations

import argparse
import asyncio

from dynamo_tpu.utils import get_logger

log = get_logger("components.prefill")


async def _main(args) -> None:
    from dynamo_tpu.parallel.mesh import init_multihost
    from dynamo_tpu.utils.xla_cache import enable_compilation_cache

    enable_compilation_cache()  # engine restarts reload executables from disk
    init_multihost()  # no-op unless DYNTPU_COORDINATOR is set
    from dynamo_tpu.disagg.prefill_worker import PrefillWorker
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.models.registry import is_tiny_family
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    drt = DistributedRuntime(cplane_address=args.cplane)
    await drt.connect()

    if is_tiny_family(args.model):
        card = ModelDeploymentCard.for_tiny(args.model)
    else:
        card = ModelDeploymentCard.from_local_path(args.model)
    engine = AsyncJaxEngine(
        EngineConfig.for_model(
            args.model,
            tp=args.tp,
            num_pages=args.num_pages,
            max_seqs=args.max_seqs,
            page_size=args.page_size,
            max_model_len=args.max_model_len,
            kv_cache_dtype=getattr(args, "kv_cache_dtype", None),
            kv_stream=not args.no_kv_stream,
            kv_stream_lanes=args.kv_stream_lanes,
            slo_ttft_ms=args.slo_ttft_ms,
            slo_itl_ms=args.slo_itl_ms,
            prefill_pipeline_depth=getattr(
                args, "prefill_pipeline_depth", None
            ) or EngineConfig.prefill_pipeline_depth,
        )
    )
    await engine.start()
    if engine.config.prefix_fetch and not getattr(args, "no_prefix_fetch", False):
        # fleet prefix cache, prefill side (ROADMAP item 3 follow-up): when a
        # queued request carries a router-attached holder, the prefill engine
        # PULLS the prefix over the dataplane before recomputing it (same
        # timeout -> recompute fallback as decode-side FETCHING_KV)
        from dynamo_tpu.disagg.prefix_fetch import PrefixFetchClient

        engine.attach_prefix_fetch(PrefixFetchClient(
            asyncio.get_running_loop(),
            timeout_s=engine.config.prefix_fetch_timeout_s,
        ))
    worker = PrefillWorker(engine, drt, args.namespace, card.display_name)
    await worker.start()

    # fleet-health visibility: the queue consumer itself has no RPC surface,
    # so serve a status endpoint whose stats broadcast carries the engine's
    # health/resource/SLO snapshots. This puts the prefill pool on the same
    # scrape plane as decode workers (/cluster/status, planner replica
    # counting via the instance key this registration creates).
    def _stats() -> dict:
        stats = {
            "kv_metrics": engine.metrics().to_wire(),
            "health": engine.health.snapshot(),
            "resources": engine.resource_snapshot(),
            "slo": engine.slo_snapshot(),
            "prefill": {"completed": worker.completed},
        }
        stage = engine.stage_snapshot()
        if stage:
            stats["stage_seconds"] = stage
        return stats

    async def _status(request: dict):
        yield {"ok": True, "health": engine.health.snapshot()}

    ep = drt.namespace(args.namespace).component(args.component).endpoint("status")
    served = await ep.serve_endpoint(_status, metrics=_stats)

    log.info("prefill worker up: model=%s namespace=%s", card.display_name, args.namespace)
    try:
        while True:
            await asyncio.sleep(3600)
    finally:
        await served.stop()
        await worker.stop()
        await engine.shutdown()


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("model", help="model path or tiny:{...} spec")
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--component", default="prefill-worker",
                   help="component name for the status endpoint (matches the "
                        "planner's prefill pool and /cluster/status scraping)")
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--num-pages", type=int, default=512)
    p.add_argument("--max-seqs", type=int, default=8)
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--max-model-len", type=int, default=2048)
    p.add_argument("--kv-cache-dtype", choices=["bf16", "int8"], default=None,
                   help="KV cache storage dtype: int8 halves attention HBM "
                        "traffic, page capacity, and disagg wire bytes "
                        "(per-page scales ride the part headers)")
    p.add_argument("--prefill-pipeline-depth", type=int, default=None,
                   help="packed prefill calls dispatched ahead of result "
                        "materialization (1 = strict reconcile per call; "
                        "default 2 — a dedicated prefill worker is exactly "
                        "the burst regime dispatch-ahead pays off in)")
    p.add_argument("--kv-stream-lanes", type=int, default=2,
                   help="parallel KV data-plane connections per decode worker "
                        "(chunk-streamed parts stripe across lanes)")
    p.add_argument("--no-kv-stream", action="store_true",
                   help="disable chunk-streamed KV transfer (one monolithic "
                        "post-prefill send per request)")
    p.add_argument("--no-prefix-fetch", action="store_true",
                   help="disable the prefill-side fleet prefix pull (always "
                        "recompute instead of pulling a holder's cached "
                        "prefix over the dataplane)")
    p.add_argument("--slo-ttft-ms", type=float, default=None,
                   help="TTFT SLO target in ms (env DYNTPU_SLO_TTFT_MS)")
    p.add_argument("--slo-itl-ms", type=float, default=None,
                   help="inter-token-latency SLO target in ms (env "
                        "DYNTPU_SLO_ITL_MS)")
    p.add_argument("--cplane", default=None)
    asyncio.run(_main(p.parse_args(argv)))


if __name__ == "__main__":
    main()
