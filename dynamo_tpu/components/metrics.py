"""Standalone metrics component: scrapes ForwardPassMetrics from a component's
workers, aggregates (avg/min/max + KV-hit-rate percent), and exposes
Prometheus plus the fleet-health view.

Mirrors the reference metrics binary (reference: components/metrics/src/
{main.rs:115-272,lib.rs:125-633}); the mock worker analogue lives in
tests (reference: components/metrics/src/bin/mock_worker.rs).

Endpoints:
  - ``/metrics``          federated Prometheus exposition: pool aggregates +
                          per-worker families labeled with worker_id (health
                          state, staleness, resource gauges, stage seconds)
  - ``/cluster/status``   JSON fleet view: per-worker health snapshot,
                          last-seen staleness, gauges, SLO state — the
                          ``tools/dynotop.py`` data source
  - ``/cluster/events``   fleet flight-recorder timeline: every worker's
                          recent journal events merged in (wall, seq) order,
                          filterable with ``?kind=``/``?tenant=``/
                          ``?request=`` query params (utils/events.py)

    python -m dynamo_tpu.components.metrics --namespace dynamo --component backend --port 9091
"""

from __future__ import annotations

import argparse
import asyncio
import time
from typing import Optional

from aiohttp import web

from dynamo_tpu.llm.kv_router.indexer import render_radix_metrics
from dynamo_tpu.llm.kv_router.metrics_aggregator import KvMetricsAggregator
from dynamo_tpu.llm.kv_router.router import KV_HIT_RATE_SUBJECT
from dynamo_tpu.utils import get_logger
from dynamo_tpu.utils.health import STATES, is_snapshot_servable
from dynamo_tpu.utils.prometheus import render_family

log = get_logger("components.metrics")


class MetricsService:
    def __init__(
        self,
        drt,
        namespace: str,
        component: str,
        host: str = "0.0.0.0",
        port: int = 9091,
        interval: float = 2.0,
        max_missed_scrapes: int = 3,
    ):
        self.drt = drt
        self.namespace = namespace
        self.component = component
        self.host = host
        self.port = port
        self.aggregator = KvMetricsAggregator(
            drt.cplane, namespace, component, interval=interval,
            max_missed_scrapes=max_missed_scrapes,
        )
        # cumulative KV hit-rate from router events
        self._isl_blocks = 0
        self._overlap_blocks = 0
        # latest radix-index health the router piggybacked on its hit-rate
        # broadcast (nodes/bytes/evictions/lookup hit counters)
        self._router_radix: Optional[dict] = None
        self._runner: Optional[web.AppRunner] = None

    async def start(self) -> int:
        await self.aggregator.start()
        await self.drt.cplane.subscribe(
            f"{self.namespace}.{KV_HIT_RATE_SUBJECT}", self._on_hit_rate
        )
        app = web.Application()
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/cluster/status", self._cluster_status)
        app.router.add_get("/cluster/events", self._cluster_events)
        app.router.add_get("/cluster/costs", self._cluster_costs)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        log.info("metrics on %s:%d scraping %s/%s", self.host, self.port, self.namespace, self.component)
        return self.port

    async def stop(self) -> None:
        await self.aggregator.stop()
        if self._runner is not None:
            await self._runner.cleanup()

    def _on_hit_rate(self, msg: dict) -> None:
        p = msg["payload"]
        self._isl_blocks += p.get("isl_blocks", 0)
        self._overlap_blocks += p.get("overlap_blocks", 0)
        radix = p.get("radix")
        if isinstance(radix, dict):
            self._router_radix = radix

    # ---------------- fleet status (JSON) ----------------

    def cluster_status(self) -> dict:
        """The ``/cluster/status`` document: per-worker health, staleness,
        gauges, and SLO state plus a fleet summary (dynotop's data source)."""
        now = time.monotonic()
        workers = []
        summary = {
            "workers": 0, "servable": 0, "stale": 0, "unservable": 0,
            # workers mid-drain with live migration in flight (their
            # sequences are moving to peers — disagg/migrate.py)
            "migrating": 0,
        }
        for view in self.aggregator.worker_views():
            health = view.health
            entry = {
                "worker_id": f"{view.instance_id:x}",
                "last_seen_s": round(view.age_s(now), 3),
                "last_seen_wall": view.last_seen_wall,
                "missed_scrapes": view.missed_scrapes,
                "stale": view.stale,
                "servable": view.servable,
                "health": health,
                "kv_metrics": view.data.get("kv_metrics"),
                "resources": view.data.get("resources"),
                "slo": view.data.get("slo"),
                "goodput": view.data.get("goodput"),
                "costs": view.data.get("costs"),
                "stage_seconds": view.data.get("stage_seconds"),
                "disagg": view.data.get("disagg"),
                "events": view.data.get("events"),
            }
            workers.append(entry)
            summary["workers"] += 1
            summary["servable"] += 1 if view.servable else 0
            summary["stale"] += 1 if view.stale else 0
            summary["unservable"] += 0 if is_snapshot_servable(health) else 1
            if (health or {}).get("state") == "migrating":
                summary["migrating"] += 1
        return {
            "namespace": self.namespace,
            "component": self.component,
            "ts": time.time(),
            "scrape_interval_s": self.aggregator.interval,
            "max_missed_scrapes": self.aggregator.max_missed_scrapes,
            "summary": summary,
            "kv_hit_rate": {
                "isl_blocks": self._isl_blocks,
                "overlap_blocks": self._overlap_blocks,
            },
            "router_radix": self._router_radix,
            "workers": workers,
            # merged fleet timeline tail (dynotop's events pane reads this
            # off the one /cluster/status fetch it already makes)
            "recent_events": self.cluster_events(limit=64),
        }

    async def _cluster_status(self, request: web.Request) -> web.Response:
        return web.json_response(self.cluster_status())

    def cluster_events(
        self,
        kind: str = "",
        tenant: str = "",
        request_id: str = "",
        limit: int = 200,
    ) -> list[dict]:
        """The fleet flight-recorder timeline: every worker's recent journal
        events merged in (wall, seq) order, worker-labeled, filterable."""
        from dynamo_tpu.utils import events as events_mod

        merged = events_mod.merge_recent(
            [
                (f"{view.instance_id:x}", view.data.get("events") or {})
                for view in self.aggregator.worker_views()
            ],
            # over-fetch before filtering so a filtered view still fills up
            limit=max(limit, 1000) if (kind or tenant or request_id) else limit,
        )
        if kind:
            merged = [e for e in merged if e.get("kind", "").startswith(kind)]
        if tenant:
            merged = [e for e in merged if e.get("tenant") == tenant]
        if request_id:
            merged = [e for e in merged if e.get("request_id") == request_id]
        return merged[-limit:]

    def cluster_costs(self) -> dict:
        """The ``/cluster/costs`` document: every worker's MeterLedger
        snapshot (utils/metering.py) merged into fleet-wide per-tenant burn —
        device-seconds (total and by dispatch kind), per-tier KV byte-seconds
        and residency, queued-seconds, and the admitted-vs-consumed token
        counters. Additive merge: each field is a cumulative counter or a
        current level on exactly one worker, so the fleet view is the sum.
        The planner reads the same merge as its per-tenant demand signal."""
        tenants: dict[str, dict] = {}
        adapters: dict[str, float] = {}
        tiers: dict[str, dict] = {}
        per_worker = []
        for view in self.aggregator.worker_views():
            costs = view.data.get("costs") or {}
            if not costs:
                continue
            per_worker.append({
                "worker_id": f"{view.instance_id:x}",
                "device_s_total": costs.get("device_s_total", 0.0),
                "top_tenant": costs.get("top_tenant", ""),
            })
            for tenant, row in (costs.get("tenants") or {}).items():
                agg = tenants.setdefault(tenant, {
                    "device_s": 0.0, "by_kind": {}, "kv_byte_s": {},
                    "kv_resident_bytes": {}, "queued_s": 0.0, "tokens": {},
                })
                agg["device_s"] = round(
                    agg["device_s"] + (row.get("device_s") or 0.0), 6
                )
                agg["queued_s"] = round(
                    agg["queued_s"] + (row.get("queued_s") or 0.0), 6
                )
                for k, v in (row.get("by_kind") or {}).items():
                    agg["by_kind"][k] = round(agg["by_kind"].get(k, 0.0) + v, 6)
                for t, v in (row.get("kv_byte_s") or {}).items():
                    agg["kv_byte_s"][t] = round(
                        agg["kv_byte_s"].get(t, 0.0) + v, 6
                    )
                for t, v in (row.get("kv_resident_bytes") or {}).items():
                    agg["kv_resident_bytes"][t] = (
                        agg["kv_resident_bytes"].get(t, 0) + int(v)
                    )
                for k, v in (row.get("tokens") or {}).items():
                    agg["tokens"][k] = agg["tokens"].get(k, 0) + int(v)
            for jk, s in (costs.get("adapters") or {}).items():
                adapters[jk] = round(adapters.get(jk, 0.0) + s, 6)
            for tier, row in (costs.get("tiers") or {}).items():
                agg = tiers.setdefault(
                    tier, {"resident_bytes": 0, "byte_s": 0.0}
                )
                agg["resident_bytes"] += int(row.get("resident_bytes") or 0)
                agg["byte_s"] = round(
                    agg["byte_s"] + (row.get("byte_s") or 0.0), 6
                )
        total = round(sum(r["device_s"] for r in tenants.values()), 6)
        shares = {
            t: round(r["device_s"] / total, 5)
            for t, r in tenants.items() if total > 0
        }
        return {
            "namespace": self.namespace,
            "component": self.component,
            "ts": time.time(),
            "tenants": tenants,
            "adapters": adapters,
            "tiers": tiers,
            "device_s_total": total,
            "device_share": shares,
            "workers": per_worker,
        }

    async def _cluster_costs(self, request: web.Request) -> web.Response:
        return web.json_response(self.cluster_costs())

    async def _cluster_events(self, request: web.Request) -> web.Response:
        q = request.query
        try:
            limit = max(1, min(2000, int(q.get("limit", "200"))))
        except ValueError:
            limit = 200
        events = self.cluster_events(
            kind=q.get("kind", ""),
            tenant=q.get("tenant", ""),
            request_id=q.get("request", q.get("request_id", "")),
            limit=limit,
        )
        return web.json_response({
            "count": len(events),
            "events": events,
        })

    # ---------------- Prometheus ----------------

    def render(self) -> str:
        """Conformant Prometheus exposition: every metric family carries its
        own HELP/TYPE pair ahead of its samples (promtool-checkable — one
        free-text comment covering everything is not)."""
        loads = self.aggregator.get_metrics()
        views = self.aggregator.worker_views()
        base = {"namespace": self.namespace, "component": self.component}
        out = render_family(
            "llm_kv_workers", "gauge",
            "workers currently reporting ForwardPassMetrics",
            [(base, len(loads))],
        )
        for field in (
            "request_active_slots",
            "request_total_slots",
            "kv_active_blocks",
            "kv_total_blocks",
            "num_requests_waiting",
            "gpu_cache_usage_perc",
            "gpu_prefix_cache_hit_rate",
        ):
            values = [getattr(w, field) for w in loads]
            samples = [
                ({**base, "worker_id": f"{w.worker_id:x}"}, getattr(w, field))
                for w in loads
            ]
            out += render_family(
                f"llm_kv_{field}", "gauge",
                f"worker {field} (per reporting worker)", samples,
            )
            if values:
                for agg, val in (
                    ("avg", sum(values) / len(values)),
                    ("min", min(values)),
                    ("max", max(values)),
                ):
                    out += render_family(
                        f"llm_kv_{field}_{agg}", "gauge",
                        f"{agg} of {field} across reporting workers",
                        [(base, val)],
                    )
        pct = 100.0 * self._overlap_blocks / self._isl_blocks if self._isl_blocks else 0.0
        out += render_family(
            "llm_kv_hit_rate_percent", "gauge",
            "cumulative KV prefix-cache hit rate from router events",
            [(base, str(round(pct, 3)))],
        )
        out += render_family(
            "llm_kv_hit_rate_isl_blocks_total", "counter",
            "cumulative input-sequence blocks seen by the router",
            [(base, self._isl_blocks)],
        )
        out += render_family(
            "llm_kv_hit_rate_overlap_blocks_total", "counter",
            "cumulative cached-prefix blocks matched by the router",
            [(base, self._overlap_blocks)],
        )
        if self._router_radix is not None:
            # composed from the indexer's own renderer so the family names
            # have exactly one emitting site
            out += render_radix_metrics(
                self._router_radix, namespace=self.namespace, component=self.component
            )
        # ---- fleet-wide per-priority-class SLO view: the per-frontend
        # dynamo_slo_* series aggregate here across every scraped worker, so
        # "is the critical class inside budget FLEET-wide" is one query ----
        prio_comp: dict[tuple, tuple] = {}  # (class, metric) -> (weighted, n)
        prio_viol: dict[tuple, int] = {}
        for view in views:
            prios = (view.data.get("slo") or {}).get("priorities") or {}
            for pcls, metrics in prios.items():
                for metric, s in metrics.items():
                    if not isinstance(s, dict):
                        continue
                    key = (pcls, metric)
                    cnt = s.get("count") or 0
                    comp = s.get("compliance")
                    if comp is not None and cnt:
                        wsum, n = prio_comp.get(key, (0.0, 0))
                        prio_comp[key] = (wsum + float(comp) * cnt, n + cnt)
                    prio_viol[key] = prio_viol.get(key, 0) + int(
                        s.get("violations_total") or 0
                    )
        if prio_comp:
            out += render_family(
                "dynamo_slo_compliance_ratio", "gauge",
                "fleet-wide fraction of window samples meeting the target, "
                "per priority class (sample-weighted across scraped workers)",
                [({**base, "priority": pcls, "metric": m}, round(w / n, 5))
                 for (pcls, m), (w, n) in sorted(prio_comp.items())],
            )
        if prio_viol:
            out += render_family(
                "dynamo_slo_violations_total", "counter",
                "fleet-wide SLO violations per priority class, summed across "
                "scraped workers",
                [({**base, "priority": pcls, "metric": m}, v)
                 for (pcls, m), v in sorted(prio_viol.items())],
            )
        # ---- fleet health: per-worker instance-labeled families ----
        now = time.monotonic()
        state_samples, seen_samples, missed_samples, hb_samples = [], [], [], []
        resource_samples: dict[str, list] = {}
        for view in views:
            wlabels = {**base, "worker_id": f"{view.instance_id:x}"}
            health = view.health or {}
            state = health.get("state", "unknown")
            for s in STATES:
                state_samples.append(({**wlabels, "state": s}, 1 if s == state else 0))
            seen_samples.append((wlabels, round(view.age_s(now), 3)))
            missed_samples.append((wlabels, view.missed_scrapes))
            if "heartbeat_age_s" in health:
                hb_samples.append((wlabels, health["heartbeat_age_s"]))
            resources = view.data.get("resources") or {}
            for key, value in sorted(resources.items()):
                if isinstance(value, bool) or not isinstance(value, (int, float)):
                    continue  # nested dicts/strings ride /cluster/status only
                resource_samples.setdefault(key, []).append((wlabels, value))
        if views:
            out += render_family(
                "llm_worker_health_state", "gauge",
                "scraped worker lifecycle state (one-hot over the state label)",
                state_samples,
            )
            out += render_family(
                "llm_worker_last_seen_seconds", "gauge",
                "seconds since the worker last answered a stats scrape",
                seen_samples,
            )
            out += render_family(
                "llm_worker_missed_scrapes", "gauge",
                "consecutive scrape rounds the worker has missed",
                missed_samples,
            )
            if hb_samples:
                out += render_family(
                    "llm_worker_heartbeat_age_seconds", "gauge",
                    "engine-loop heartbeat age reported in the worker's last stats",
                    hb_samples,
                )
        for key, samples in sorted(resource_samples.items()):
            out += render_family(
                f"llm_worker_resource_{key}", "gauge",
                f"worker resource gauge {key} (from engine resource snapshot)",
                samples,
            )
        # per-stage engine-time attribution scraped from worker stats
        # (engine StageStats -> worker stats_handler -> this component)
        stage_samples = []
        for instance_id, data in self.aggregator.get_raw():
            stage = data.get("stage_seconds") or {}
            for key, value in sorted(stage.items()):
                if not key.endswith("_s"):
                    continue  # counts ride the *_n/_rows fields; seconds only
                stage_samples.append((
                    {**base, "worker_id": f"{instance_id:x}", "stage": key[:-2]},
                    value,
                ))
        if stage_samples:
            out += render_family(
                "llm_engine_stage_seconds_total", "counter",
                "cumulative engine seconds attributed to each serving stage",
                stage_samples,
            )
        return out

    async def _metrics(self, request: web.Request) -> web.Response:
        return web.Response(text=self.render(), content_type="text/plain")


async def _main(args) -> None:
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    drt = DistributedRuntime(cplane_address=args.cplane)
    await drt.connect()
    svc = MetricsService(
        drt, args.namespace, args.component, args.host, args.port,
        interval=args.interval, max_missed_scrapes=args.max_missed_scrapes,
    )
    await svc.start()
    while True:
        await asyncio.sleep(3600)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--component", default="backend")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=9091)
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--max-missed-scrapes", type=int, default=3,
                   help="scrape rounds a silent worker survives before it is "
                        "aged out of the fleet view")
    p.add_argument("--cplane", default=None)
    asyncio.run(_main(p.parse_args(argv)))


if __name__ == "__main__":
    main()
