"""Standalone metrics component: scrapes ForwardPassMetrics from a component's
workers, aggregates (avg/min/max + KV-hit-rate percent), and exposes
Prometheus.

Mirrors the reference metrics binary (reference: components/metrics/src/
{main.rs:115-272,lib.rs:125-633}); the mock worker analogue lives in
tests (reference: components/metrics/src/bin/mock_worker.rs).

    python -m dynamo_tpu.components.metrics --namespace dynamo --component backend --port 9091
"""

from __future__ import annotations

import argparse
import asyncio
from typing import Optional

from aiohttp import web

from dynamo_tpu.llm.kv_router.metrics_aggregator import KvMetricsAggregator
from dynamo_tpu.llm.kv_router.router import KV_HIT_RATE_SUBJECT
from dynamo_tpu.utils import get_logger
from dynamo_tpu.utils.prometheus import render_family

log = get_logger("components.metrics")


class MetricsService:
    def __init__(
        self,
        drt,
        namespace: str,
        component: str,
        host: str = "0.0.0.0",
        port: int = 9091,
        interval: float = 2.0,
    ):
        self.drt = drt
        self.namespace = namespace
        self.component = component
        self.host = host
        self.port = port
        self.aggregator = KvMetricsAggregator(
            drt.cplane, namespace, component, interval=interval
        )
        # cumulative KV hit-rate from router events
        self._isl_blocks = 0
        self._overlap_blocks = 0
        self._runner: Optional[web.AppRunner] = None

    async def start(self) -> int:
        await self.aggregator.start()
        await self.drt.cplane.subscribe(
            f"{self.namespace}.{KV_HIT_RATE_SUBJECT}", self._on_hit_rate
        )
        app = web.Application()
        app.router.add_get("/metrics", self._metrics)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        log.info("metrics on %s:%d scraping %s/%s", self.host, self.port, self.namespace, self.component)
        return self.port

    async def stop(self) -> None:
        await self.aggregator.stop()
        if self._runner is not None:
            await self._runner.cleanup()

    def _on_hit_rate(self, msg: dict) -> None:
        p = msg["payload"]
        self._isl_blocks += p.get("isl_blocks", 0)
        self._overlap_blocks += p.get("overlap_blocks", 0)

    def render(self) -> str:
        """Conformant Prometheus exposition: every metric family carries its
        own HELP/TYPE pair ahead of its samples (promtool-checkable — one
        free-text comment covering everything is not)."""
        loads = self.aggregator.get_metrics()
        base = {"namespace": self.namespace, "component": self.component}
        out = render_family(
            "llm_kv_workers", "gauge",
            "workers currently reporting ForwardPassMetrics",
            [(base, len(loads))],
        )
        for field in (
            "request_active_slots",
            "request_total_slots",
            "kv_active_blocks",
            "kv_total_blocks",
            "num_requests_waiting",
            "gpu_cache_usage_perc",
            "gpu_prefix_cache_hit_rate",
        ):
            values = [getattr(w, field) for w in loads]
            samples = [
                ({**base, "worker_id": f"{w.worker_id:x}"}, getattr(w, field))
                for w in loads
            ]
            out += render_family(
                f"llm_kv_{field}", "gauge",
                f"worker {field} (per reporting worker)", samples,
            )
            if values:
                for agg, val in (
                    ("avg", sum(values) / len(values)),
                    ("min", min(values)),
                    ("max", max(values)),
                ):
                    out += render_family(
                        f"llm_kv_{field}_{agg}", "gauge",
                        f"{agg} of {field} across reporting workers",
                        [(base, val)],
                    )
        pct = 100.0 * self._overlap_blocks / self._isl_blocks if self._isl_blocks else 0.0
        out += render_family(
            "llm_kv_hit_rate_percent", "gauge",
            "cumulative KV prefix-cache hit rate from router events",
            [(base, str(round(pct, 3)))],
        )
        out += render_family(
            "llm_kv_hit_rate_isl_blocks_total", "counter",
            "cumulative input-sequence blocks seen by the router",
            [(base, self._isl_blocks)],
        )
        out += render_family(
            "llm_kv_hit_rate_overlap_blocks_total", "counter",
            "cumulative cached-prefix blocks matched by the router",
            [(base, self._overlap_blocks)],
        )
        # per-stage engine-time attribution scraped from worker stats
        # (engine StageStats -> worker stats_handler -> this component)
        stage_samples = []
        for instance_id, data in self.aggregator.get_raw():
            stage = data.get("stage_seconds") or {}
            for key, value in sorted(stage.items()):
                if not key.endswith("_s"):
                    continue  # counts ride the *_n/_rows fields; seconds only
                stage_samples.append((
                    {**base, "worker_id": f"{instance_id:x}", "stage": key[:-2]},
                    value,
                ))
        if stage_samples:
            out += render_family(
                "llm_engine_stage_seconds_total", "counter",
                "cumulative engine seconds attributed to each serving stage",
                stage_samples,
            )
        return out

    async def _metrics(self, request: web.Request) -> web.Response:
        return web.Response(text=self.render(), content_type="text/plain")


async def _main(args) -> None:
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    drt = DistributedRuntime(cplane_address=args.cplane)
    await drt.connect()
    svc = MetricsService(drt, args.namespace, args.component, args.host, args.port)
    await svc.start()
    while True:
        await asyncio.sleep(3600)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--component", default="backend")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=9091)
    p.add_argument("--cplane", default=None)
    asyncio.run(_main(p.parse_args(argv)))


if __name__ == "__main__":
    main()
