"""Deployable service components: frontend (HTTP + model discovery), processor
(preprocess + KV-aware routing), worker (JAX engine), prefill worker.

These are the building blocks the reference ships as examples/llm components +
the standalone http/metrics binaries (reference: components/http, components/
metrics, examples/llm/components/)."""
