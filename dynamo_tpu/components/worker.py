"""Worker service: the JAX engine behind a runtime endpoint.

Tokens-in/tokens-out over the wire: PreprocessedRequest dict -> stream of
BackendOutput dicts (detokenization happens here, next to the engine, so text
deltas stream back ready to serve — reference: examples/llm/components/
worker.py VllmWorker, lib/llm/src/backend.rs).

Publishes KV events (kv_events subject) and ForwardPassMetrics (stats handler)
so KV routers can target it. Optionally wraps the engine in the disagg decode
path.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.llm.backend import Backend
from dynamo_tpu.llm.kv_router.publisher import KvEventPublisher
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.model_registry import ModelEntry, ModelRegistration
from dynamo_tpu.llm.protocols.common import PreprocessedRequest
from dynamo_tpu.llm.tokenizer import get_tokenizer
from dynamo_tpu.utils import get_logger

log = get_logger("components.worker")

GENERATE_ENDPOINT = "generate"
MIGRATE_ENDPOINT = "migrate"


class WorkerService:
    def __init__(
        self,
        drt,
        namespace: str,
        component: str,
        card: ModelDeploymentCard,
        engine_config: EngineConfig,
        enable_disagg_decode: bool = False,
        register: bool = True,
        engine_factory=None,
        admin_port: int | None = None,
    ):
        self.drt = drt
        self.namespace = namespace
        self.component = component
        self.card = card
        self.engine_config = engine_config
        self.enable_disagg_decode = enable_disagg_decode
        self.register = register
        # optional (kv_event_sink) -> engine: hosts an external engine (e.g.
        # llm.external.ExternalTokenEngine) behind this worker instead of the
        # native JAX engine — the reference's engine-agnostic worker slot
        self.engine_factory = engine_factory
        self.engine = None  # AsyncJaxEngine or DisaggDecodeEngine
        self.backend: Optional[Backend] = None
        self._served = None
        self._kv_publisher: Optional[KvEventPublisher] = None
        # fleet-wide prefix cache: peers pull OUR cached prefixes from this
        # export server; its address rides the stats broadcast so the KV
        # router can attach us as a holder (disagg/prefix_fetch.py)
        self.kv_pull_server = None
        # live migration (disagg/migrate.py): the peer-facing `migrate`
        # runtime endpoint adopts manifests; /admin/drain on the admin HTTP
        # port triggers the migrate-then-die drain of THIS worker
        self.admin_port = admin_port
        self._admin_runner = None
        self._migrate_served = None
        self._migrate_client = None

    async def start(self) -> "WorkerService":
        loop = asyncio.get_running_loop()
        worker_id = self.drt.primary_lease.lease_id
        subject = f"{self.namespace}|{self.component}.kv_events"
        self._kv_publisher = KvEventPublisher(self.drt.cplane, subject, worker_id, loop=loop)

        if self.engine_factory is not None:
            inner = self.engine_factory(self._kv_publisher.publish)
            starter = getattr(inner, "start", None)
            if starter is not None:
                result = starter()
                if asyncio.iscoroutine(result):
                    await result
        else:
            inner = AsyncJaxEngine(self.engine_config, kv_event_sink=self._kv_publisher.publish)
            await inner.start()
        if self.engine_config.prefix_fetch and isinstance(inner, AsyncJaxEngine):
            from dynamo_tpu.disagg.prefix_fetch import KvPullServer, PrefixFetchClient

            # both directions of the fleet prefix cache: serve our prefixes
            # to pulling peers, and pull theirs when the router attaches a
            # holder to an incoming request
            self.kv_pull_server = await KvPullServer(inner).start()
            inner.kv_pull_server = self.kv_pull_server
            inner.attach_prefix_fetch(PrefixFetchClient(
                loop, timeout_s=self.engine_config.prefix_fetch_timeout_s
            ))
        engine = inner
        if self.enable_disagg_decode:
            from dynamo_tpu.disagg.decode_worker import DisaggDecodeEngine

            engine = DisaggDecodeEngine(
                inner, self.drt, self.namespace, self.component, self.card.display_name
            )
            await engine.start()
        self.engine = engine
        self._inner_engine = inner

        tokenizer = get_tokenizer(self.card.tokenizer)
        self.backend = Backend(engine, tokenizer)

        ep = self.drt.namespace(self.namespace).component(self.component).endpoint(GENERATE_ENDPOINT)
        self._served = await ep.serve_endpoint(self._handle, metrics=self._stats)

        # live migration: adopt peers' manifests on `migrate`, and keep a
        # client to the same endpoint so OUR drain can hand sequences out
        if self.engine_config.migration and isinstance(inner, AsyncJaxEngine):
            mep = (
                self.drt.namespace(self.namespace)
                .component(self.component)
                .endpoint(MIGRATE_ENDPOINT)
            )
            self._migrate_served = await mep.serve_endpoint(self._handle_migrate)
            self._migrate_client = await self.drt.client(
                self.namespace, self.component, MIGRATE_ENDPOINT
            )
            # QoS shed hook (engine-thread callable): when a waiting
            # critical request must evict a lower-class lane, hand the
            # victim to a servable peer via live migration instead of
            # preempt+recompute — the batch request survives elsewhere and
            # this worker's slot frees when the relay takes over
            me = self.drt.primary_lease.lease_id
            eng_loop = loop

            def _shed_via_migration(request_id: str) -> bool:
                try:
                    peers = [
                        i for i in self._migrate_client.instance_ids() if i != me
                    ]
                except Exception:
                    return False
                if not peers:
                    return False
                adopter = self._peer_adopter(peers[0])
                asyncio.run_coroutine_threadsafe(
                    inner.migrate_out(request_id, adopter), eng_loop
                )
                return True

            if inner.scheduler is not None:
                inner.scheduler.migrate_shed = _shed_via_migration
        if self.admin_port is not None:
            await self._start_admin(self.admin_port)

        if self.register:
            entry = ModelEntry(
                name=self.card.display_name,
                endpoint=f"dyn://{self.namespace}.{self.component}.{GENERATE_ENDPOINT}",
                model_type="chat",
                card=self.card,
            )
            # lease-tied + refreshed: the card dies with this worker's lease
            # and any surviving co-worker's refresh restores it (MDC TTL
            # semantics, reference: model_card/model.rs)
            self._registration = await ModelRegistration(
                self.drt.cplane, entry, lease_id=self.drt.primary_lease.lease_id
            ).start()
            # multi-LoRA: every configured adapter registers as its own
            # servable model name <base>:<adapter> (same endpoint + card;
            # frontends list and route them like any model; the worker
            # resolves the suffix back to lora_name in _handle)
            self._lora_registrations = []
            if getattr(self.engine_config, "lora_adapters", ()):
                from dynamo_tpu.lora.adapter import parse_adapter_specs

                for name in parse_adapter_specs(self.engine_config.lora_adapters):
                    a_entry = ModelEntry(
                        name=f"{self.card.display_name}:{name}",
                        endpoint=entry.endpoint,
                        model_type="chat",
                        card=self.card,
                    )
                    self._lora_registrations.append(await ModelRegistration(
                        self.drt.cplane, a_entry,
                        lease_id=self.drt.primary_lease.lease_id,
                    ).start())
        return self

    async def stop(self) -> None:
        if self._admin_runner is not None:
            await self._admin_runner.cleanup()
        if self._migrate_served is not None:
            await self._migrate_served.stop()
        if self._migrate_client is not None:
            await self._migrate_client.stop()
        for reg in getattr(self, "_lora_registrations", ()):
            await reg.stop(unregister=False)
        if getattr(self, "_registration", None) is not None:
            # unregister=False: the card key is lease-tied, so OUR lease revoke
            # (DRT shutdown) removes it if we were the owner — while a clean
            # scale-down of one worker of a multi-worker model must NOT blip
            # the shared card for the survivors
            await self._registration.stop(unregister=False)
        if self._served is not None:
            await self._served.stop()
        if self.kv_pull_server is not None:
            await self.kv_pull_server.stop()
        if self.engine is not None:
            await self.engine.shutdown()

    def _stats(self) -> dict:
        stats = {"kv_metrics": self._inner_engine.metrics().to_wire()}
        # per-stage latency attribution (scheduler StageStats): scraped by the
        # standalone metrics component into llm_engine_stage_seconds_total
        stage = getattr(self._inner_engine, "stage_snapshot", None)
        if stage is not None:
            snap = stage()
            if snap:
                stats["stage_seconds"] = snap
        # fleet health plane: lifecycle state + heartbeat age (routers and the
        # planner skip draining/dead workers), resource gauges (page pool,
        # HBM, compile churn), and the rolling SLO state — all ride the same
        # stats broadcast the aggregator already scrapes
        health = getattr(self._inner_engine, "health", None)
        if health is not None:
            stats["health"] = health.snapshot()
        resources = getattr(self._inner_engine, "resource_snapshot", None)
        if resources is not None:
            snap = resources()
            if snap:
                stats["resources"] = snap
        slo = getattr(self._inner_engine, "slo_snapshot", None)
        if slo is not None:
            stats["slo"] = slo()
        ev = getattr(self._inner_engine, "events_snapshot", None)
        if ev is not None:
            # flight-recorder summary: newest events + per-kind counts (the
            # metrics component's /cluster/events merges the recent lists;
            # dynotop's EVT column reads the counts)
            stats["events"] = ev()
        goodput = getattr(self._inner_engine, "goodput_snapshot", None)
        if goodput is not None:
            # windowed per-scenario/tenant SLO-met fraction (dynotop GOODPUT
            # column; item-5 QoS scheduling reads the per-tenant view)
            stats["goodput"] = goodput()
        costs = getattr(self._inner_engine, "cost_snapshot", None)
        if costs is not None:
            # cost-attribution rollup (utils/metering.py): per-tenant device-
            # seconds and KV byte-seconds — the metrics component's
            # /cluster/costs merge, dynotop's COST column, and the planner's
            # per-tenant demand signal all read this broadcast
            snap = costs()
            if snap:
                stats["costs"] = snap
        # live migration: whether this worker adopts peers' sequences (the
        # planner's rebalance decisions only target migration-enabled pairs)
        stats["migration"] = {
            "enabled": bool(getattr(self.engine_config, "migration", False))
            and self._migrate_client is not None,
        }
        if self.admin_port is not None and self._admin_runner is not None:
            # the planner's rebalance EXECUTOR reads this out of the stats
            # broadcast to POST /admin/drain on the decided source worker
            stats["admin"] = {"address": f"127.0.0.1:{self.admin_port}"}
        if self.kv_pull_server is not None:
            # the fleet prefix cache's discovery channel: routers read the
            # pull address out of this broadcast to attach us as a holder
            srv = self.kv_pull_server
            stats["kv_pull"] = {
                "address": srv.address,
                "served": srv.served,
                "gone": srv.gone,
                "served_blocks": dict(srv.served_blocks),
                "bytes_sent": srv.bytes_sent,
            }
        if self.enable_disagg_decode and self.engine is not None:
            stats["disagg"] = {
                "remote_prefills": self.engine.remote_prefills,
                "local_prefills": self.engine.local_prefills,
            }
            if self.engine.kv_server is not None:
                kv = self.engine.kv_server
                stats["disagg"]["kv_dataplane"] = {
                    "received": kv.received,
                    "parts_received": kv.parts_received,
                    "bytes_received": kv.bytes_received,
                    "dropped": kv.dropped,
                    "rejected": kv.rejected,
                    "checksum_failures": kv.checksum_failures,
                    "parts_scattered": self.engine.parts_scattered,
                    "address": kv.address,
                }
        return stats

    # ---------------- live migration (disagg/migrate.py) ----------------

    async def _handle_migrate(self, request: dict):
        """Peer-facing adoption endpoint: a draining/hot peer ships one
        sequence's manifest here; we adopt it (seq_handoff KV pull with
        recompute fallback) and stream the continuation tokens back — the
        peer relays them into its still-open client stream."""
        from dynamo_tpu.disagg.migrate import SequenceManifest

        manifest = SequenceManifest.from_wire(request)
        async for out in self._inner_engine.adopt_migrated(manifest):
            yield {
                "request_id": out.request_id,
                "token": out.token,
                "finished": out.finished,
                "finish_reason": out.finish_reason,
                "cached_tokens": out.cached_tokens,
            }

    def _peer_adopter(self, instance_id: int):
        """Adapter from the peer's `migrate` stream to the StepOutput shape
        AsyncJaxEngine.migrate_out relays."""
        from dynamo_tpu.engine.scheduler import StepOutput

        async def adopter(manifest):
            stream = await self._migrate_client.direct(
                manifest.to_wire(), instance_id
            )
            async for item in stream:
                yield StepOutput(
                    request_id=item.get("request_id", manifest.request_id),
                    token=item.get("token"),
                    finished=bool(item.get("finished")),
                    finish_reason=item.get("finish_reason"),
                    cached_tokens=int(item.get("cached_tokens", 0) or 0),
                )

        return adopter

    async def drain(self, target_instance: int | None = None) -> dict:
        """Operator drain, migrate-then-die instead of drain-by-attrition:
        mark this worker draining (routers/planner stop sending work), hand
        every in-flight sequence to a peer worker of the same component, and
        report what moved. Sequences whose handoff fails keep decoding here
        (never worse than attrition). The caller shuts the worker down once
        this returns."""
        eng = self._inner_engine
        health = getattr(eng, "health", None)
        if health is not None:
            health.set_state("draining", "operator drain requested")
        results = {"migrated": 0, "resumed": 0, "failed": 0, "skipped": 0}
        if not getattr(self.engine_config, "migration", True) or self._migrate_client is None:
            return {**results, "migration": "disabled"}
        if target_instance is None:
            me = self.drt.primary_lease.lease_id
            peers = [i for i in self._migrate_client.instance_ids() if i != me]
            target_instance = peers[0] if peers else None
        if target_instance is None:
            log.warning("drain: no migration peer available; draining by attrition")
            return {**results, "migration": "no-peer"}
        if health is not None:
            health.set_state("migrating", "drain: handing sequences to peer")
        adopter = self._peer_adopter(target_instance)
        sched = eng.scheduler
        rids = [
            s.req.request_id for s in sched.slots
            if s is not None and not s.finished
        ]
        for rid in rids:
            try:
                res = await eng.migrate_out(rid, adopter)
            except Exception:
                log.exception("drain: migration of %s crashed", rid)
                results["failed"] += 1
                continue
            status = res.get("status", "failed")
            results["migrated" if status == "ok" else
                    status if status in results else "failed"] += 1
        if health is not None:
            health.set_state("draining", "drain: migration pass complete")
        results["migration"] = "done"
        results["target_instance"] = f"{target_instance:x}"
        log.info("drain complete: %s", results)
        return results

    async def _start_admin(self, port: int) -> None:
        """Tiny operator-facing HTTP plane: POST /admin/drain {target?:
        "<instance hex>"} triggers the migrate-then-die drain."""
        from aiohttp import web

        app = web.Application()

        async def _drain(request: web.Request) -> web.Response:
            target = None
            try:
                body = await request.json()
            except Exception:
                body = {}
            if isinstance(body, dict) and body.get("target"):
                target = int(str(body["target"]), 16)
            result = await self.drain(target_instance=target)
            return web.json_response(result)

        app.router.add_post("/admin/drain", _drain)
        self._admin_runner = web.AppRunner(app, access_log=None)
        await self._admin_runner.setup()
        site = web.TCPSite(self._admin_runner, "127.0.0.1", port)
        await site.start()
        self.admin_port = site._server.sockets[0].getsockname()[1]
        log.info("worker admin endpoint on 127.0.0.1:%d", self.admin_port)

    async def _handle(self, request: dict):
        pre = PreprocessedRequest.from_wire(request)
        # distributed-path base:adapter resolution: the frontend routes by
        # registered model NAME; the worker maps the suffix back to the
        # adapter it configured (exact display-name prefix match, so a tiny
        # override JSON containing ':' can't misparse)
        if not pre.lora_name and pre.model:
            base_prefix = self.card.display_name + ":"
            if str(pre.model).startswith(base_prefix):
                suffix = str(pre.model)[len(base_prefix):]
                from dynamo_tpu.lora.adapter import parse_adapter_specs

                if suffix in parse_adapter_specs(
                    getattr(self.engine_config, "lora_adapters", ())
                ):
                    pre.lora_name = suffix
        async for out in self.backend.generate(pre):
            yield {
                "request_id": out.request_id,
                "text": out.text,
                "token_ids": out.token_ids,
                "finish_reason": out.finish_reason,
                "cumulative_tokens": out.cumulative_tokens,
                "cached_tokens": out.cached_tokens,
                "logprobs": out.logprobs,
            }


async def _main(args) -> None:
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.utils.xla_cache import enable_compilation_cache

    from dynamo_tpu.parallel.mesh import init_multihost

    enable_compilation_cache()  # engine restarts reload executables from disk
    init_multihost()  # no-op unless DYNTPU_COORDINATOR is set

    drt = DistributedRuntime(cplane_address=args.cplane)
    await drt.connect()
    from dynamo_tpu.models.registry import is_tiny_family

    if is_tiny_family(args.model):
        card = ModelDeploymentCard.for_tiny(args.model)
    else:
        card = ModelDeploymentCard.from_local_path(args.model)
    svc = WorkerService(
        drt,
        args.namespace,
        args.component,
        card,
        EngineConfig.for_model(
            args.model,
            tp=args.tp,
            page_size=args.page_size,
            num_pages=args.num_pages,
            max_seqs=args.max_seqs,
            max_model_len=args.max_model_len,
            quantize=getattr(args, "quantize", None),
            kv_cache_dtype=getattr(args, "kv_cache_dtype", None),
            speculative=getattr(args, "speculative", None),
            lora_adapters=tuple(
                s.strip() for s in (getattr(args, "lora_adapters", "") or "").split(",")
                if s.strip()
            ),
            max_loras=getattr(args, "max_loras", None) or 4,
            lora_rank=getattr(args, "lora_rank", None) or 8,
            kv_stream=not getattr(args, "no_kv_stream", False),
            kv_stream_lanes=getattr(args, "kv_stream_lanes", None) or 2,
            prefix_fetch=not getattr(args, "no_prefix_fetch", False),
            prefix_fetch_timeout_s=getattr(args, "prefix_fetch_timeout_s", None) or 5.0,
            prefix_fetch_min_blocks=getattr(args, "prefix_fetch_min_blocks", None) or 1,
            migration=not getattr(args, "no_migration", False),
            migration_timeout_s=getattr(args, "migration_timeout_s", None) or 10.0,
            qos=not getattr(args, "no_qos", False),
            qos_preempt_wait_ms=getattr(args, "qos_preempt_wait_ms", None) or 250.0,
            metering=not getattr(args, "no_metering", False),
            slo_ttft_ms=getattr(args, "slo_ttft_ms", None),
            slo_itl_ms=getattr(args, "slo_itl_ms", None),
            prefill_buckets=tuple(
                int(b) for b in getattr(args, "prefill_buckets", "").split(",") if b
            ) or EngineConfig.prefill_buckets,
            prefill_flat_depth=getattr(args, "prefill_flat_depth", None) or 8192,
            prefill_pipeline_depth=getattr(
                args, "prefill_pipeline_depth", None
            ) or EngineConfig.prefill_pipeline_depth,
            host_cache_blocks=getattr(args, "host_cache_blocks", None) or 0,
            host_cache_bytes=getattr(args, "host_cache_bytes", None) or 0,
            disk_cache_bytes=getattr(args, "disk_cache_bytes", None) or 0,
            disk_cache_dir=getattr(args, "disk_cache_dir", None) or "",
            offload_watermark=getattr(args, "offload_watermark", None) or 0.90,
        ),
        enable_disagg_decode=args.disagg,
        admin_port=getattr(args, "admin_port", None),
    )
    await svc.start()
    log.info(
        "worker up: model=%s endpoint=dyn://%s.%s.%s disagg=%s",
        card.display_name, args.namespace, args.component, GENERATE_ENDPOINT, args.disagg,
    )
    try:
        while True:
            await asyncio.sleep(3600)
    finally:
        await svc.stop()


def main(argv=None) -> None:
    """Plain-process decode/aggregated worker (helm: worker.yaml; the SDK
    graph variants live in examples/graphs/)."""
    import argparse
    import os

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("model", help="model path or tiny:{...} spec")
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--component", default="backend")
    p.add_argument("--cplane", default=os.environ.get("DYNTPU_CPLANE", "127.0.0.1:4222"))
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--num-pages", type=int, default=512)
    p.add_argument("--max-seqs", type=int, default=8)
    p.add_argument("--max-model-len", type=int, default=2048)
    p.add_argument("--quantize", choices=["int8_wo"], default=None,
                   help="weight-only quantization applied at load time")
    p.add_argument("--kv-cache-dtype", choices=["bf16", "int8"], default=None,
                   help="KV cache storage dtype: int8 halves attention HBM "
                        "traffic and ~doubles page capacity (per-page "
                        "scales; composes with --quantize)")
    p.add_argument("--speculative", default=None, metavar="KIND:...",
                   help="speculative decoding: ngram:<k> (prompt-lookup "
                        "proposals) or draft:<model>:<k> (a second, smaller "
                        "registry model with its own paged KV drafts k "
                        "tokens per round; composes with --quantize / "
                        "--kv-cache-dtype)")
    p.add_argument("--lora-adapters", default="",
                   help="comma-separated LoRA adapter specs served as "
                        "<model>:<name> (name | name=<dir> | "
                        "name=random:<seed>); a mixed-adapter batch decodes "
                        "in one gathered dispatch (dynamo_tpu/lora/)")
    p.add_argument("--max-loras", type=int, default=4,
                   help="device adapter slots; more adapters than slots "
                        "multiplex via LRU eviction/hot-swap")
    p.add_argument("--lora-rank", type=int, default=8,
                   help="adapter pool rank (smaller adapters zero-pad; "
                        "larger are rejected at load)")
    p.add_argument("--disagg", action="store_true", help="wrap in the disagg decode path")
    p.add_argument("--slo-ttft-ms", type=float, default=None,
                   help="TTFT SLO target in ms (rolling percentiles + error "
                        "budget ride stats and /metrics; env "
                        "DYNTPU_SLO_TTFT_MS)")
    p.add_argument("--slo-itl-ms", type=float, default=None,
                   help="inter-token-latency SLO target in ms (env "
                        "DYNTPU_SLO_ITL_MS)")
    p.add_argument("--kv-stream-lanes", type=int, default=2,
                   help="parallel KV data-plane connections per destination "
                        "(disagg; parts stripe across lanes)")
    p.add_argument("--no-kv-stream", action="store_true",
                   help="disable chunk-streamed KV transfer (fall back to one "
                        "monolithic post-prefill send)")
    p.add_argument("--no-prefix-fetch", action="store_true",
                   help="disable the fleet-wide prefix cache (don't serve KV "
                        "pulls or fetch remote prefixes from peers)")
    p.add_argument("--prefix-fetch-timeout-s", type=float, default=5.0,
                   help="remote prefix pull deadline; on expiry the request "
                        "degrades to recompute (never an error)")
    p.add_argument("--prefix-fetch-min-blocks", type=int, default=1,
                   help="minimum holder advantage (blocks) over the local "
                        "prefix cache before a pull is worth issuing")
    p.add_argument("--no-migration", action="store_true",
                   help="disable live sequence migration (drain degrades to "
                        "attrition and the frontend answers retriable 503s "
                        "while draining)")
    p.add_argument("--migration-timeout-s", type=float, default=10.0,
                   help="deadline belt on one sequence handoff (KV pull + "
                        "first continuation token); on expiry the sequence "
                        "resumes decoding locally")
    p.add_argument("--no-qos", action="store_true",
                   help="disable multi-tenant QoS scheduling (priority "
                        "classes ignored: FIFO admission, recency-only "
                        "preemption victims)")
    p.add_argument("--no-metering", action="store_true",
                   help="disable per-tenant cost attribution (no ledger: "
                        "dynamo_cost_* families, /cluster/costs shares and "
                        "per-request cost footers all go dark)")
    p.add_argument("--qos-preempt-wait-ms", type=float, default=250.0,
                   help="how long a critical request waits with no free "
                        "slot before the scheduler evicts a lower-class "
                        "lane for it (anti-thrash gate)")
    p.add_argument("--admin-port", type=int, default=None,
                   help="operator admin HTTP port on 127.0.0.1 (0 = "
                        "ephemeral): POST /admin/drain migrates in-flight "
                        "sequences to a peer and marks this worker draining")
    p.add_argument("--prefill-buckets", default="",
                   help="comma-separated padded prefill chunk lengths (e.g. "
                        "512,1024,2048 for long-context configs); empty = "
                        "the engine default")
    p.add_argument("--prefill-flat-depth", type=int, default=8192,
                   help="context depth past which the scheduler shrinks "
                        "prefill chunks to keep per-chunk latency flat "
                        "(0 disables)")
    p.add_argument("--prefill-pipeline-depth", type=int, default=None,
                   help="packed prefill calls dispatched ahead of result "
                        "materialization (1 = strict reconcile per call; "
                        "default 2 overlaps call N+1's host prep with call "
                        "N's device time — see tools/profile_prefill.py)")
    p.add_argument("--host-cache-blocks", type=int, default=0,
                   help="host-DRAM KV offload tier capacity in blocks "
                        "(0 disables; long-context cold KV drains here "
                        "under page pressure)")
    p.add_argument("--host-cache-bytes", type=int, default=0,
                   help="host-DRAM KV tier budget in bytes, resolved to "
                        "blocks at the model's ACTUAL per-page wire cost "
                        "(an int8 KV cache fits ~2x the blocks of bf16 in "
                        "the same budget; the larger of the two knobs wins)")
    p.add_argument("--disk-cache-bytes", type=int, default=0,
                   help="disk KV tier budget in bytes (0 disables; requires "
                        "a host tier — host-pool LRU victims demote to disk "
                        "int8-compressed instead of dropping, and a cold "
                        "session resume restores disk->host->HBM without a "
                        "prefill recompute)")
    p.add_argument("--disk-cache-dir", default="",
                   help="directory for disk-tier block files (default: the "
                        "DYNTPU_KV_DISK_DIR env var, else a fresh tempdir "
                        "the store owns and cleans up)")
    p.add_argument("--offload-watermark", type=float, default=0.90,
                   help="page-pool occupancy fraction that triggers the "
                        "batched cold-block drain to the host tier "
                        "(>= 1.0 disables the proactive drain)")
    args = p.parse_args(argv)
    asyncio.run(_main(args))


if __name__ == "__main__":
    main()
