"""Processor: KV-aware routing tier between frontends and workers.

Serves ``generate``: takes a PreprocessedRequest wire dict, picks the best
worker via the KvRouter (radix overlap + load cost), forwards with direct
routing, and relays the BackendOutput stream.

Mirrors the reference Processor/Router pair (reference: examples/llm/
components/{processor.py,kv_router.py}).
"""

from __future__ import annotations

from typing import Optional

from dynamo_tpu.llm.kv_router.router import KvRouter
from dynamo_tpu.llm.kv_router.scheduler import AllWorkersBusyError, NoWorkersError
from dynamo_tpu.utils import get_logger, tracing

log = get_logger("components.processor")


class ProcessorService:
    def __init__(
        self,
        drt,
        namespace: str,
        component: str = "processor",
        worker_component: str = "backend",
        kv_block_size: int = 16,
        routing: str = "kv",  # kv | random | round_robin
    ):
        self.drt = drt
        self.namespace = namespace
        self.component = component
        self.worker_component = worker_component
        self.kv_block_size = kv_block_size
        self.routing = routing
        self.router: Optional[KvRouter] = None
        self._worker_client = None
        self._served = None

    async def start(self) -> "ProcessorService":
        from dynamo_tpu.components.worker import GENERATE_ENDPOINT

        self._worker_client = await self.drt.client(
            self.namespace, self.worker_component, GENERATE_ENDPOINT
        )
        if self.routing == "kv":
            self.router = KvRouter(
                self.drt, self.namespace, self.worker_component, self.kv_block_size
            )
            await self.router.start()
        ep = self.drt.namespace(self.namespace).component(self.component).endpoint("generate")
        self._served = await ep.serve_endpoint(self._handle)
        return self

    async def stop(self) -> None:
        if self._served is not None:
            await self._served.stop()
        if self.router is not None:
            await self.router.stop()
        if self._worker_client is not None:
            await self._worker_client.stop()

    async def _handle(self, request: dict):
        token_ids = request.get("token_ids", [])
        instance_id = None
        # multi-LoRA: the adapter uid salts every hash the routing decision
        # uses, mirroring the engines' salted block identity — an adapter's
        # requests only score overlap against that adapter's cached blocks
        salt = 0
        if request.get("lora_name"):
            from dynamo_tpu.lora.adapter import lora_uid

            salt = lora_uid(str(request["lora_name"]))
        if self.router is not None:
            try:
                # routing-decision time is hop overhead a trace should see
                with tracing.span("processor.schedule", tokens=len(token_ids)):
                    instance_id, overlap = await self.router.schedule_with_overlap(
                        token_ids, salt=salt
                    )
                # fleet-wide prefix cache: when a peer's cached prefix beats
                # the chosen worker's, attach it so the worker can PULL the
                # pages over the dataplane instead of recomputing them — the
                # same OverlapScores the placement used, no second radix walk
                holder = self.router.best_remote_holder(overlap, instance_id)
                if holder is not None:
                    addr = self.router.pull_address(holder[0])
                    if addr:
                        request = dict(request)
                        request["kv_holder_addr"] = addr
                        request["kv_holder_blocks"] = holder[1]
            except (NoWorkersError, AllWorkersBusyError) as e:
                log.warning("kv scheduling failed (%s); falling back to random", e)

        if instance_id is not None:
            stream = await self._worker_client.direct(request, instance_id)
        elif self.routing == "round_robin":
            stream = await self._worker_client.round_robin(request)
        else:
            stream = await self._worker_client.random(request)
        async for item in stream:
            yield item
