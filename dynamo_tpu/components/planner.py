"""Planner: dynamic worker scaling from live serving signals.

The reference names the Planner as a headline capability but ships it as
aspiration only (reference: docs/architecture.md:47 — "empower the Planner to
make intelligent, zero-downtime adjustments"; no planner code exists in the
snapshot). Here it is a working component:

  - **decode pool**: scales on slot pressure (mean request-slot utilization,
    queued requests) and KV pressure (mean page-pool utilization) scraped from
    every worker's ForwardPassMetrics.
  - **prefill pool**: scales on the disagg work-queue depth — the reference's
    motivating example (long-ISL surges back up the prefill queue long before
    decode slots saturate).

Decisions are sustained-signal + cooldown gated (no flapping) and published to
the control-plane KV at ``planner/{namespace}/desired/{component}``. Consumers:
the sdk serve supervisor polls these keys when started with
``--planner-scaling`` and spawns/terminates worker processes
(dynamo_tpu/sdk/serve.py _apply_planner_scaling — the single-host loop), and a
K8s controller can feed them into dynamo_tpu/deploy/reconciler.py's
DeploymentSpec replicas. The policy core is pure (observe() in, decisions out)
so it is testable without a cluster.
"""

from __future__ import annotations

import asyncio
import json
import time
from dataclasses import dataclass
from typing import Optional

from dynamo_tpu.utils import events, get_logger

log = get_logger("components.planner")


@dataclass
class PoolPolicy:
    """Scaling envelope + thresholds for one worker pool."""

    min_replicas: int = 1
    max_replicas: int = 8
    # scale up when the pressure signal exceeds this for `sustain` observations
    up_threshold: float = 0.8
    # scale down when it stays below this for `sustain` observations
    down_threshold: float = 0.3
    sustain: int = 3
    cooldown_s: float = 30.0


@dataclass
class ScaleDecision:
    component: str
    current: int
    desired: int
    reason: str

    @property
    def is_change(self) -> bool:
        return self.desired != self.current


@dataclass
class _PoolState:
    above: int = 0
    below: int = 0
    last_change: float = float("-inf")  # no cooldown before the first change


@dataclass
class RebalancePolicy:
    """Hot-spot live-migration thresholds (disagg/migrate.py). A worker is
    HOT when its KV occupancy crosses ``occupancy_hot`` or its windowed
    goodput burns below ``goodput_floor`` while a COLD peer (occupancy under
    ``occupancy_cold``) has headroom; sustained-signal + cooldown gating
    mirrors the scaling policy so load noise can't thrash sequences around
    the fleet."""

    occupancy_hot: float = 0.85
    occupancy_cold: float = 0.55
    goodput_floor: float = 0.90
    sustain: int = 3
    cooldown_s: float = 60.0


@dataclass
class RebalanceDecision:
    """One migrate-from-hot-to-cold recommendation, published to the
    control-plane KV for the supervisor/operator to act on (the source
    worker's /admin/drain with the target instance executes it)."""

    source: str  # hot worker id (hex)
    target: str  # cold worker id (hex)
    reason: str


class Planner:
    """Pure scaling policy. Feed observations; get decisions."""

    def __init__(
        self,
        decode_policy: PoolPolicy | None = None,
        prefill_policy: PoolPolicy | None = None,
        # queue depth that saturates the prefill pressure signal per replica
        prefill_queue_per_worker: int = 4,
        rebalance_policy: RebalancePolicy | None = None,
    ):
        self.decode_policy = decode_policy or PoolPolicy()
        self.prefill_policy = prefill_policy or PoolPolicy()
        self.prefill_queue_per_worker = prefill_queue_per_worker
        self.rebalance_policy = rebalance_policy or RebalancePolicy()
        self._decode = _PoolState()
        self._prefill = _PoolState()
        self._rebalance = _PoolState()

    # ---------------- signals ----------------

    @staticmethod
    def decode_pressure(loads) -> float:
        """Max of slot-, queue- and KV-pressure across the decode pool (any
        one of them saturating means the pool needs help)."""
        if not loads:
            return 0.0
        n = len(loads)
        slot = sum(w.request_load_ratio for w in loads) / n
        kv = sum(w.kv_load_ratio for w in loads) / n
        waiting = sum(w.num_requests_waiting for w in loads)
        total_slots = sum(max(1, w.request_total_slots) for w in loads)
        queue = min(1.0, waiting / total_slots)
        return max(slot, kv, queue)

    def prefill_pressure(self, queue_depth: int, replicas: int) -> float:
        cap = max(1, replicas) * self.prefill_queue_per_worker
        return min(1.0, queue_depth / cap)

    # ---------------- policy ----------------

    def _evaluate(
        self, state: _PoolState, policy: PoolPolicy, component: str,
        current: int, pressure: float, now: float,
    ) -> ScaleDecision:
        if pressure >= policy.up_threshold:
            state.above += 1
            state.below = 0
        elif pressure <= policy.down_threshold:
            state.below += 1
            state.above = 0
        else:
            state.above = state.below = 0

        desired = current
        reason = f"pressure={pressure:.2f} steady"
        in_cooldown = (now - state.last_change) < policy.cooldown_s
        if state.above >= policy.sustain and not in_cooldown:
            desired = min(policy.max_replicas, current + 1)
            reason = f"pressure={pressure:.2f} >= {policy.up_threshold} x{state.above}"
        elif state.below >= policy.sustain and not in_cooldown:
            desired = max(policy.min_replicas, current - 1)
            reason = f"pressure={pressure:.2f} <= {policy.down_threshold} x{state.below}"
        desired = max(policy.min_replicas, min(policy.max_replicas, desired))
        if desired != current:
            state.last_change = now
            state.above = state.below = 0
        return ScaleDecision(component, current, desired, reason)

    def rebalance(
        self, workers: list, now: Optional[float] = None
    ) -> Optional[RebalanceDecision]:
        """Hot-spot rebalancing off the /cluster/status signals: pick the
        hottest and coldest migration-capable workers and, when the skew
        sustains past the thresholds, recommend migrating load hot -> cold.

        ``workers``: dicts with ``worker_id`` (hex str), ``occupancy``
        (KV page-pool fraction), ``goodput`` (windowed SLO-met fraction or
        None), ``servable`` (bool), ``migration`` (bool, adopts handoffs).
        Pure policy — testable without a cluster."""
        now = time.monotonic() if now is None else now
        pol = self.rebalance_policy
        state = self._rebalance
        eligible = [
            w for w in workers
            if w.get("servable", True) and w.get("migration", True)
        ]
        decision = None
        if len(eligible) >= 2:
            hot = max(eligible, key=lambda w: w.get("occupancy", 0.0))
            cold = min(eligible, key=lambda w: w.get("occupancy", 0.0))
            occ_hot = hot.get("occupancy", 0.0)
            occ_cold = cold.get("occupancy", 0.0)
            gp = hot.get("goodput")
            # burning = the hot worker is actively spending SLO budget: its
            # windowed goodput sits under the floor, OR its own two-window
            # burn-rate alert is firing (the flight-recorder signal; absent
            # key = False, so pre-burn-rate fleets behave unchanged)
            burn_alert = bool(hot.get("burn_alert"))
            burning = (gp is not None and gp < pol.goodput_floor) or burn_alert
            if (
                hot is not cold
                and occ_cold <= pol.occupancy_cold
                and (occ_hot >= pol.occupancy_hot
                     or (burning and occ_hot > occ_cold))
            ):
                reason = (
                    f"occupancy {occ_hot:.2f}->{occ_cold:.2f}"
                    + (f", goodput {gp:.2f} < {pol.goodput_floor}"
                       if gp is not None and gp < pol.goodput_floor else "")
                    + (
                        ", burn-rate alert "
                        + ",".join(hot.get("burn_alerting") or ("?",))
                        if burn_alert else ""
                    )
                )
                decision = RebalanceDecision(
                    source=str(hot.get("worker_id")),
                    target=str(cold.get("worker_id")),
                    reason=reason,
                )
        if decision is None:
            state.above = 0
            return None
        state.above += 1
        in_cooldown = (now - state.last_change) < pol.cooldown_s
        if state.above < pol.sustain or in_cooldown:
            return None
        state.last_change = now
        state.above = 0
        return decision

    def observe(
        self,
        decode_loads,  # list[WorkerLoad] scraped from the decode pool
        prefill_queue_depth: int,
        decode_replicas: int,
        prefill_replicas: int,
        now: Optional[float] = None,
        decode_component: str = "worker",
        prefill_component: str = "prefill-worker",
    ) -> list[ScaleDecision]:
        now = time.monotonic() if now is None else now
        return [
            self._evaluate(
                self._decode, self.decode_policy, decode_component,
                decode_replicas, self.decode_pressure(decode_loads), now,
            ),
            self._evaluate(
                self._prefill, self.prefill_policy, prefill_component,
                prefill_replicas, self.prefill_pressure(prefill_queue_depth, prefill_replicas), now,
            ),
        ]


def desired_replicas_key(namespace: str, component: str) -> str:
    return f"planner/{namespace}/desired/{component}"


def migrate_key(namespace: str, component: str) -> str:
    """Control-plane KV key the planner publishes hot-spot rebalance
    decisions under; the supervisor/operator executes them by POSTing the
    source worker's /admin/drain with the target instance."""
    return f"planner/{namespace}/migrate/{component}"


def demand_key(namespace: str, component: str) -> str:
    """Control-plane KV key the planner publishes the per-tenant demand
    signal under (ROADMAP item 1): windowed device-seconds burn per tenant
    from the fleet's cost broadcasts (utils/metering.py), the measured-
    consumption input an SLO-driven profile planner scales from."""
    return f"planner/{namespace}/demand/{component}"


class PlannerService:
    """Scrapes signals, runs the policy, publishes desired replicas to the
    control-plane KV (watchable by the reconciler / serve supervisor)."""

    def __init__(
        self,
        drt,
        namespace: str,
        decode_component: str = "worker",
        prefill_component: str = "prefill-worker",
        prefill_queue: Optional[str] = None,
        planner: Optional[Planner] = None,
        interval: float = 5.0,
        execute_rebalance: bool = True,
        execute_cooldown_s: float = 120.0,
    ):
        from dynamo_tpu.llm.kv_router.metrics_aggregator import KvMetricsAggregator

        self.drt = drt
        self.namespace = namespace
        self.decode_component = decode_component
        self.prefill_component = prefill_component
        self.prefill_queue = prefill_queue or f"{namespace}.prefill"
        self.planner = planner or Planner()
        self.interval = interval
        self.aggregator = KvMetricsAggregator(drt.cplane, namespace, decode_component)
        self._task: Optional[asyncio.Task] = None
        self.decisions: list[ScaleDecision] = []  # latest round
        self.rebalance_decision: Optional[RebalanceDecision] = None
        # the supervisor leg (PR 14 follow-up): ACT on our own published
        # migrate decisions by POSTing the source worker's /admin/drain
        # (address from its stats broadcast) instead of waiting for an
        # external operator loop; its own cooldown on top of the policy's so
        # a republished decision can't re-drain the same worker back-to-back
        self.execute_rebalance = execute_rebalance
        self.execute_cooldown_s = execute_cooldown_s
        self._last_execute = float("-inf")
        self.rebalance_executed = 0
        self.rebalance_execute_failures = 0
        # per-tenant demand signal (ROADMAP item 1): the cost broadcasts
        # carry CUMULATIVE device-seconds; successive scrapes difference
        # into a per-interval burn so the planner sees current demand, not
        # lifetime totals. tenant_demand is the latest window's burn.
        self._last_burn: dict[str, float] = {}
        self.tenant_demand: dict[str, float] = {}

    async def start(self) -> None:
        self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()

    async def _replica_count(self, component: str) -> int:
        prefix = f"instances/{self.namespace}/components/{component}/"
        try:
            kvs = await self.drt.cplane.kv_get_prefix(prefix)
            return max(1, len(kvs))
        except Exception:
            return 1

    async def step(self) -> list[ScaleDecision]:
        # scrape_once returns the SERVABLE fleet view only: workers aged out
        # after max_missed_scrapes silent rounds, or whose scraped health is
        # draining/dead, never feed the pressure signals — a dead worker's
        # frozen "all slots free" snapshot would otherwise hold scale-down
        # decisions open forever (see llm/kv_router/metrics_aggregator.py)
        loads = await self.aggregator.scrape_once()
        try:
            depth = await self.drt.cplane.queue_depth(self.prefill_queue)
        except Exception:
            depth = 0
        demand = self.observe_tenant_burn()
        events.emit(
            "planner.observe", request_id="",
            workers=len(loads), prefill_queue_depth=depth,
            burn_alerts=sum(
                1 for w in self._rebalance_inputs() if w.get("burn_alert")
            ),
            tenants_burning=len(demand),
            top_tenant=max(demand, key=demand.get) if demand else "",
        )
        if demand:
            await self.drt.cplane.kv_put(
                demand_key(self.namespace, self.decode_component),
                json.dumps({"tenants": demand, "ts": time.time()}).encode(),
            )
        decisions = self.planner.observe(
            loads,
            depth,
            await self._replica_count(self.decode_component),
            await self._replica_count(self.prefill_component),
            decode_component=self.decode_component,
            prefill_component=self.prefill_component,
        )
        self.decisions = decisions
        for d in decisions:
            await self.drt.cplane.kv_put(
                desired_replicas_key(self.namespace, d.component),
                json.dumps(
                    {"replicas": d.desired, "reason": d.reason, "ts": time.time()}
                ).encode(),
            )
            if d.is_change:
                log.info(
                    "scale %s: %d -> %d (%s)", d.component, d.current, d.desired, d.reason
                )
                events.emit(
                    "planner.decide", request_id="",
                    action="scale", component=d.component,
                    current=d.current, desired=d.desired, reason=d.reason,
                )
        # hot-spot rebalancing (live migration): occupancy/goodput-burn skew
        # across the decode pool becomes a migrate-hot-to-cold decision
        rebalance = self.planner.rebalance(self._rebalance_inputs())
        self.rebalance_decision = rebalance
        if rebalance is not None:
            await self.drt.cplane.kv_put(
                migrate_key(self.namespace, self.decode_component),
                json.dumps({
                    "source": rebalance.source, "target": rebalance.target,
                    "reason": rebalance.reason, "ts": time.time(),
                }).encode(),
            )
            log.info(
                "rebalance %s: migrate %s -> %s (%s)",
                self.decode_component, rebalance.source, rebalance.target,
                rebalance.reason,
            )
            events.emit(
                "planner.decide", request_id="",
                action="rebalance", source=rebalance.source,
                target=rebalance.target, reason=rebalance.reason,
            )
            if self.execute_rebalance:
                await self._execute(rebalance)
        return decisions

    async def _execute(self, decision: RebalanceDecision) -> None:
        """Act on a published rebalance decision: POST the source worker's
        /admin/drain naming the target instance (migrate-then-die; the
        worker's drain handles peers/failure arms). Cooldown-guarded so a
        decision republished across scrape rounds drains once; a source
        with no admin surface in its stats broadcast is skipped (logged) —
        the decision stays published for an operator to act on."""
        now = time.monotonic()
        if (now - self._last_execute) < self.execute_cooldown_s:
            return
        addr = None
        for view in self.aggregator.worker_views():
            if f"{view.instance_id:x}" == decision.source:
                addr = (view.data.get("admin") or {}).get("address")
                break
        if not addr:
            log.warning(
                "rebalance execute skipped: source %s advertises no admin "
                "address (run the worker with --admin-port, or drain it "
                "manually)", decision.source,
            )
            return
        self._last_execute = now
        events.emit(
            "planner.execute", request_id="",
            action="drain", source=decision.source, target=decision.target,
        )
        import aiohttp

        try:
            async with aiohttp.ClientSession() as session:
                async with session.post(
                    f"http://{addr}/admin/drain",
                    json={"target": decision.target},
                    timeout=aiohttp.ClientTimeout(total=300),
                ) as resp:
                    body = await resp.json()
                    if resp.status != 200:
                        raise RuntimeError(f"drain answered {resp.status}: {body}")
            self.rebalance_executed += 1
            log.info(
                "rebalance executed: drained %s -> %s (%s)",
                decision.source, decision.target, body,
            )
        except Exception:
            self.rebalance_execute_failures += 1
            log.exception(
                "rebalance execute failed for %s -> %s",
                decision.source, decision.target,
            )

    def render_metrics(self) -> str:
        """Planner-plane exposition (the rebalance executor's audit trail)."""
        from dynamo_tpu.utils.prometheus import render_family

        return render_family(
            "dynamo_planner_rebalance_executed_total", "counter",
            "planner-published rebalance decisions the supervisor executed "
            "by POSTing the source worker's /admin/drain (result=error = "
            "the drain call failed; the decision stays published)",
            [({"result": "ok"}, self.rebalance_executed),
             ({"result": "error"}, self.rebalance_execute_failures)],
        )

    def observe_tenant_burn(self) -> dict[str, float]:
        """Per-tenant demand from the scraped cost broadcasts (ROADMAP
        item 1's measured-consumption input): sum each tenant's CUMULATIVE
        attributed device-seconds across the fleet, difference against the
        previous scrape, and return this window's burn. Monotonic-counter
        discipline: a shrinking sum (worker restarted or aged out) resets
        that tenant's baseline instead of reporting negative demand."""
        totals: dict[str, float] = {}
        for view in self.aggregator.worker_views():
            costs = view.data.get("costs") or {}
            for tenant, row in (costs.get("tenants") or {}).items():
                if not tenant:
                    continue  # system/untagged work is not tenant demand
                totals[tenant] = (
                    totals.get(tenant, 0.0) + (row.get("device_s") or 0.0)
                )
        demand = {}
        for tenant, s in totals.items():
            prev = self._last_burn.get(tenant, 0.0)
            if s > prev:
                demand[tenant] = round(s - prev, 6)
        self._last_burn = totals
        self.tenant_demand = demand
        return demand

    def _rebalance_inputs(self) -> list[dict]:
        """Per-worker rebalance signals from the scraped fleet view: KV
        occupancy, windowed goodput, servability, migration capability."""
        out = []
        for view in self.aggregator.worker_views():
            res = view.data.get("resources") or {}
            total = res.get("kv_pages_total") or 0
            used = res.get("kv_pages_used", 0)
            gp = view.data.get("goodput") or {}
            # burn-rate verdict off the worker's SLO broadcast (read-only:
            # the planner consumes the two-window alert, never recomputes it)
            burn = (view.data.get("slo") or {}).get("burn") or {}
            out.append({
                "worker_id": f"{view.instance_id:x}",
                "occupancy": (used / total) if total else 0.0,
                "goodput": gp.get("goodput"),
                "servable": view.servable,
                "migration": bool(
                    (view.data.get("migration") or {}).get("enabled", False)
                ),
                "burn_alert": bool(burn.get("alerting")),
                "burn_alerting": list(burn.get("alerting") or ()),
            })
        return out

    async def _loop(self) -> None:
        try:
            while True:
                try:
                    await self.step()
                except Exception:
                    log.exception("planner step failed")
                await asyncio.sleep(self.interval)
        except asyncio.CancelledError:
            pass
