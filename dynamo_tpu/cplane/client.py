"""Async client for the control-plane broker.

API surface mirrors what the runtime needs from etcd+NATS
(reference: lib/runtime/src/transports/etcd.rs:52-431, nats.rs:44-831):
kv_create/kv_put/kv_get_prefix/kv_get_and_watch_prefix, leases with keepalive
coupled to a cancellation callback, publish/subscribe/request, work queues.
"""

from __future__ import annotations

import asyncio
import itertools
import secrets
import uuid
from dataclasses import dataclass
from typing import Any, AsyncIterator, Callable, Optional

from dynamo_tpu.cplane.wire import read_frame, write_frame
from dynamo_tpu.utils import get_logger

log = get_logger("cplane.client")


@dataclass
class WatchEvent:
    kind: str  # put | delete
    key: str
    value: Optional[bytes]
    lease_id: int = 0


@dataclass
class KvItem:
    key: str
    value: bytes
    lease_id: int = 0


@dataclass
class QueueMessage:
    msg_id: int
    payload: Any


class PrefixWatcher:
    """Initial snapshot + live event stream for a key prefix."""

    def __init__(self, watch_id: int, items: list[KvItem], queue: asyncio.Queue, client: "CplaneClient"):
        self.watch_id = watch_id
        self.initial = items
        self._queue = queue
        self._client = client

    async def __aiter__(self) -> AsyncIterator[WatchEvent]:
        while True:
            ev = await self._queue.get()
            if ev is None:
                return
            yield ev

    async def events(self) -> AsyncIterator[WatchEvent]:
        async for ev in self.__aiter__():
            yield ev

    async def stop(self) -> None:
        await self._client._unwatch(self.watch_id)


class Lease:
    def __init__(self, client: "CplaneClient", lease_id: int, ttl: float,
                 secret: str):
        self.client = client
        self.lease_id = lease_id
        self.ttl = ttl
        # ownership proof for re-adoption and keepalive/revoke: lease ids are
        # broadcast to every watcher, so the bare id must not be enough to
        # hijack the lease. Minted once, in CplaneClient.lease_create — the
        # broker must see the same secret the Lease object carries.
        self.secret = secret
        self._task: Optional[asyncio.Task] = None
        self.on_expired: Optional[Callable[[], None]] = None

    def start_keepalive(self) -> None:
        self._task = asyncio.create_task(self._keepalive_loop())

    async def _keepalive_loop(self) -> None:
        interval = max(0.2, self.ttl / 3)
        failures_since = None
        try:
            while True:
                await asyncio.sleep(interval)
                try:
                    await self.client._request(
                        {"op": "lease_keepalive", "lease_id": self.lease_id,
                         "secret": self.secret}
                    )
                    failures_since = None
                except Exception as e:
                    if isinstance(e, RuntimeError) and "expired" in str(e):
                        # broker is up but forgot the lease (TTL'd out during
                        # a stall): re-adopt it under its original id — the id
                        # names endpoint subjects — and re-register owners
                        try:
                            await self.client._request(
                                {"op": "lease_create", "ttl": self.ttl,
                                 "lease_id": self.lease_id, "secret": self.secret}
                            )
                            for hook in list(self.client.reconnect_hooks):
                                await hook()
                            log.warning("lease %x re-established after expiry", self.lease_id)
                            failures_since = None
                            continue
                        except Exception:
                            pass
                    # transient: the client's reconnect re-attaches this lease
                    # under its original id; declare it dead only after the
                    # reconnect window has clearly elapsed without healing
                    now = asyncio.get_running_loop().time()
                    if failures_since is None:
                        failures_since = now
                    elapsed = now - failures_since
                    log.warning(
                        "lease %x keepalive failed (%.0fs): %s", self.lease_id, elapsed, e
                    )
                    if elapsed > self.client.reconnect_window:
                        if self.on_expired:
                            self.on_expired()
                        return
        except asyncio.CancelledError:
            pass

    async def revoke(self) -> None:
        if self._task:
            self._task.cancel()
        self.client._leases.pop(self.lease_id, None)
        try:
            await self.client._request(
                {"op": "lease_revoke", "lease_id": self.lease_id,
                 "secret": self.secret}
            )
        except Exception:
            pass


class CplaneClient:
    def __init__(
        self,
        address: str = "127.0.0.1:4222",
        reconnect_window: float = 30.0,
    ):
        host, _, port = address.rpartition(":")
        self.host, self.port = host or "127.0.0.1", int(port)
        self.reconnect_window = reconnect_window
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._rids = itertools.count(1)
        self._watch_ids = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._watch_queues: dict[int, asyncio.Queue] = {}
        self._watch_prefixes: dict[int, str] = {}
        self._watch_seen: dict[int, set[str]] = {}
        self._sub_handlers: dict[str, Callable[[dict], None]] = {}
        self._leases: dict[int, "Lease"] = {}
        self._reader_task: Optional[asyncio.Task] = None
        self._reconnect_task: Optional[asyncio.Task] = None
        self._up: Optional[asyncio.Event] = None
        self._closed = False
        self._dead = False  # reconnect window exhausted or closed
        # one deadline for the WHOLE outage: replay failures re-enter
        # _reconnect without resetting it, so a deterministic replay error
        # can't retry forever
        self._heal_deadline: Optional[float] = None
        # called when the broker connection is lost FOR GOOD (reconnect window
        # exhausted); transient drops are healed transparently
        self.on_disconnect: Optional[Callable[[], None]] = None
        # async hooks run after a successful reconnect + state replay (e.g.
        # ServedEndpoint re-registration)
        self.reconnect_hooks: list[Callable] = []

    # ------------- lifecycle -------------

    async def connect(self) -> "CplaneClient":
        self._reader, self._writer = await asyncio.open_connection(self.host, self.port)
        self._up = asyncio.Event()
        self._up.set()
        self._reader_task = asyncio.create_task(self._read_loop())
        return self

    async def close(self) -> None:
        self._closed = True
        self._dead = True
        if self._up is not None:
            self._up.set()  # release any parked _request() waiters
        if self._reader_task:
            self._reader_task.cancel()
        if self._reconnect_task:
            self._reconnect_task.cancel()
        if self._writer:
            self._writer.close()

    async def _read_loop(self) -> None:
        try:
            while True:
                msg = await read_frame(self._reader)
                self._handle(msg)
        except (asyncio.IncompleteReadError, ConnectionResetError, asyncio.CancelledError):
            pass
        finally:
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("broker connection lost"))
            self._pending.clear()
            if not self._closed:
                self._up.clear()
                self._reconnect_task = asyncio.create_task(self._reconnect())

    def _give_up(self) -> None:
        self._dead = True
        if self._up is not None:
            self._up.set()  # release parked _request() waiters to fail fast
        for q in self._watch_queues.values():
            q.put_nowait(None)
        if not self._closed and self.on_disconnect:
            self.on_disconnect()

    async def _reconnect(self) -> None:
        """Heal the broker connection: backoff-retry within reconnect_window,
        then replay session state — subscriptions, watches (with a
        seen-key diff so missed deletes surface as synthetic events), and
        leases (re-attached under their original ids, which name endpoint
        subjects) — and finally run the registered reconnect hooks
        (reference: etcd.rs lease keep-alive + client retry semantics)."""
        loop = asyncio.get_running_loop()
        if self._heal_deadline is None:
            self._heal_deadline = loop.time() + self.reconnect_window
        deadline = self._heal_deadline
        delay = 0.2
        while not self._closed:
            try:
                self._reader, self._writer = await asyncio.open_connection(self.host, self.port)
                break
            except OSError:
                if loop.time() + delay > deadline:
                    log.warning(
                        "broker %s:%d unreachable for %.0fs; giving up",
                        self.host, self.port, self.reconnect_window,
                    )
                    self._give_up()
                    return
                await asyncio.sleep(delay)
                delay = min(delay * 2, 2.0)
        if self._closed:
            return
        self._reader_task = asyncio.create_task(self._read_loop())
        # NOTE: _up stays CLEAR until the replay below (lease re-adoption,
        # resubscribe, watch resync) finishes — otherwise a lease-attached op
        # racing the replay (kv_put with lease_id, queue ack) can reach the
        # broker before its lease exists again and fail with "lease not
        # found" even though the outage heals moments later. The replay's own
        # _request calls bypass the park (the socket is live already).
        try:
            for lease in list(self._leases.values()):
                await self._request(
                    {"op": "lease_create", "ttl": lease.ttl,
                     "lease_id": lease.lease_id, "secret": lease.secret},
                    _replay=True,
                )
            for subject in list(self._sub_handlers):
                await self._request({"op": "subscribe", "subject": subject}, _replay=True)
            for watch_id, prefix in list(self._watch_prefixes.items()):
                r = await self._request(
                    {"op": "watch", "watch_id": watch_id, "prefix": prefix},
                    _replay=True,
                )
                q = self._watch_queues.get(watch_id)
                if q is None:
                    continue
                now = {i["key"]: i for i in r["items"]}
                seen = self._watch_seen.setdefault(watch_id, set())
                for key in seen - now.keys():
                    q.put_nowait(WatchEvent(kind="delete", key=key, value=None))
                for key, item in now.items():
                    q.put_nowait(
                        WatchEvent(kind="put", key=key, value=item["value"],
                                   lease_id=item["lease_id"])
                    )
                self._watch_seen[watch_id] = set(now)
            # replay done: release parked requests (hooks below may _request)
            self._up.set()
            for hook in list(self.reconnect_hooks):
                await hook()
            self._heal_deadline = None  # fully healed: next outage gets a fresh window
            log.info("broker connection healed (%s:%d)", self.host, self.port)
        except Exception:
            if loop.time() > deadline:
                log.exception("reconnect replay kept failing past the window; giving up")
                self._give_up()
                return
            log.exception("reconnect replay failed; retrying")
            await asyncio.sleep(min(1.0, max(0.2, delay)))
            try:
                self._writer.close()
            except Exception:
                pass

    def _handle(self, msg: dict) -> None:
        if "rid" in msg and msg["rid"] is not None:
            fut = self._pending.pop(msg["rid"], None)
            if fut is not None and not fut.done():
                if msg.get("ok"):
                    fut.set_result(msg)
                else:
                    fut.set_exception(RuntimeError(msg.get("error", "broker error")))
            return
        event = msg.get("event")
        if event == "watch":
            q = self._watch_queues.get(msg["watch_id"])
            if q is not None:
                seen = self._watch_seen.setdefault(msg["watch_id"], set())
                if msg["kind"] == "put":
                    seen.add(msg["key"])
                else:
                    seen.discard(msg["key"])
                q.put_nowait(
                    WatchEvent(
                        kind=msg["kind"], key=msg["key"], value=msg.get("value"),
                        lease_id=msg.get("lease_id", 0),
                    )
                )
        elif event == "message":
            handler = self._sub_handlers.get(msg["subject"])
            if handler is not None:
                handler(msg)

    async def _request(self, msg: dict, _replay: bool = False) -> dict:
        if not _replay and self._up is not None and not self._up.is_set() and not self._closed:
            # connection is healing: park briefly instead of failing fast
            try:
                await asyncio.wait_for(self._up.wait(), self.reconnect_window)
            except asyncio.TimeoutError:
                raise ConnectionError("broker connection lost")
        if self._dead or self._closed:
            raise ConnectionError("broker connection lost")
        rid = next(self._rids)
        msg["rid"] = rid
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        await write_frame(self._writer, msg)
        return await fut

    # ------------- KV -------------

    async def kv_put(self, key: str, value: bytes, lease_id: int = 0) -> int:
        r = await self._request({"op": "kv_put", "key": key, "value": value, "lease_id": lease_id})
        return r["revision"]

    async def kv_create(self, key: str, value: bytes, lease_id: int = 0) -> bool:
        """Create-if-absent; returns False if the key already exists."""
        try:
            await self._request({"op": "kv_create", "key": key, "value": value, "lease_id": lease_id})
            return True
        except RuntimeError as e:
            if "exists" in str(e):
                return False
            raise

    async def kv_get(self, key: str) -> Optional[bytes]:
        r = await self._request({"op": "kv_get", "key": key})
        return r["value"] if r.get("found") else None

    async def kv_get_prefix(self, prefix: str) -> list[KvItem]:
        r = await self._request({"op": "kv_get_prefix", "prefix": prefix})
        return [KvItem(key=i["key"], value=i["value"], lease_id=i["lease_id"]) for i in r["items"]]

    async def kv_delete(self, key: str) -> bool:
        r = await self._request({"op": "kv_delete", "key": key})
        return r["deleted"]

    async def kv_get_and_watch_prefix(self, prefix: str) -> PrefixWatcher:
        watch_id = next(self._watch_ids)
        q: asyncio.Queue = asyncio.Queue()
        self._watch_queues[watch_id] = q
        self._watch_prefixes[watch_id] = prefix
        r = await self._request({"op": "watch", "watch_id": watch_id, "prefix": prefix})
        items = [KvItem(key=i["key"], value=i["value"], lease_id=i["lease_id"]) for i in r["items"]]
        self._watch_seen[watch_id] = {i.key for i in items}
        return PrefixWatcher(watch_id, items, q, self)

    async def _unwatch(self, watch_id: int) -> None:
        self._watch_queues.pop(watch_id, None)
        self._watch_prefixes.pop(watch_id, None)
        self._watch_seen.pop(watch_id, None)
        await self._request({"op": "unwatch", "watch_id": watch_id})

    # ------------- leases -------------

    async def lease_create(self, ttl: float = 10.0) -> Lease:
        secret = secrets.token_hex(16)
        r = await self._request({"op": "lease_create", "ttl": ttl, "secret": secret})
        lease = Lease(self, r["lease_id"], r["ttl"], secret=secret)
        self._leases[lease.lease_id] = lease
        lease.start_keepalive()
        return lease

    # ------------- subjects -------------

    async def subscribe(self, subject: str, handler: Callable[[dict], None]) -> None:
        self._sub_handlers[subject] = handler
        await self._request({"op": "subscribe", "subject": subject})

    async def unsubscribe(self, subject: str) -> None:
        self._sub_handlers.pop(subject, None)
        await self._request({"op": "unsubscribe", "subject": subject})

    async def publish(self, subject: str, payload: Any, reply: Optional[str] = None) -> int:
        r = await self._request({"op": "publish", "subject": subject, "payload": payload, "reply": reply})
        return r["delivered"]

    async def request_subject(self, subject: str, payload: Any, timeout: float = 30.0) -> Any:
        """NATS-style request/reply over an inbox subject."""
        inbox = f"_INBOX.{uuid.uuid4().hex}"
        fut: asyncio.Future = asyncio.get_running_loop().create_future()

        def on_reply(msg: dict) -> None:
            if not fut.done():
                fut.set_result(msg["payload"])

        await self.subscribe(inbox, on_reply)
        try:
            delivered = await self.publish(subject, payload, reply=inbox)
            if delivered == 0:
                raise ConnectionError(f"no responders on {subject}")
            return await asyncio.wait_for(fut, timeout)
        finally:
            await self.unsubscribe(inbox)

    # ------------- queues -------------

    async def queue_push(self, queue: str, payload: Any) -> int:
        r = await self._request({"op": "queue_push", "queue": queue, "payload": payload})
        return r["msg_id"]

    async def queue_pull(self, queue: str, timeout: Optional[float] = None) -> QueueMessage:
        coro = self._request({"op": "queue_pull", "queue": queue})
        r = await (asyncio.wait_for(coro, timeout) if timeout else coro)
        return QueueMessage(msg_id=r["msg_id"], payload=r["payload"])

    async def queue_ack(self, queue: str, msg_id: int) -> None:
        await self._request({"op": "queue_ack", "queue": queue, "msg_id": msg_id})

    async def queue_nack(self, queue: str, msg_id: int) -> None:
        await self._request({"op": "queue_nack", "queue": queue, "msg_id": msg_id})

    async def queue_depth(self, queue: str) -> int:
        return (await self.queue_info(queue))["depth"]

    async def queue_info(self, queue: str) -> dict:
        """{depth, inflight, waiters} — waiters counts parked pulls (a live
        consumer is listening)."""
        return await self._request({"op": "queue_depth", "queue": queue})

    async def ping(self) -> float:
        r = await self._request({"op": "ping"})
        return r["now"]
