"""Broker wire protocol: 4-byte big-endian length prefix + msgpack payload."""

from __future__ import annotations

import asyncio
import struct
from typing import Any

import msgpack

MAX_FRAME = 64 * 1024 * 1024


def pack(obj: Any) -> bytes:
    payload = msgpack.packb(obj, use_bin_type=True)
    return struct.pack(">I", len(payload)) + payload


async def read_frame(reader: asyncio.StreamReader) -> Any:
    header = await reader.readexactly(4)
    (length,) = struct.unpack(">I", header)
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    payload = await reader.readexactly(length)
    return msgpack.unpackb(payload, raw=False)


async def write_frame(writer: asyncio.StreamWriter, obj: Any) -> None:
    writer.write(pack(obj))
    await writer.drain()
