"""The control-plane broker: KV + leases + watches + pub/sub + work queues in
one asyncio TCP server.

Fills the role of the reference's infra pair (reference: deploy/docker-compose.yml
runs etcd + nats-server -js):

  - KV with create-if-absent txn, prefix get, prefix watch
    (reference: lib/runtime/src/transports/etcd.rs:52-431)
  - leases with TTL + keepalive; expiry deletes attached keys and notifies
    watchers (reference: lib/runtime/src/transports/etcd/lease.rs)
  - subjects: fire-and-forget publish to subscribers; request/reply with a
    single responder (the request plane, reference: transports/nats.rs)
  - durable work queues with pull + ack/nack semantics (the prefill queue,
    reference: examples/llm/utils/nats_queue.py JetStream work-queue)

Run standalone:  python -m dynamo_tpu.cplane.broker --port 4222
"""

from __future__ import annotations

import argparse
import asyncio
import hmac
import itertools
import time
from collections import defaultdict, deque
from dataclasses import dataclass, field
from typing import Any, Optional

from dynamo_tpu.cplane.wire import read_frame, write_frame
from dynamo_tpu.utils import get_logger

log = get_logger("cplane.broker")

DEFAULT_LEASE_TTL = 10.0

# sentinel: handler parked the request and will respond later (queue pulls)
DEFER = object()


@dataclass
class _Conn:
    conn_id: int
    writer: asyncio.StreamWriter
    send_queue: asyncio.Queue = field(default_factory=asyncio.Queue)
    subscriptions: set[str] = field(default_factory=set)
    watches: dict[int, str] = field(default_factory=dict)  # watch_id -> prefix
    leases: set[int] = field(default_factory=set)
    closed: bool = False


@dataclass
class _Lease:
    lease_id: int
    ttl: float
    conn_id: int
    expires_at: float
    keys: set[str] = field(default_factory=set)
    # client-minted ownership proof: required to re-adopt the lease id on a
    # new connection (ids are broadcast to watchers; the id alone must not
    # let a peer hijack another worker's endpoint identity)
    secret: str = ""


@dataclass
class _QueueMsg:
    msg_id: int
    payload: Any
    delivered_to: Optional[int] = None  # conn_id while in-flight


class Broker:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        persist_path: Optional[str] = None,
        latency: Optional[tuple[float, float]] = None,
    ):
        """latency: (mean_s, jitter_s) injected before every op — the
        reference's mock-network latency models (NoDelay/Constant/
        NormalDistribution, lib/runtime/tests/common/mock.rs) slot: lets
        tests simulate a slow control plane without a cluster. Also settable
        via DYNTPU_CPLANE_LATENCY_MS / DYNTPU_CPLANE_JITTER_MS on the module
        main."""
        self.host = host
        self.port = port
        self.persist_path = persist_path
        self.latency = latency
        self._persist_file = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: dict[int, _Conn] = {}
        self._conn_ids = itertools.count(1)
        # lease ids must be unique ACROSS broker incarnations: a reconnecting
        # client re-adopts its lease by id (proof of ownership), so a restarted
        # broker handing out the same small ids again would let that reattach
        # hijack a new client's lease. Seed the counter from wall time.
        self._lease_ids = itertools.count(((int(time.time()) & 0xFFFFFFFF) << 16) | 0x1000)
        self._watch_event_ids = itertools.count(1)
        self._msg_ids = itertools.count(1)

        self._kv: dict[str, dict] = {}  # key -> {value, lease_id, revision}
        self._revision = 0
        self._leases: dict[int, _Lease] = {}
        self._subs: dict[str, set[int]] = defaultdict(set)  # subject -> conn ids
        self._queues: dict[str, deque[_QueueMsg]] = defaultdict(deque)
        self._inflight: dict[tuple[str, int], _QueueMsg] = {}
        self._queue_waiters: dict[str, deque] = defaultdict(deque)
        self._stopped = asyncio.Event()

    # ------------- lifecycle -------------

    async def start(self) -> int:
        if self.persist_path:
            self._load_persist()
        self._server = await asyncio.start_server(self._handle_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self._reaper_task = asyncio.create_task(self._lease_reaper())
        log.info("broker listening on %s:%d", self.host, self.port)
        return self.port

    async def stop(self) -> None:
        self._stopped.set()
        if getattr(self, "_reaper_task", None) is not None:
            self._reaper_task.cancel()
        if self._server:
            self._server.close()
        # close live connections BEFORE wait_closed(): on 3.12+ wait_closed
        # blocks until every connection handler finishes
        for conn in list(self._conns.values()):
            conn.writer.close()
        if self._server:
            await self._server.wait_closed()
        if self._persist_file is not None:
            self._persist_file.close()
            self._persist_file = None

    # ------------- persistence (append-log + compaction on load) -------------
    #
    # Durable state = non-lease KV and work-queue contents (the reference's
    # etcd raft log + JetStream file store, transports/etcd.rs / nats.rs).
    # Lease-attached keys are deliberately NOT persisted: leases die with
    # their connections, and owners re-register through the client's
    # reconnect hooks.

    def _load_persist(self) -> None:
        import os

        import msgpack

        records = []
        if os.path.exists(self.persist_path):
            with open(self.persist_path, "rb") as f:
                unpacker = msgpack.Unpacker(f, raw=False)
                try:
                    for rec in unpacker:
                        records.append(rec)
                except Exception:
                    log.warning("persist log tail truncated; recovering prefix")
        max_msg_id = 0
        for rec in records:
            op = rec.get("op")
            if op == "kv_put":
                self._revision += 1
                self._kv[rec["key"]] = {
                    "value": rec["value"], "lease_id": 0, "revision": self._revision
                }
            elif op == "kv_delete":
                self._kv.pop(rec["key"], None)
            elif op == "queue_push":
                m = _QueueMsg(msg_id=rec["msg_id"], payload=rec["payload"])
                self._queues[rec["queue"]].append(m)
                max_msg_id = max(max_msg_id, rec["msg_id"])
            elif op == "queue_ack":
                q = self._queues[rec["queue"]]
                for m in list(q):
                    if m.msg_id == rec["msg_id"]:
                        q.remove(m)
                        break
        if max_msg_id:
            self._msg_ids = itertools.count(max_msg_id + 1)
        # compact: rewrite current state as a fresh log so growth is bounded
        # by live state per restart, not by history
        tmp = self.persist_path + ".tmp"
        with open(tmp, "wb") as f:
            for key, entry in self._kv.items():
                if entry["lease_id"] == 0:
                    f.write(msgpack.packb({"op": "kv_put", "key": key, "value": entry["value"]}))
            for qname, q in self._queues.items():
                for m in q:
                    f.write(msgpack.packb(
                        {"op": "queue_push", "queue": qname, "msg_id": m.msg_id, "payload": m.payload}
                    ))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.persist_path)
        self._persist_file = open(self.persist_path, "ab")
        if records:
            log.info(
                "persist: recovered %d kv keys, %d queued messages",
                len(self._kv), sum(len(q) for q in self._queues.values()),
            )

    def _log_persist(self, rec: dict) -> None:
        # flush() only (no per-append fsync): deliberate tradeoff — records
        # survive a broker PROCESS crash, not a host power loss. The control
        # plane re-derives liveness state anyway, and per-append fsync would
        # serialize every kv_put/queue_push on disk latency. Set
        # DYNTPU_BROKER_FSYNC=1 for full durability.
        if self._persist_file is None and self.persist_path:
            self._persist_file = open(self.persist_path, "ab")
        if self._persist_file is not None:
            import msgpack
            import os

            self._persist_file.write(msgpack.packb(rec))
            self._persist_file.flush()
            if os.environ.get("DYNTPU_BROKER_FSYNC") == "1":
                os.fsync(self._persist_file.fileno())

    async def serve_forever(self) -> None:
        await self.start()
        await self._stopped.wait()

    # ------------- connection handling -------------

    async def _handle_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        conn = _Conn(conn_id=next(self._conn_ids), writer=writer)
        self._conns[conn.conn_id] = conn
        sender = asyncio.create_task(self._sender(conn))
        delay_line = None
        delay_worker = None
        if self.latency is not None:
            # per-op latency WITHOUT blocking the reader: ops enter a FIFO
            # delay line stamped with their own deadline, so delays overlap
            # (no serial compounding across a pipelined burst) while per-conn
            # ordering is preserved
            import random

            mean, jitter = self.latency
            delay_line: asyncio.Queue = asyncio.Queue()

            async def drain():
                loop = asyncio.get_running_loop()
                while True:
                    deadline, m = await delay_line.get()
                    now = loop.time()
                    if deadline > now:
                        await asyncio.sleep(deadline - now)
                    await self._dispatch(conn, m)

            delay_worker = asyncio.create_task(drain())
        try:
            while True:
                msg = await read_frame(reader)
                if delay_line is not None:
                    d = max(0.0, random.gauss(mean, jitter) if jitter else mean)
                    delay_line.put_nowait((asyncio.get_running_loop().time() + d, msg))
                else:
                    await self._dispatch(conn, msg)
        except (asyncio.IncompleteReadError, ConnectionResetError, BrokenPipeError):
            pass
        except Exception:
            log.exception("connection %d error", conn.conn_id)
        finally:
            if delay_worker is not None:
                delay_worker.cancel()
            conn.closed = True
            self._drop_conn(conn)
            sender.cancel()
            try:
                writer.close()
            except Exception:
                pass

    async def _sender(self, conn: _Conn) -> None:
        try:
            while True:
                msg = await conn.send_queue.get()
                await write_frame(conn.writer, msg)
        except (asyncio.CancelledError, ConnectionResetError, BrokenPipeError):
            pass

    def _send(self, conn: _Conn, msg: dict) -> None:
        if not conn.closed:
            conn.send_queue.put_nowait(msg)

    def _drop_conn(self, conn: _Conn) -> None:
        self._conns.pop(conn.conn_id, None)
        for subject in conn.subscriptions:
            self._subs[subject].discard(conn.conn_id)
        # expire this connection's leases immediately (process death semantics)
        for lease_id in list(conn.leases):
            self._expire_lease(lease_id, reason="conn-closed")
        # nack any in-flight queue messages it held
        for (qname, msg_id), msg in list(self._inflight.items()):
            if msg.delivered_to == conn.conn_id:
                del self._inflight[(qname, msg_id)]
                msg.delivered_to = None
                self._queues[qname].appendleft(msg)
                self._kick_queue(qname)
        # purge its parked pulls so the waiters readiness count stays honest
        for qname, waiters in self._queue_waiters.items():
            self._queue_waiters[qname] = deque(
                (cid, rid) for cid, rid in waiters if cid != conn.conn_id
            )

    # ------------- dispatch -------------

    async def _dispatch(self, conn: _Conn, msg: dict) -> None:
        op = msg.get("op")
        rid = msg.get("rid")
        handler = getattr(self, f"_op_{op}", None)
        if handler is None:
            self._send(conn, {"rid": rid, "ok": False, "error": f"unknown op {op}"})
            return
        try:
            result = handler(conn, msg)
            if asyncio.iscoroutine(result):
                result = await result
            if result is DEFER:
                return
            if result is not None:
                self._send(conn, {"rid": rid, "ok": True, **result})
            else:
                self._send(conn, {"rid": rid, "ok": True})
        except Exception as e:
            self._send(conn, {"rid": rid, "ok": False, "error": str(e)})

    # ------------- KV ops -------------

    def _notify_watchers(self, key: str, value: Optional[bytes], kind: str, lease_id: int) -> None:
        for conn in self._conns.values():
            for watch_id, prefix in conn.watches.items():
                if key.startswith(prefix):
                    self._send(
                        conn,
                        {
                            "event": "watch",
                            "watch_id": watch_id,
                            "kind": kind,  # put | delete
                            "key": key,
                            "value": value,
                            "lease_id": lease_id,
                            "revision": self._revision,
                        },
                    )

    def _op_kv_put(self, conn: _Conn, msg: dict) -> dict:
        key, value = msg["key"], msg["value"]
        lease_id = msg.get("lease_id", 0)
        # validate FIRST: a rejected put must not mutate ownership state
        lease = None
        if lease_id:
            lease = self._leases.get(lease_id)
            if lease is None:
                raise ValueError(f"lease {lease_id} not found")
        # ownership MOVES on re-put: a key re-put under another lease (or with
        # no lease) must leave the previous owner's keys set, or that lease's
        # later expiry would delete a key it no longer owns (e.g. a shared
        # model card kept fresh by several workers' refresh loops)
        prev = self._kv.get(key)
        if prev is not None and prev["lease_id"] not in (0, lease_id):
            old = self._leases.get(prev["lease_id"])
            if old is not None:
                old.keys.discard(key)
        if lease is not None:
            lease.keys.add(key)
        prev = self._kv.get(key)
        self._revision += 1
        self._kv[key] = {"value": value, "lease_id": lease_id, "revision": self._revision}
        if lease_id == 0:
            self._log_persist({"op": "kv_put", "key": key, "value": value})
        elif prev is not None and prev["lease_id"] == 0:
            # persisted key transitions to lease-attached: tombstone the old
            # record or a restart would resurrect the stale non-lease value
            self._log_persist({"op": "kv_delete", "key": key})
        self._notify_watchers(key, value, "put", lease_id)
        return {"revision": self._revision}

    def _op_kv_create(self, conn: _Conn, msg: dict) -> dict:
        """Create-if-absent txn (reference: etcd.rs kv_create)."""
        if msg["key"] in self._kv:
            raise ValueError("key exists")
        return self._op_kv_put(conn, msg)

    def _op_kv_get(self, conn: _Conn, msg: dict) -> dict:
        entry = self._kv.get(msg["key"])
        if entry is None:
            return {"found": False}
        return {"found": True, "value": entry["value"], "lease_id": entry["lease_id"]}

    def _op_kv_get_prefix(self, conn: _Conn, msg: dict) -> dict:
        prefix = msg["prefix"]
        items = [
            {"key": k, "value": v["value"], "lease_id": v["lease_id"]}
            for k, v in sorted(self._kv.items())
            if k.startswith(prefix)
        ]
        return {"items": items, "revision": self._revision}

    def _op_kv_delete(self, conn: _Conn, msg: dict) -> dict:
        entry = self._kv.pop(msg["key"], None)
        if entry is not None:
            self._revision += 1
            if entry["lease_id"] == 0:
                self._log_persist({"op": "kv_delete", "key": msg["key"]})
            self._notify_watchers(msg["key"], None, "delete", entry["lease_id"])
        return {"deleted": entry is not None}

    def _op_watch(self, conn: _Conn, msg: dict) -> dict:
        watch_id = msg["watch_id"]
        conn.watches[watch_id] = msg["prefix"]
        # initial snapshot mirrors kv_get_and_watch_prefix
        items = [
            {"key": k, "value": v["value"], "lease_id": v["lease_id"]}
            for k, v in sorted(self._kv.items())
            if k.startswith(msg["prefix"])
        ]
        return {"items": items}

    def _op_unwatch(self, conn: _Conn, msg: dict) -> dict:
        conn.watches.pop(msg["watch_id"], None)
        return {}

    # ------------- leases -------------

    def _op_lease_create(self, conn: _Conn, msg: dict) -> dict:
        ttl = float(msg.get("ttl", DEFAULT_LEASE_TTL))
        lease_id = msg.get("lease_id") or next(self._lease_ids)
        if msg.get("lease_id"):
            # keep the id generator ahead of reattached ids (which came from a
            # previous broker incarnation's counter)
            nxt = next(self._lease_ids)
            self._lease_ids = itertools.count(max(lease_id + 1, nxt))
        existing = self._leases.get(lease_id)
        if existing is not None:
            if existing.secret and not hmac.compare_digest(
                str(msg.get("secret", "")), existing.secret
            ):
                raise ValueError(f"lease {lease_id} secret mismatch")
            # reattach after a reconnect: a lease id is an identity (it names
            # endpoint subjects/instances), so its owner re-adopts it on a new
            # connection. If an older connection still appears live, it is a
            # half-open leftover of the same client (the id is the proof of
            # ownership): move the lease FIRST — so the old conn's teardown
            # can't expire it — then force the stale conn closed.
            old = self._conns.get(existing.conn_id)
            if old is not None and existing.conn_id != conn.conn_id:
                old.leases.discard(lease_id)
                try:
                    old.writer.close()
                except Exception:
                    pass
            existing.conn_id = conn.conn_id
            existing.ttl = ttl
            existing.expires_at = time.monotonic() + ttl
            conn.leases.add(lease_id)
            return {"lease_id": lease_id, "ttl": ttl}
        self._leases[lease_id] = _Lease(
            lease_id=lease_id, ttl=ttl, conn_id=conn.conn_id,
            expires_at=time.monotonic() + ttl, secret=msg.get("secret", ""),
        )
        conn.leases.add(lease_id)
        return {"lease_id": lease_id, "ttl": ttl}

    def _check_lease_owner(self, conn: _Conn, lease: _Lease, msg: dict) -> None:
        # lease ids are broadcast to every watcher, so the bare id must not be
        # enough to keep a dead worker's lease alive (stale endpoint pinned
        # forever) or to revoke a live worker's lease (its keys deleted). The
        # owner proves itself with the create-time secret, or by speaking on
        # the connection the lease is attached to.
        if lease.conn_id == conn.conn_id:
            return
        if lease.secret and hmac.compare_digest(
            str(msg.get("secret", "")), lease.secret
        ):
            # the owner moved to a new connection: rebind, or the stale
            # conn's eventual teardown would expire a live owner's lease
            old = self._conns.get(lease.conn_id)
            if old is not None:
                old.leases.discard(lease.lease_id)
            lease.conn_id = conn.conn_id
            conn.leases.add(lease.lease_id)
            return
        raise ValueError(f"lease {lease.lease_id} not owned by caller")

    def _op_lease_keepalive(self, conn: _Conn, msg: dict) -> dict:
        lease = self._leases.get(msg["lease_id"])
        if lease is None:
            raise ValueError("lease expired")
        self._check_lease_owner(conn, lease, msg)
        lease.expires_at = time.monotonic() + lease.ttl
        return {"ttl": lease.ttl}

    def _op_lease_revoke(self, conn: _Conn, msg: dict) -> dict:
        lease = self._leases.get(msg["lease_id"])
        if lease is not None:
            self._check_lease_owner(conn, lease, msg)
        self._expire_lease(msg["lease_id"], reason="revoked")
        return {}

    def _expire_lease(self, lease_id: int, reason: str) -> None:
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            return
        conn = self._conns.get(lease.conn_id)
        if conn:
            conn.leases.discard(lease_id)
        for key in lease.keys:
            entry = self._kv.get(key)
            # belt: only delete keys this lease still OWNS
            if entry is not None and entry["lease_id"] == lease_id:
                del self._kv[key]
                self._revision += 1
                self._notify_watchers(key, None, "delete", lease_id)
        log.debug("lease %x expired (%s), %d keys removed", lease_id, reason, len(lease.keys))

    async def _lease_reaper(self) -> None:
        try:
            while not self._stopped.is_set():
                now = time.monotonic()
                for lease_id, lease in list(self._leases.items()):
                    if lease.expires_at < now:
                        self._expire_lease(lease_id, reason="ttl")
                await asyncio.sleep(0.5)
        except asyncio.CancelledError:
            pass

    # ------------- subjects (pub/sub + request) -------------

    def _op_subscribe(self, conn: _Conn, msg: dict) -> dict:
        subject = msg["subject"]
        conn.subscriptions.add(subject)
        self._subs[subject].add(conn.conn_id)
        return {}

    def _op_unsubscribe(self, conn: _Conn, msg: dict) -> dict:
        subject = msg["subject"]
        conn.subscriptions.discard(subject)
        self._subs[subject].discard(conn.conn_id)
        return {}

    def _op_publish(self, conn: _Conn, msg: dict) -> dict:
        subject = msg["subject"]
        n = 0
        for conn_id in list(self._subs.get(subject, ())):
            target = self._conns.get(conn_id)
            if target is not None:
                self._send(
                    target,
                    {"event": "message", "subject": subject, "payload": msg["payload"],
                     "reply": msg.get("reply")},
                )
                n += 1
        return {"delivered": n}

    # ------------- work queues -------------

    def _kick_queue(self, qname: str) -> None:
        q = self._queues[qname]
        waiters = self._queue_waiters[qname]
        while q and waiters:
            conn_id, rid = waiters.popleft()
            conn = self._conns.get(conn_id)
            if conn is None or conn.closed:
                continue
            m = q.popleft()
            m.delivered_to = conn_id
            self._inflight[(qname, m.msg_id)] = m
            self._send(conn, {"rid": rid, "ok": True, "msg_id": m.msg_id, "payload": m.payload})

    def _op_queue_push(self, conn: _Conn, msg: dict) -> dict:
        qname = msg["queue"]
        m = _QueueMsg(msg_id=next(self._msg_ids), payload=msg["payload"])
        self._queues[qname].append(m)
        self._log_persist(
            {"op": "queue_push", "queue": qname, "msg_id": m.msg_id, "payload": m.payload}
        )
        self._kick_queue(qname)
        return {"msg_id": m.msg_id, "depth": len(self._queues[qname])}

    def _op_queue_pull(self, conn: _Conn, msg: dict):
        """Pull one message; parks the request until a message is available."""
        qname = msg["queue"]
        q = self._queues[qname]
        if q:
            m = q.popleft()
            m.delivered_to = conn.conn_id
            self._inflight[(qname, m.msg_id)] = m
            return {"msg_id": m.msg_id, "payload": m.payload}
        self._queue_waiters[qname].append((conn.conn_id, msg.get("rid")))
        return DEFER  # response sent by _kick_queue when a message arrives

    def _op_queue_ack(self, conn: _Conn, msg: dict) -> dict:
        self._inflight.pop((msg["queue"], msg["msg_id"]), None)
        self._log_persist({"op": "queue_ack", "queue": msg["queue"], "msg_id": msg["msg_id"]})
        return {}

    def _op_queue_nack(self, conn: _Conn, msg: dict) -> dict:
        m = self._inflight.pop((msg["queue"], msg["msg_id"]), None)
        if m is not None:
            m.delivered_to = None
            self._queues[msg["queue"]].appendleft(m)
            self._kick_queue(msg["queue"])
        return {}

    def _op_queue_depth(self, conn: _Conn, msg: dict) -> dict:
        return {"depth": len(self._queues[msg["queue"]]),
                "inflight": sum(1 for (q, _) in self._inflight if q == msg["queue"]),
                # parked pulls: readiness signal that a consumer is listening
                "waiters": len(self._queue_waiters[msg["queue"]])}

    def _op_ping(self, conn: _Conn, msg: dict) -> dict:
        return {"now": time.time()}


def main() -> None:
    import os

    parser = argparse.ArgumentParser(description="dynamo-tpu control-plane broker")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=4222)
    parser.add_argument("--persist", default=os.environ.get("DYNTPU_CPLANE_PERSIST"))
    args = parser.parse_args()
    lat_ms = float(os.environ.get("DYNTPU_CPLANE_LATENCY_MS", "0"))
    jit_ms = float(os.environ.get("DYNTPU_CPLANE_JITTER_MS", "0"))
    latency = (lat_ms / 1e3, jit_ms / 1e3) if lat_ms or jit_ms else None

    async def run():
        broker = Broker(args.host, args.port, persist_path=args.persist, latency=latency)
        port = await broker.start()
        print(f"listening on {args.host}:{port}", flush=True)
        await broker._stopped.wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
