"""Control plane: a single built-in broker replacing the reference's
etcd + NATS pair (discovery/leases/watches + request plane/events/queues,
reference: lib/runtime/src/transports/{etcd.rs,nats.rs}).

Hardware-agnostic by design — the data plane (KV blocks, response streams)
never flows through here.
"""

from dynamo_tpu.cplane.client import CplaneClient
