"""Draft proposers for speculative decoding.

The engine asks a proposer for up to k likely continuation tokens given a
sequence's full token history (prompt + generated so far). Proposals are
free-form guesses: a wrong draft costs only its share of one verification
pass, never output quality (the verifier accepts/rejects exactly).

``NgramProposer`` implements prompt-lookup decoding (Saxena et al.): match
the longest recent suffix of the history against an earlier occurrence and
propose the tokens that followed it. On repetition-heavy text (code,
summarization, multi-turn chat quoting context) acceptance rates are high
enough that one verify pass regularly advances k+1 tokens.
"""

from __future__ import annotations

from typing import Protocol, Sequence, runtime_checkable

import numpy as np


@runtime_checkable
class Proposer(Protocol):
    """Pluggable draft source (n-gram today; a draft model fits the same
    contract: stateless per call, history in, <= k token ids out)."""

    def propose(self, token_ids: Sequence[int], k: int) -> list[int]: ...


class NgramProposer:
    """Prompt-lookup proposer: longest-suffix n-gram match over the history.

    For n from ``max_ngram`` down to ``min_ngram``: take the history's last n
    tokens, find the MOST RECENT earlier occurrence of that n-gram, and
    propose k tokens by copying forward with the match's lag — extended
    periodically past the history's end, so a generation loop of period d
    yields full-k drafts that follow the loop exactly. Stateless — the
    history arrives fresh each call, so multi-token advances, preemption,
    and disagg adoption need no index maintenance.
    """

    def __init__(self, max_ngram: int = 4, min_ngram: int = 1):
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram; got {min_ngram}..{max_ngram}"
            )
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram

    def propose(self, token_ids: Sequence[int], k: int) -> list[int]:
        L = len(token_ids)
        if k <= 0 or L < self.min_ngram + 1:
            return []
        arr = np.asarray(token_ids, np.int64)
        for n in range(min(self.max_ngram, L - 1), self.min_ngram - 1, -1):
            suffix = arr[L - n :]
            # windows over arr[:-1] so the suffix's own position never
            # self-matches; any match therefore has >= 1 continuation token
            windows = np.lib.stride_tricks.sliding_window_view(arr[:-1], n)
            matches = np.nonzero((windows == suffix).all(axis=1))[0]
            if matches.size == 0:
                continue
            # most recent match wins (closest context); predict by copying
            # with its lag d, extending PERIODICALLY past the history's end —
            # a looping chain's latest match sits one period back, and plain
            # arr[start:start+k] would truncate the draft at the loop period,
            # wasting the verify pass's remaining rows
            d = (L - n) - int(matches[-1])
            return [int(arr[L - d + (i % d)]) for i in range(k)]
        return []
