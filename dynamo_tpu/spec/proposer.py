"""Draft proposers for speculative decoding.

The engine asks a proposer for up to k likely continuation tokens given a
sequence's full token history (prompt + generated so far). Proposals are
free-form guesses: a wrong draft costs only its share of one verification
pass, never output quality (the verifier accepts/rejects exactly).

``NgramProposer`` implements prompt-lookup decoding (Saxena et al.): match
the longest recent suffix of the history against an earlier occurrence and
propose the tokens that followed it. On repetition-heavy text (code,
summarization, multi-turn chat quoting context) acceptance rates are high
enough that one verify pass regularly advances k+1 tokens.

``NgramIndex`` is the incremental form the scheduler actually serves with:
the full-history rescan (O(history * max_ngram) per round — every round, per
sequence) becomes an O(max_ngram) dict update per ACCEPTED token plus an
O(max_ngram) lookup per propose. A long chat at 4K history used to pay ~16K
window comparisons per spec round; the index pays ~4 dict ops per new token.
"""

from __future__ import annotations

from typing import Protocol, Sequence, runtime_checkable


@runtime_checkable
class Proposer(Protocol):
    """Pluggable host-side draft source (n-gram today). A draft MODEL does
    not fit this host contract — it is device state dispatched through
    ModelRunner.dispatch_draft — which is why make_proposer returns None for
    the draft kind."""

    def propose(self, token_ids: Sequence[int], k: int) -> list[int]: ...


class NgramIndex:
    """Incremental suffix index over one sequence's token history.

    For each n in [min_ngram, max_ngram] it tracks, per n-gram, its most
    recent start position (``_last``) and the start of the occurrence that
    position displaced (``_prev``). The history's current suffix is always
    the most recent occurrence of itself, so its most recent EARLIER match —
    exactly what the stateless scan found over windows of history[:-1] — is
    ``_prev``'s entry. Appending a token registers max_ngram n-grams; a
    propose does max_ngram lookups: both independent of history length.

    ``work`` counts dict registrations + lookups (the unit tests' O(new
    tokens) assertion rides it; the counter costs one integer add per op).
    """

    def __init__(self, tokens: Sequence[int], max_ngram: int = 4, min_ngram: int = 1):
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram; got {min_ngram}..{max_ngram}"
            )
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram
        self.tokens: list[int] = []
        # per-n maps live at index n (indices < min_ngram unused)
        self._last: list[dict] = [dict() for _ in range(max_ngram + 1)]
        self._prev: list[dict] = [dict() for _ in range(max_ngram + 1)]
        self.work = 0
        self.extend(tokens)

    def __len__(self) -> int:
        return len(self.tokens)

    def append(self, token: int) -> None:
        self.tokens.append(int(token))
        i = len(self.tokens) - 1
        for n in range(self.min_ngram, self.max_ngram + 1):
            s = i - n + 1
            if s < 0:
                break
            g = tuple(self.tokens[s : i + 1])
            self.work += 1
            last = self._last[n]
            old = last.get(g)
            if old is not None:
                self._prev[n][g] = old
            last[g] = s

    def extend(self, tokens: Sequence[int]) -> None:
        for t in tokens:
            self.append(t)

    def propose(self, k: int) -> list[int]:
        tokens = self.tokens
        L = len(tokens)
        if k <= 0 or L < self.min_ngram + 1:
            return []
        for n in range(min(self.max_ngram, L - 1), self.min_ngram - 1, -1):
            g = tuple(tokens[L - n :])
            self.work += 1
            # the suffix is its own most recent occurrence (registered at its
            # final token's append); the previous one is the most recent
            # EARLIER match. When _last somehow predates the suffix (can't
            # happen through append, but stay total), use it directly.
            s = self._last[n].get(g)
            if s == L - n:
                s = self._prev[n].get(g)
            if s is None:
                continue
            # most recent match wins (closest context); predict by copying
            # with its lag d, extending PERIODICALLY past the history's end —
            # a looping chain's latest match sits one period back, and a
            # plain slice would truncate the draft at the loop period,
            # wasting the verify pass's remaining rows
            d = (L - n) - s
            return [int(tokens[L - d + (i % d)]) for i in range(k)]
        return []


class NgramProposer:
    """Prompt-lookup proposer: longest-suffix n-gram match over the history.

    For n from ``max_ngram`` down to ``min_ngram``: take the history's last n
    tokens, find the MOST RECENT earlier occurrence of that n-gram, and
    propose k tokens by copying forward with the match's lag. The stateless
    ``propose`` builds a throwaway index (tests, one-shot callers); serving
    paths hold a per-sequence :class:`NgramIndex` via :meth:`index` and pay
    only for new tokens.
    """

    def __init__(self, max_ngram: int = 4, min_ngram: int = 1):
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram; got {min_ngram}..{max_ngram}"
            )
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram

    def index(self, tokens: Sequence[int]) -> NgramIndex:
        """A per-sequence incremental index seeded with ``tokens``."""
        return NgramIndex(tokens, max_ngram=self.max_ngram, min_ngram=self.min_ngram)

    def propose(self, token_ids: Sequence[int], k: int) -> list[int]:
        return self.index(token_ids).propose(k)
