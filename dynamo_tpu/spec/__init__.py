"""Speculative decoding subsystem.

Two proposer families for the paged engine, both verified through the same
batched multi-token verify pass (Leviathan et al., "Fast Inference from
Transformers via Speculative Decoding"; Chen et al., "Accelerating Large
Language Model Decoding with Speculative Sampling"):

  - ``ngram:k`` — draft-free prompt-lookup (Saxena et al.): a per-sequence
    suffix index guesses up to k continuation tokens from the sequence's own
    prompt+output history. Wins on repetition-heavy text only.
  - ``draft:<model>:<k>`` — a second, smaller model loaded through the
    registry drafts k tokens per round in ONE batched on-device dispatch
    (spec/draft.py DraftModelRunner: its own paged KV pool + per-sequence
    draft page tables on the width ladder). Because the draft emits real
    probability rows, temperature>0 acceptance runs the exact
    rejection-sampling rule against q (not a one-hot), recovering speedups
    on arbitrary text where n-gram acceptance collapses.

Greedy requests advance token-identically to the non-speculative engine;
temperature>0 requests are distribution-exact
(engine/sampling.py:accept_speculative).

Config surface: ``EngineConfig.speculative`` / ``--speculative ngram:k`` /
``--speculative draft:<model>:<k>`` parses through :func:`parse_speculative`;
the scheduler builds the n-gram proposer via :func:`make_proposer` (draft
proposals ride ``ModelRunner.dispatch_draft`` instead — a draft model is
device state, not a host-side Proposer).
"""

from __future__ import annotations

from dataclasses import dataclass

from dynamo_tpu.spec.proposer import NgramIndex, NgramProposer, Proposer

__all__ = [
    "NgramIndex",
    "NgramProposer",
    "Proposer",
    "SpecConfig",
    "make_proposer",
    "parse_speculative",
]

#: proposer kinds accepted by ``--speculative``
SPEC_KINDS = ("ngram", "draft")


@dataclass(frozen=True)
class SpecConfig:
    """Parsed speculative-decoding settings."""

    kind: str = "ngram"
    k: int = 4  # draft tokens proposed (and verified) per engine round
    max_ngram: int = 4  # longest history suffix the n-gram proposer matches
    min_ngram: int = 1  # shortest suffix worth matching
    # draft kind only: registry id of the draft model (a tiny:{...} override
    # JSON or a local checkpoint dir; loaded with the engine's quantize /
    # kv_cache_dtype so the draft composes with int8 weights and int8 KV)
    model: str | None = None


def parse_speculative(spec) -> SpecConfig | None:
    """``None``/"off" -> None; "ngram" / "ngram:4" / "draft:<model>:<k>" ->
    SpecConfig.

    One parser shared by EngineConfig validation, the CLIs, and the runner's
    warmup so a bad spec string fails at config time, not mid-serving. Draft
    model ids may themselves contain colons (tiny:{...} override JSON, or an
    absolute path): only a purely-numeric LAST segment is taken as k, the
    rest is the model id verbatim.
    """
    if spec is None or isinstance(spec, SpecConfig):
        return spec
    s = str(spec).strip()
    if s in ("", "none", "off"):
        return None
    parts = s.split(":")
    kind = parts[0]
    if kind not in SPEC_KINDS:
        raise ValueError(
            f"unknown speculative kind {kind!r} (supported: {SPEC_KINDS})"
        )
    k = 4
    model = None
    if kind == "draft":
        rest = parts[1:]
        if rest and rest[-1].isdigit():
            k = int(rest.pop())
        model = ":".join(rest)
        if not model:
            raise ValueError(
                "draft speculation needs a model id: --speculative "
                "draft:<model>[:<k>]"
            )
    elif len(parts) > 1 and parts[1]:
        k = int(parts[1])
    if not 1 <= k <= 16:
        raise ValueError(f"speculative k must be in [1, 16]; got {k}")
    return SpecConfig(kind=kind, k=k, model=model)


def make_proposer(cfg: SpecConfig) -> Proposer | None:
    """Host-side proposer for the config; None for the draft kind (drafting
    is a batched device dispatch owned by the ModelRunner, not a per-sequence
    host call)."""
    if cfg.kind == "ngram":
        return NgramProposer(max_ngram=cfg.max_ngram, min_ngram=cfg.min_ngram)
    if cfg.kind == "draft":
        return None
    raise ValueError(f"no proposer for speculative kind {cfg.kind!r}")
