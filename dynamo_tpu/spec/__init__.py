"""Speculative decoding subsystem.

Draft-free speculation for the paged engine: a per-sequence ``Proposer``
guesses up to k continuation tokens from the sequence's own prompt+output
history (n-gram / prompt-lookup decoding — Saxena et al.; the interface also
admits a draft-model proposer later), and the engine verifies all k guesses
plus samples one bonus token in ONE multi-query forward pass against the
existing page table (Leviathan et al., "Fast Inference from Transformers via
Speculative Decoding"). Greedy requests advance token-identically to the
non-speculative engine; temperature>0 requests use distribution-exact
rejection sampling (engine/sampling.py:accept_speculative).

Config surface: ``EngineConfig.speculative`` / ``--speculative ngram:k``
parses through :func:`parse_speculative`; the scheduler builds the proposer
via :func:`make_proposer`.
"""

from __future__ import annotations

from dataclasses import dataclass

from dynamo_tpu.spec.proposer import NgramProposer, Proposer

__all__ = [
    "NgramProposer",
    "Proposer",
    "SpecConfig",
    "make_proposer",
    "parse_speculative",
]

#: proposer kinds accepted by ``--speculative`` (a draft-model proposer slots
#: in here without touching the engine: it only has to implement Proposer)
SPEC_KINDS = ("ngram",)


@dataclass(frozen=True)
class SpecConfig:
    """Parsed speculative-decoding settings."""

    kind: str = "ngram"
    k: int = 4  # draft tokens proposed (and verified) per engine round
    max_ngram: int = 4  # longest history suffix the n-gram proposer matches
    min_ngram: int = 1  # shortest suffix worth matching


def parse_speculative(spec) -> SpecConfig | None:
    """``None``/"off" -> None; "ngram" / "ngram:4" -> SpecConfig.

    One parser shared by EngineConfig validation, the CLIs, and the runner's
    warmup so a bad spec string fails at config time, not mid-serving.
    """
    if spec is None or isinstance(spec, SpecConfig):
        return spec
    s = str(spec).strip()
    if s in ("", "none", "off"):
        return None
    parts = s.split(":")
    kind = parts[0]
    if kind not in SPEC_KINDS:
        raise ValueError(
            f"unknown speculative kind {kind!r} (supported: {SPEC_KINDS})"
        )
    k = 4
    if len(parts) > 1 and parts[1]:
        k = int(parts[1])
    if not 1 <= k <= 16:
        raise ValueError(f"speculative k must be in [1, 16]; got {k}")
    return SpecConfig(kind=kind, k=k)


def make_proposer(cfg: SpecConfig) -> Proposer:
    if cfg.kind == "ngram":
        return NgramProposer(max_ngram=cfg.max_ngram, min_ngram=cfg.min_ngram)
    raise ValueError(f"no proposer for speculative kind {cfg.kind!r}")
