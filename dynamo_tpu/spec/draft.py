"""Draft-model speculative decoding: the device side.

``--speculative draft:<model>:<k>`` loads a second, smaller model through the
registry (with the engine's ``quantize`` / ``kv_cache_dtype``, so the draft
composes with int8 weights and the int8 KV cache) and drafts k tokens per
spec round for EVERY spec-mode lane in one batched, donated, jit'd dispatch —
no per-sequence Python in the round's hot path.

The draft keeps its own paged KV:

  - a separate page pool (same page_size / num_pages geometry as the target,
    page 0 reserved as the trash page) with a minimal per-sequence free-list
    allocator — no prefix cache: draft KV is cheap to recompute and its only
    reader is the next draft round;
  - per-sequence draft page tables sized by the SAME width ladder as the
    target (config.table_bucket_for), so a short chat dispatches a narrow
    draft table and only deep sequences pay wide gathers;
  - rejected draft rows are simply overwritten by the next round's feeds at
    the advanced anchor — exactly the target verify pass's KV discipline.

Per round, one ``draft_step`` dispatch does BOTH phases on device:

  1. catch-up: feed the tokens the target emitted since the draft's last fed
     position (always the single correction/bonus token in steady state)
     through the draft model's multi-query ``verify`` pass, landing on the
     logits for the next position;
  2. drafting: a ``lax.scan`` of k single-token decode steps, each sampling
     a draft token from the draft's FILTERED distribution (the request's
     temperature/top-k/top-p/min-p — the q the acceptance rule needs) and
     feeding it back. The full q rows ride back as a [B, K, V] device array
     that flows straight into the verify pass's acceptance — they never
     touch the host.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.sampling import _NEG_INF, filter_keep_mask
from dynamo_tpu.utils import get_logger

log = get_logger("spec.draft")

#: fold base for draft-token sampling streams. MUST differ from the
#: acceptance stream's base (sampling.accept_speculative, 0x5EC5) and the
#: window sampler's (0x5EED): rejection sampling is exact only when the
#: accept/reject uniforms are independent of the draws that produced the
#: drafts.
_DRAFT_KEY_BASE = 0xD4AF


class DraftModelRunner:
    """Owns the draft model's params, paged KV pool, page bookkeeping, and
    the jitted prefill / draft-step dispatches. Built by ModelRunner when
    ``config.spec.kind == "draft"``; every method runs on the engine thread.
    """

    def __init__(self, config, spec, compile_monitor=None):
        from dynamo_tpu.models.registry import load_model

        self.config = config
        self.spec = spec
        self.model, self.params = load_model(
            spec.model, quantize=config.quantize,
            kv_cache_dtype=config.kv_cache_dtype,
        )
        self.kv = self.model.init_kv_cache(config.num_pages, config.page_size)
        # minimal page allocator: page 0 is the trash page, everything else
        # free-listed per sequence (no sharing, no prefix cache)
        self._free: list[int] = list(range(config.num_pages - 1, 0, -1))
        self._pages: dict[str, list[int]] = {}
        self._key = jax.random.key(_DRAFT_KEY_BASE)
        # telemetry (dynamo_spec_draft_*): dispatch seconds land in the
        # scheduler's StageStats; pool occupancy is read from here
        self.prefills = 0

        from dynamo_tpu.utils.compile_monitor import monitored_jit

        def _mjit(label, fn, **kw):
            # monitor=None is a passthrough; otherwise draft compiles land in
            # the same compile-churn gauges as the target runner's
            return monitored_jit(jax.jit(fn, **kw), label, compile_monitor)

        self._prefill = _mjit(
            "draft_prefill", self._prefill_impl,
            donate_argnums=(1,), static_argnames=("mp",),
        )
        self._draft = _mjit("draft_step", self._draft_impl, donate_argnums=(1,))

    # ---------------- page bookkeeping ----------------

    @property
    def pages_total(self) -> int:
        return self.config.num_pages - 1

    @property
    def pages_used(self) -> int:
        return self.pages_total - len(self._free)

    def pages_of(self, seq_id: str) -> list[int] | None:
        return self._pages.get(seq_id)

    def ensure_capacity(self, seq_id: str, length: int) -> bool:
        """Pages to hold ``length`` draft-timeline tokens. False on OOM
        (nothing partially taken — the caller drops the sequence's draft
        state and the round degrades to verify-only)."""
        pages = self._pages.setdefault(seq_id, [])
        need = -(-length // self.config.page_size) - len(pages)
        if need <= 0:
            return True
        if need > len(self._free):
            return False
        for _ in range(need):
            pages.append(self._free.pop())
        return True

    def free_sequence(self, seq_id: str) -> None:
        pages = self._pages.pop(seq_id, None)
        if pages:
            self._free.extend(pages)

    def table_for(self, seq_id: str) -> np.ndarray:
        """Page table at the sequence's current width-ladder rung."""
        pages = self._pages.get(seq_id, [])
        table = np.zeros(self.config.table_bucket_for(max(1, len(pages))), np.int32)
        table[: len(pages)] = pages
        return table

    # ---------------- jitted bodies ----------------

    def _prefill_impl(self, params, kv, ints, mp=None):
        """One draft prefill chunk: ints [bucket + mp + 2] = token buf, page
        table, (start_pos, n_real). KV-write only — the chunk's logits are
        dead (XLA DCEs the unembed); the first draft round's catch-up feed
        re-feeds the last prompt token and samples from there."""
        if mp is None:
            mp = self.config.max_pages_per_seq
        bucket = ints.shape[0] - mp - 2
        tokens = ints[:bucket]
        page_table = ints[bucket : bucket + mp]
        start = ints[bucket + mp]
        n = ints[bucket + mp + 1]
        positions = start + jnp.arange(bucket, dtype=jnp.int32)
        valid = jnp.arange(bucket) < n
        _, kv = self.model.prefill(
            params, kv, tokens, positions, page_table, valid, n - 1
        )
        return kv

    def _draft_impl(self, params, kv, ints, flts, key):
        """Catch-up feed + k-step autoregressive drafting for all lanes.

        ``ints`` [5 + (K+1) + W, B] = positions (first catch-up fed position),
        active, n_feed, top_ks, seeds, the K+1 catch-up token rows, then the
        transposed draft page tables (W = the round's ladder width, static
        via shape; K is config-static). ``flts`` [3, B] = temps, top_ps,
        min_ps. Returns (draft tokens [B, K], draft probs q [B, K, V], kv):
        q[:, j] is the filtered distribution token j+1 was sampled from —
        the exact q the rejection-sampling acceptance divides by."""
        K = self.spec.k
        K1 = K + 1
        positions = ints[0]
        active = ints[1].astype(bool)
        n_feed = ints[2]
        top_ks = ints[3]
        seeds = ints[4]
        fed = ints[5 : 5 + K1].T  # [B, K1]
        page_tables = ints[5 + K1 :].T  # [B, W]
        temps, top_ps, min_ps = flts[0], flts[1], flts[2]
        B = positions.shape[0]

        # phase 1: multi-query catch-up (rows past n_feed land on the trash
        # page); logits at row n_feed-1 predict the first draft token
        t_idx = jnp.arange(K1, dtype=jnp.int32)
        pos_mat = positions[:, None] + t_idx[None, :]
        row_valid = active[:, None] & (t_idx[None, :] < n_feed[:, None])
        logits_all, kv = self.model.verify(
            params, kv, fed, pos_mat, page_tables, row_valid
        )
        b_idx = jnp.arange(B)
        logits = logits_all[b_idx, jnp.maximum(n_feed - 1, 0)]  # [B, V]

        # per-slot sampling keys: seeded slots fold (seed, anchor position)
        # off the draft base so their drafts are deterministic across retries
        # (and INDEPENDENT of the acceptance stream — different base);
        # unseeded fold the slot index off this round's key
        base = jax.random.key(_DRAFT_KEY_BASE)

        def slot_key(i, seed, p):
            seeded = jax.random.fold_in(jax.random.fold_in(base, seed), p)
            unseeded = jax.random.fold_in(key, i)
            return jax.lax.cond(seed != 0, lambda: seeded, lambda: unseeded)

        slot_keys = jax.vmap(slot_key)(
            jnp.arange(B, dtype=jnp.int32), seeds, positions
        )
        temp = jnp.where(temps > 0, temps, 1.0)[:, None]

        def body(carry, j):
            kv, logits, pos = carry
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            keep = filter_keep_mask(logits, temps, top_ks, top_ps, min_p=min_ps)
            masked = jnp.where(keep, logits, _NEG_INF) / temp
            q = jax.nn.softmax(masked, axis=-1)  # [B, V]
            keys_j = jax.vmap(lambda k_: jax.random.fold_in(k_, j))(slot_keys)
            sampled = jax.vmap(
                lambda k_, row: jax.random.categorical(k_, row)
            )(keys_j, masked).astype(jnp.int32)
            tok = jnp.where(temps > 0, sampled, greedy)
            # feed the draft token (writes its KV row; the row is correct for
            # as long as the token survives acceptance, overwritten at the
            # advanced anchor otherwise — same discipline as verify KV)
            logits, kv = self.model.decode(
                params, kv, tok, pos, page_tables, active
            )
            return (kv, logits, pos + 1), (tok, q)

        (kv, _, _), (toks, qs) = jax.lax.scan(
            body, (kv, logits, positions + n_feed), jnp.arange(K)
        )
        # scan stacks on the leading axis: [K, B] / [K, B, V] -> lane-major
        return toks.T, jnp.swapaxes(qs, 0, 1), kv

    # ---------------- host API (engine thread) ----------------

    def _next_key(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def prefill_sequence(self, seq_id: str, tokens: list[int]) -> bool:
        """Chunked draft prefill of a sequence's full history (no prefix
        cache: the draft always recomputes — coherent by construction across
        the target's host-offload restores and remote-prefill adoptions).
        Returns False when the draft pool can't hold the history + one
        round's drafts; nothing is left allocated on failure."""
        self.free_sequence(seq_id)  # always a fresh build: no stale pages
        n = len(tokens)
        if not self.ensure_capacity(seq_id, n + self.spec.k + 1):
            self.free_sequence(seq_id)
            return False
        table = self.table_for(seq_id)
        mp = len(table)
        start = 0
        while start < n:
            end = min(start + self.config.chunk_len_for(start), n)
            bucket = self.config.bucket_for(end - start)
            ints = np.zeros(bucket + mp + 2, np.int32)
            ints[: end - start] = tokens[start:end]
            ints[bucket : bucket + mp] = table
            ints[bucket + mp] = start
            ints[bucket + mp + 1] = end - start
            self.kv = self._prefill(self.params, self.kv, jnp.asarray(ints), mp=mp)
            start = end
        self.prefills += 1
        return True

    def dispatch_draft(
        self,
        positions: np.ndarray,  # [B] first catch-up fed position per lane
        page_tables: np.ndarray,  # [B, W] draft page tables at the round's rung
        active: np.ndarray,  # [B] bool
        fed_tokens: np.ndarray,  # [B, K+1] catch-up tokens (V-padded tail)
        n_feed: np.ndarray,  # [B] real catch-up token count (>= 1 when active)
        temps: np.ndarray,
        top_ks: np.ndarray,
        top_ps: np.ndarray,
        min_ps: np.ndarray | None = None,
        seeds: np.ndarray | None = None,
    ):
        """One batched draft round over every lane. Returns (draft tokens
        [B, K] device array, draft probs [B, K, V] device array). The caller
        materializes the tokens (it must build the verify feed) and passes
        the prob rows STRAIGHT into dispatch_verify — they stay on device."""
        B = positions.shape[0]
        K1 = self.spec.k + 1
        ints = np.empty((5 + K1 + page_tables.shape[1], B), np.int32)
        ints[0] = positions
        ints[1] = active
        ints[2] = np.maximum(n_feed, 1)
        ints[3] = top_ks
        ints[4] = seeds if seeds is not None else 0
        ints[5 : 5 + K1] = fed_tokens.T
        ints[5 + K1 :] = page_tables.T
        flts = np.empty((3, B), np.float32)
        flts[0] = temps
        flts[1] = top_ps
        flts[2] = min_ps if min_ps is not None else 0.0
        toks, qs, self.kv = self._draft(
            self.params, self.kv, jnp.asarray(ints), jnp.asarray(flts),
            self._next_key(),
        )
        try:
            toks.copy_to_host_async()
        except Exception:
            pass
        return toks, qs

    def warmup(self) -> None:
        """Compile the draft-step executable (first-rung width) and the
        smallest prefill bucket; all lanes inactive / writes on the trash
        page, so the calls execute harmlessly."""
        B = self.config.max_seqs
        W = self.config.table_buckets[0]
        out = self.dispatch_draft(
            np.zeros(B, np.int32), np.zeros((B, W), np.int32),
            np.zeros(B, bool), np.zeros((B, self.spec.k + 1), np.int32),
            np.ones(B, np.int32), np.zeros(B, np.float32),
            np.zeros(B, np.int32), np.ones(B, np.float32),
        )
        jax.block_until_ready(out[0])  # graftlint: sync-ok warmup: compile gate, not serving traffic
        b = self.config.prefill_buckets[0]
        ints = np.zeros(b + W + 2, np.int32)
        ints[b + W + 1] = 1
        self.kv = self._prefill(self.params, self.kv, jnp.asarray(ints), mp=W)
