"""`dynamo-tpu build`: package a service graph into a deployable artifact.

The reference's `dynamo build` packages a graph as a bento (BentoML-derived
archive with Rust binaries inside — reference: deploy/dynamo/sdk/src/dynamo/
sdk/cli, pyproject.toml bento packaging). The native analogue is leaner: the
framework is a single Python package, so the artifact is the **deployment
contract**, not a code archive:

  artifact/
    manifest.json     — entry point, graph, per-service meta (the build record)
    deployment.yaml   — a deploy-plane DeploymentSpec (dynamo_tpu/deploy/crd.py)
                        rendered from the graph: `dynamo-tpu deploy create` or
                        the K8s reconciler consume it directly
    config.yaml       — the service YAML config, copied verbatim (when given)
    Containerfile     — image-build recipe for the artifact (the reference's
                        DynamoNimRequest image-build slot, reference:
                        deploy/dynamo/operator/internal/controller/
                        dynamonimrequest_controller.go): `docker build` /
                        kaniko produce the image every service in
                        deployment.yaml runs; the deploy API's /builds
                        endpoint renders the corresponding in-cluster Job

Per-service replicas/chips resolve exactly like the serve supervisor does
(meta defaults overridden by the YAML section), so a built artifact deploys
what `serve` would have run.
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
from pathlib import Path

from dynamo_tpu.deploy.crd import DeploymentSpec, ServiceSpec
from dynamo_tpu.llm.model_card import slugify
from dynamo_tpu.sdk.config import ServiceConfig
from dynamo_tpu.sdk.serve import class_spec, discover_graph
from dynamo_tpu.sdk.serve_worker import load_class
from dynamo_tpu.utils import get_logger

log = get_logger("sdk.build")


def build_spec(entry_spec: str, config: dict, name: str | None = None,
               image: str = "dynamo-tpu:latest") -> tuple[DeploymentSpec, list[dict]]:
    """Resolve the graph and render a DeploymentSpec + per-service build info."""
    entry_cls = load_class(entry_spec)
    graph = discover_graph(entry_cls)
    services = []
    info = []
    for cls in graph:
        meta = cls.__dynamo_service__
        section = config.get(cls.__name__, {})
        resources = section.get("resources", meta.resources) or {}
        workers = section.get("workers", meta.workers)
        workers = 1 if workers == "cpu_count" else int(workers)
        svc = ServiceSpec(
            name=slugify(cls.__name__),
            command=[
                "python", "-m", "dynamo_tpu.sdk.serve_worker", class_spec(cls),
            ],
            replicas=workers,
            tpu_chips=int(resources.get("tpu", 0) or 0),
            config=section,
        )
        services.append(svc)
        info.append(
            {
                "class": class_spec(cls),
                "namespace": meta.namespace,
                "component": meta.component,
                "workers": workers,
                "resources": resources,
            }
        )
    dep_name = name or slugify(entry_cls.__name__)
    spec = DeploymentSpec(name=dep_name, image=image, services=services)
    spec.validate()
    return spec, info


def build_artifact(
    entry_spec: str,
    output_dir: str,
    config_file: str | None = None,
    name: str | None = None,
    image: str = "dynamo-tpu:latest",
) -> Path:
    import yaml

    config = ServiceConfig.from_yaml_and_overrides(config_file, [])
    spec, info = build_spec(entry_spec, config, name=name, image=image)

    out = Path(output_dir)
    out.mkdir(parents=True, exist_ok=True)
    (out / "manifest.json").write_text(
        json.dumps(
            {
                "entry": entry_spec,
                "deployment": spec.name,
                "image": image,
                "services": info,
            },
            indent=2,
        )
    )
    (out / "deployment.yaml").write_text(yaml.safe_dump(spec.to_dict(), sort_keys=False))
    if config_file:
        shutil.copyfile(config_file, out / "config.yaml")
    _copy_entry_source(entry_spec, out)
    (out / "Containerfile").write_text(render_containerfile(entry_spec))
    (out / ".dockerignore").write_text("__pycache__/\n*.pyc\n.git/\n")
    log.info("built %s -> %s (%d services)", entry_spec, out, len(spec.services))
    return out


def _copy_entry_source(entry_spec: str, out: Path) -> None:
    """Vendor the graph's entry code into the artifact under src/: the wheel
    only ships dynamo_tpu*, so the user's graph module must ride along or
    the container's `python -m ... <module>` dies with ModuleNotFoundError."""
    import importlib

    root_pkg = entry_spec.split(":", 1)[0].split(".", 1)[0]
    if root_pkg.startswith("dynamo_tpu"):
        return  # already in the installed wheel
    mod = importlib.import_module(root_pkg)
    src = Path(mod.__file__)
    dst = out / "src"
    dst.mkdir(exist_ok=True)
    if src.name == "__init__.py":  # package: copy the tree
        shutil.copytree(
            src.parent, dst / root_pkg, dirs_exist_ok=True,
            ignore=shutil.ignore_patterns("__pycache__", "*.pyc"),
        )
    else:  # single-module entry
        shutil.copyfile(src, dst / src.name)


def render_containerfile(entry_spec: str) -> str:
    """Image recipe for the artifact: the framework plus the graph's entry
    code (vendored under src/ by build_artifact), with per-service commands
    supplied by the Deployment manifests (deployment.yaml's command fields
    override CMD). Built by `docker build` locally or by the Job the deploy
    API renders (POST /api/v1/builds)."""
    module = entry_spec.split(":", 1)[0]
    return (
        "# syntax=docker/dockerfile:1\n"
        "FROM python:3.12-slim\n"
        "WORKDIR /app\n"
        "# the whole artifact (manifest, deployment.yaml, vendored src/,\n"
        "# optional wheels) — COPY with a glob that can match nothing is a\n"
        "# hard error in docker/kaniko, so copy the directory and branch\n"
        "COPY . /app/artifact/\n"
        "RUN if ls /app/artifact/*.whl >/dev/null 2>&1; then \\\n"
        "      pip install --no-cache-dir /app/artifact/*.whl; \\\n"
        "    else \\\n"
        "      pip install --no-cache-dir dynamo-tpu; \\\n"
        "    fi\n"
        "ENV PYTHONUNBUFFERED=1 PYTHONPATH=/app/artifact/src\n"
        "# default: run the entry service; Deployments override per service\n"
        f"CMD [\"python\", \"-m\", \"dynamo_tpu.sdk.serve_worker\", \"{module}\"]\n"
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="dynamo-tpu build", description=__doc__)
    parser.add_argument("entry", help="module.path:ServiceClass")
    parser.add_argument("-f", "--file", default=None, help="YAML service config")
    parser.add_argument("-o", "--output", default="./build", help="artifact directory")
    parser.add_argument("--name", default=None, help="deployment name (default: entry class)")
    parser.add_argument("--image", default="dynamo-tpu:latest", help="container image ref")
    args = parser.parse_args(argv)
    build_artifact(args.entry, args.output, config_file=args.file, name=args.name,
                   image=args.image)
    return 0


if __name__ == "__main__":
    sys.exit(main())
