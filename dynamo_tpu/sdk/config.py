"""Service configuration: YAML sections per service class + CLI overrides.

reference: the SDK's YAML config + --Service.key=value overrides injected as
DYNAMO_SERVICE_CONFIG env JSON (deploy/dynamo/sdk/src/dynamo/sdk/lib/
service.py:111-118, docs/guides/dynamo_serve.md:157-219). Ours uses
DYNTPU_SERVICE_CONFIG.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any, Optional

ENV_KEY = "DYNTPU_SERVICE_CONFIG"


class ServiceConfig:
    _instance: Optional["ServiceConfig"] = None

    def __init__(self, data: Optional[dict] = None):
        self.data = data or {}

    @classmethod
    def load(cls) -> "ServiceConfig":
        if cls._instance is None:
            raw = os.environ.get(ENV_KEY)
            cls._instance = cls(json.loads(raw) if raw else {})
        return cls._instance

    @classmethod
    def reset(cls) -> None:
        cls._instance = None

    def for_service(self, name: str) -> dict:
        return dict(self.data.get(name, {}))

    def get(self, service: str, key: str, default: Any = None) -> Any:
        return self.data.get(service, {}).get(key, default)

    @classmethod
    def from_yaml_and_overrides(
        cls, yaml_path: Optional[str], overrides: list[str]
    ) -> dict:
        """Build the config dict: YAML file plus --Service.key=value overrides."""
        data: dict[str, dict] = {}
        if yaml_path:
            import yaml

            loaded = yaml.safe_load(Path(yaml_path).read_text()) or {}
            for svc, cfg in loaded.items():
                data[svc] = dict(cfg or {})
        for ov in overrides:
            if "=" not in ov or "." not in ov.split("=", 1)[0]:
                raise ValueError(f"override must be Service.key=value: {ov!r}")
            target, value = ov.split("=", 1)
            svc, key = target.lstrip("-").split(".", 1)
            try:
                value = json.loads(value)
            except json.JSONDecodeError:
                pass
            data.setdefault(svc, {})[key] = value
        return data
