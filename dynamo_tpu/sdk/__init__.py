"""Python SDK: declarative service graphs.

Mirrors the reference SDK surface (reference: deploy/dynamo/sdk/src/dynamo/sdk/
lib/{service.py,decorators.py,dependency.py}): ``@service`` classes with
``@endpoint`` streaming methods, ``depends()`` edges resolved to runtime
clients, YAML-configured, launched by the ``dynamo-tpu serve`` supervisor.
"""

from dynamo_tpu.sdk.decorators import service, endpoint, async_on_start
from dynamo_tpu.sdk.dependency import depends
from dynamo_tpu.sdk.config import ServiceConfig
