"""depends(): service-graph edges resolved to runtime clients at runtime.

reference: deploy/dynamo/sdk/src/dynamo/sdk/lib/dependency.py:28-80.
"""

from __future__ import annotations

from typing import Any, AsyncIterator


class DynamoClient:
    """Lazy client to another service's endpoint(s)."""

    def __init__(self, target_cls):
        self.target_cls = target_cls
        self._drt = None
        self._clients: dict[str, Any] = {}

    def bind_runtime(self, drt) -> None:
        self._drt = drt

    @property
    def meta(self):
        return self.target_cls.__dynamo_service__

    async def _client(self, endpoint: str):
        if self._drt is None:
            raise RuntimeError("dependency not bound to a runtime yet")
        c = self._clients.get(endpoint)
        if c is None:
            c = await self._drt.client(self.meta.namespace, self.meta.component, endpoint)
            await c.wait_for_instances(timeout=60)
            self._clients[endpoint] = c
        return c

    async def stream(self, payload: Any, endpoint: str = "generate", **kw) -> AsyncIterator[Any]:
        client = await self._client(endpoint)
        return await client.generate(payload, **kw)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)

        async def call(payload: Any, **kw):
            return await self.stream(payload, endpoint=name, **kw)

        return call


class _Depends:
    """Class-attribute marker replaced per-instance with a DynamoClient."""

    def __init__(self, target_cls):
        self.target_cls = target_cls

    def __set_name__(self, owner, name):
        self.attr = name
        deps = getattr(owner, "__dynamo_depends__", {})
        deps = dict(deps)
        deps[name] = self.target_cls
        owner.__dynamo_depends__ = deps

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        client = DynamoClient(self.target_cls)
        setattr(obj, self.attr, client)
        return client


def depends(target_cls) -> _Depends:
    return _Depends(target_cls)
