"""`dynamo-tpu serve` supervisor: launch a whole service graph from one entry.

reference: deploy/dynamo/sdk/src/dynamo/sdk/cli/{serve.py,serving.py} — the
circus-based process-per-service supervisor. Ours: discover the dependency
graph from the entry @service class, optionally start an embedded broker,
spawn one subprocess per service (x workers), restart on failure, tear down
on SIGINT.

    python -m dynamo_tpu.sdk.serve examples.graphs.agg:Frontend -f agg.yaml
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import time

from dynamo_tpu.sdk.allocator import ResourceAllocator
from dynamo_tpu.sdk.config import ENV_KEY, ServiceConfig
from dynamo_tpu.sdk.serve_worker import load_class
from dynamo_tpu.utils import get_logger

log = get_logger("sdk.serve")


def discover_graph(entry_cls) -> list[type]:
    """Entry class + transitive depends() targets, dependency-first order."""
    seen: dict[type, None] = {}

    def visit(cls):
        if cls in seen:
            return
        for target in getattr(cls, "__dynamo_depends__", {}).values():
            visit(target)
        seen[cls] = None

    visit(entry_cls)
    return list(seen)


def class_spec(cls) -> str:
    return f"{cls.__module__}:{cls.__name__}"


def _port_open(address: str) -> bool:
    host, _, port = address.rpartition(":")
    try:
        with socket.create_connection((host or "127.0.0.1", int(port)), timeout=0.5):
            return True
    except OSError:
        return False


class Supervisor:
    def __init__(
        self,
        entry_spec: str,
        config: dict,
        cplane: str,
        restart: bool = True,
        planner_scaling: bool = False,
        planner_poll_s: float = 5.0,
    ):
        self.entry_spec = entry_spec
        self.config = config
        self.cplane = cplane
        self.restart = restart
        self.children: dict[str, subprocess.Popen] = {}
        self.broker_proc = None
        self._stopping = False
        self.allocator = ResourceAllocator()
        self._worker_envs: dict[str, dict[str, str]] = {}
        # planner-driven scaling (components/planner.py publishes desired
        # replica counts; the supervisor is the single-host consumer — the
        # deploy reconciler is the K8s one)
        self.planner_scaling = planner_scaling
        self.planner_poll_s = planner_poll_s
        self.desired: dict[str, int] = {}  # class name -> replica count
        self._class_info: dict[str, tuple] = {}  # name -> (cls, meta, envs)
        self._last_planner_poll = 0.0

    def _env(self) -> dict:
        env = dict(os.environ)
        env[ENV_KEY] = json.dumps(self.config)
        env["DYNTPU_CPLANE"] = self.cplane
        return env

    def ensure_broker(self) -> None:
        if _port_open(self.cplane):
            log.info("control plane already running at %s", self.cplane)
            return
        host, _, port = self.cplane.rpartition(":")
        self.broker_proc = subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.cplane.broker", "--host", host or "127.0.0.1",
             "--port", port],
            env=self._env(),
        )
        for _ in range(50):
            if _port_open(self.cplane):
                return
            time.sleep(0.1)
        raise RuntimeError(f"broker failed to start on {self.cplane}")

    def spawn(self, cls, replica: int, extra_env: dict[str, str] | None = None) -> None:
        spec = class_spec(cls)
        name = f"{cls.__name__}-{replica}"
        if extra_env is not None:
            self._worker_envs[name] = extra_env
        env = self._env()
        env.update(self._worker_envs.get(name, {}))
        proc = subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.sdk.serve_worker", spec],
            env=env,
        )
        self.children[name] = proc
        log.info("spawned %s (pid %d)", name, proc.pid)

    def run(self) -> int:
        entry_cls = load_class(self.entry_spec)
        graph = discover_graph(entry_cls)
        log.info("service graph: %s", " -> ".join(c.__name__ for c in graph))
        self.ensure_broker()
        for cls in graph:
            meta = cls.__dynamo_service__
            num_workers, worker_envs = self.allocator.get_worker_env(
                meta, self.config.get(cls.__name__, {})
            )
            self.desired[cls.__name__] = num_workers
            self._class_info[cls.__name__] = (cls, meta, worker_envs)
            for i in range(num_workers):
                self.spawn(cls, i, worker_envs[i])

        def on_signal(signum, frame):
            self._stopping = True

        signal.signal(signal.SIGINT, on_signal)
        signal.signal(signal.SIGTERM, on_signal)

        exit_code = 0
        try:
            while not self._stopping:
                time.sleep(0.5)
                if self.planner_scaling:
                    self._apply_planner_scaling()
                for name, proc in list(self.children.items()):
                    rc = proc.poll()
                    if rc is None:
                        continue
                    cls_name, replica = name.rsplit("-", 1)
                    if int(replica) >= self.desired.get(cls_name, 0):
                        # scaled-down replica exiting after terminate()
                        self.children.pop(name, None)
                        continue
                    if self.restart and not self._stopping:
                        log.warning("%s exited rc=%s; restarting", name, rc)
                        cls = next(c for c in discover_graph(load_class(self.entry_spec))
                                   if c.__name__ == cls_name)
                        self.spawn(cls, int(replica))
                    else:
                        log.error("%s exited rc=%s", name, rc)
                        exit_code = rc or 1
                        self._stopping = True
                        break
        finally:
            self.shutdown()
        return exit_code

    # ---------------- planner-driven scaling ----------------

    def _read_planner_desired(self) -> dict[str, int]:
        """Fetch planner/{ns}/desired/{component} keys from the control plane.
        Returns {key: replicas}. One short-lived connection per poll."""
        import asyncio

        async def fetch():
            from dynamo_tpu.cplane.client import CplaneClient

            client = CplaneClient(self.cplane)
            await client.connect()
            try:
                items = await client.kv_get_prefix("planner/")
                out = {}
                for i in items:
                    if "/desired/" not in i.key:
                        continue
                    try:
                        out[i.key] = int(json.loads(i.value)["replicas"])
                    except Exception:
                        log.warning("malformed planner key %s", i.key)
                return out
            finally:
                await client.close()

        async def bounded():
            # the monitor loop also does crash-restarts: a hung control plane
            # must not stall it
            return await asyncio.wait_for(fetch(), timeout=3.0)

        return asyncio.run(bounded())

    def _apply_planner_scaling(self) -> None:
        now = time.time()
        if now - self._last_planner_poll < self.planner_poll_s:
            return
        self._last_planner_poll = now
        try:
            desired_by_key = self._read_planner_desired()
        except Exception as e:
            log.debug("planner poll failed: %s", e)
            return
        for cls_name, (cls, meta, envs) in self._class_info.items():
            key = f"planner/{meta.namespace}/desired/{meta.component}"
            want = desired_by_key.get(key)
            if want is None or want == self.desired.get(cls_name):
                continue
            have = self.desired[cls_name]
            log.info("planner: scaling %s %d -> %d", cls_name, have, want)
            self.desired[cls_name] = want
            for i in range(have, want):  # scale up
                # a replica of this index terminated by an earlier scale-down
                # may still be exiting: reap it before reusing the name (two
                # live processes must not share chip assignments)
                old = self.children.pop(f"{cls_name}-{i}", None)
                if old is not None and old.poll() is None:
                    try:
                        old.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        old.kill()
                        old.wait()
                # replicas beyond the initial allocation share its chip
                # assignments round-robin (time-sliced on chip; see allocator)
                env = envs[i % len(envs)] if envs else None
                self.spawn(cls, i, env)
            for i in range(want, have):  # scale down, highest index first
                name = f"{cls_name}-{i}"
                proc = self.children.get(name)
                if proc is not None and proc.poll() is None:
                    proc.terminate()

    def shutdown(self) -> None:
        self._stopping = True
        for name, proc in self.children.items():
            if proc.poll() is None:
                proc.terminate()
        deadline = time.time() + 10
        for proc in self.children.values():
            try:
                proc.wait(max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                proc.kill()
        if self.broker_proc is not None and self.broker_proc.poll() is None:
            self.broker_proc.terminate()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="dynamo-tpu serve", description=__doc__)
    parser.add_argument("entry", help="module.path:ServiceClass")
    parser.add_argument("-f", "--file", default=None, help="YAML service config")
    parser.add_argument("--cplane", default=os.environ.get("DYNTPU_CPLANE", "127.0.0.1:4222"))
    parser.add_argument("--no-restart", action="store_true")
    parser.add_argument(
        "--planner-scaling", action="store_true",
        help="scale service replicas from the planner's desired-replica keys",
    )
    parser.add_argument("overrides", nargs="*", help="--Service.key=value overrides")
    args = parser.parse_args(argv)
    config = ServiceConfig.from_yaml_and_overrides(args.file, args.overrides)
    sup = Supervisor(
        args.entry, config, args.cplane, restart=not args.no_restart,
        planner_scaling=args.planner_scaling,
    )
    return sup.run()


if __name__ == "__main__":
    raise SystemExit(main())
