"""@service / @endpoint / @async_on_start decorators.

reference: deploy/dynamo/sdk/src/dynamo/sdk/lib/service.py:66-110 (@service),
lib/decorators.py:27-59 (@dynamo_endpoint, @async_on_start).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional


@dataclass
class ServiceMeta:
    namespace: str = "dynamo"
    component: str = ""
    workers: int = 1
    resources: dict = field(default_factory=dict)  # e.g. {"tpu": 1}
    config_key: str = ""  # YAML section name (defaults to class name)


def service(
    _cls=None,
    *,
    namespace: str = "dynamo",
    component: Optional[str] = None,
    workers: int = 1,
    resources: Optional[dict] = None,
):
    """Class decorator marking a deployable service."""

    def wrap(cls):
        meta = ServiceMeta(
            namespace=namespace,
            component=component or cls.__name__.lower(),
            workers=workers,
            resources=resources or {},
            config_key=cls.__name__,
        )
        cls.__dynamo_service__ = meta
        # walk the MRO so subclassed services inherit endpoints/hooks
        endpoints: dict[str, dict] = {}
        on_start: list[str] = []
        for name in dir(cls):
            if name.startswith("__"):
                continue
            fn = getattr(cls, name, None)
            if not callable(fn):
                continue
            if hasattr(fn, "__dynamo_endpoint__"):
                endpoints[name] = fn.__dynamo_endpoint__
            if getattr(fn, "__dynamo_on_start__", False):
                on_start.append(name)
        cls.__dynamo_endpoints__ = endpoints
        cls.__dynamo_on_start__ = on_start
        return cls

    return wrap(_cls) if _cls is not None else wrap


def endpoint(_fn=None, *, name: Optional[str] = None):
    """Marks an async-generator method as a served endpoint."""

    def wrap(fn):
        fn.__dynamo_endpoint__ = {"name": name or fn.__name__}
        return fn

    return wrap(_fn) if _fn is not None else wrap


def async_on_start(fn: Callable) -> Callable:
    fn.__dynamo_on_start__ = True
    return fn
