"""Per-service worker entrypoint: runs ONE @service class in this process.

reference: deploy/dynamo/sdk/src/dynamo/sdk/cli/serve_dynamo.py:37-75 —
creates the DistributedRuntime, instantiates the class, serves its @endpoint
methods, runs @async_on_start hooks, then parks until shutdown.
"""

from __future__ import annotations

import argparse
import importlib
import inspect

from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.runtime import Runtime, Worker
from dynamo_tpu.sdk.config import ServiceConfig
from dynamo_tpu.utils import get_logger

log = get_logger("sdk.serve_worker")


def load_class(spec: str):
    module_name, _, cls_name = spec.partition(":")
    module = importlib.import_module(module_name)
    return getattr(module, cls_name)


async def run_service(runtime: Runtime, cls) -> None:
    meta = cls.__dynamo_service__
    drt = DistributedRuntime(runtime=runtime)
    await drt.connect()

    instance = cls()
    instance.runtime = drt
    config = ServiceConfig.load().for_service(meta.config_key)
    instance.config = config

    # bind dependency clients
    for attr, target in getattr(cls, "__dynamo_depends__", {}).items():
        getattr(instance, attr).bind_runtime(drt)

    for hook_name in cls.__dynamo_on_start__:
        hook = getattr(instance, hook_name)
        result = hook()
        if inspect.iscoroutine(result):
            await result

    served = []
    for method_name, ep_meta in cls.__dynamo_endpoints__.items():
        handler = getattr(instance, method_name)
        ep = drt.namespace(meta.namespace).component(meta.component).endpoint(ep_meta["name"])
        metrics = getattr(instance, "stats_handler", None)
        served.append(await ep.serve_endpoint(handler, metrics=metrics))
        log.info("serving %s/%s/%s", meta.namespace, meta.component, ep_meta["name"])

    await runtime.cancellation.cancelled()
    for s in served:
        await s.stop()
    stop = getattr(instance, "on_shutdown", None)
    if stop is not None:
        result = stop()
        if inspect.iscoroutine(result):
            await result


def main(argv=None) -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("service", help="module.path:ClassName")
    args = parser.parse_args(argv)
    cls = load_class(args.service)
    Worker.execute(lambda runtime: run_service(runtime, cls))


if __name__ == "__main__":
    main()
