"""TPU chip assignment for the serve supervisor.

reference: deploy/dynamo/sdk/src/dynamo/sdk/cli/allocator.py:33-134
(ResourceAllocator.assign_gpus / get_worker_env). Ours allocates TPU chips
instead of CUDA devices: each worker process gets a disjoint chip set via
`TPU_VISIBLE_DEVICES` (libtpu honours it the way CUDA honours
CUDA_VISIBLE_DEVICES); services that request no TPU are pinned to
`JAX_PLATFORMS=cpu` so importing jax in them never grabs the chips.

Fractional requests (e.g. {"tpu": 0.5}) co-locate workers on a shared chip —
the workers see the same TPU_VISIBLE_DEVICES and must coordinate HBM use
(time-sliced; there is no TPU MIG equivalent).

Set DYNTPU_DISABLE_TPU_ALLOCATION=1 to manage visibility manually, and
DYNTPU_DEPLOYMENT_ENV for K8s replica mode (every replica gets the same
assignment; the pod boundary provides isolation) — mirrors
DYNAMO_DISABLE_GPU_ALLOCATION / DYNAMO_DEPLOYMENT_ENV.
"""

from __future__ import annotations

import glob
import os
import warnings

DISABLE_TPU_ALLOCATION_ENV = "DYNTPU_DISABLE_TPU_ALLOCATION"
DEPLOYMENT_ENV = "DYNTPU_DEPLOYMENT_ENV"
NUM_CHIPS_ENV = "DYNTPU_TPU_CHIPS"  # override detection, e.g. =4


def detect_tpu_chips() -> int:
    """Count local TPU chips without importing jax (cheap, fork-safe)."""
    if NUM_CHIPS_ENV in os.environ:
        return int(os.environ[NUM_CHIPS_ENV])
    # TPU VM runtimes expose one /dev/accel<N> (or vfio group) per chip.
    accel = glob.glob("/dev/accel[0-9]*")
    if accel:
        return len(accel)
    vfio = [p for p in glob.glob("/dev/vfio/[0-9]*")]
    return len(vfio)


class ResourceAllocator:
    """Splits the host's TPU chips across service workers."""

    def __init__(self, total_chips: int | None = None) -> None:
        self.total_chips = detect_tpu_chips() if total_chips is None else total_chips
        self.remaining_chips: float = float(self.total_chips)
        # each entry: (remaining_fraction, fragment_unit)
        self._chips: list[tuple[float, float]] = [(1.0, 1.0)] * self.total_chips

    def assign_chips(self, count: float) -> list[int]:
        """Assign `count` chips (fractional => shared chip). Returns chip ids."""
        if count > 1 and int(count) != count:
            raise ValueError("fractional TPU requests above 1 chip are not supported")
        if count > self.remaining_chips:
            warnings.warn(
                f"Requested {count} TPU chips, but only {self.remaining_chips} remain. "
                f"Serving may fail; set {DISABLE_TPU_ALLOCATION_ENV}=1 to manage "
                "chip visibility manually.",
                ResourceWarning,
                stacklevel=3,
            )
        self.remaining_chips = max(0.0, self.remaining_chips - count)
        if count < 1:  # fractional: co-locate on a chip already split this way
            try:
                chip = next(
                    i for i, (rem, unit) in enumerate(self._chips)
                    if rem > 0 and unit == count
                )
            except StopIteration:
                try:
                    chip = next(i for i, (rem, _) in enumerate(self._chips) if rem == 1.0)
                except StopIteration:
                    chip = len(self._chips)
                    self._chips.append((1.0, count))
            remaining = self._chips[chip][0] - count
            self._chips[chip] = (remaining if remaining >= count else 0.0, count)
            return [chip]
        count = int(count)
        free = [i for i, (rem, unit) in enumerate(self._chips) if rem > 0 and unit == 1.0]
        if len(free) < count:
            warnings.warn(
                f"Not enough TPU chips: {count} requested", ResourceWarning, stacklevel=3
            )
            while len(free) < count:
                free.append(len(self._chips))
                self._chips.append((1.0, 1.0))
        for chip in free[:count]:
            self._chips[chip] = (0.0, 1.0)
        return free[:count]

    def get_worker_env(self, meta, config: dict) -> tuple[int, list[dict[str, str]]]:
        """(num_workers, per-worker env) for a service.

        `meta` is the ServiceMeta from @service; `config` the service's YAML
        section (may override workers/resources).
        """
        resources = config["resources"] if "resources" in config else meta.resources
        resources = resources or {}
        num_chips = resources.get("tpu", 0)
        workers = config.get("workers", meta.workers)
        if workers == "cpu_count":
            workers = os.cpu_count() or 1
            num_chips = 0
        num_workers = int(workers)

        if not num_chips or os.environ.get(DISABLE_TPU_ALLOCATION_ENV):
            # No chips for this service: keep jax off the TPU entirely.
            env = {"JAX_PLATFORMS": "cpu"} if not num_chips else {}
            return num_workers, [dict(env) for _ in range(num_workers)]

        if self.total_chips == 0:
            # No local chips detected (dev box, or TPU attached via a tunnel
            # that /dev scanning can't see): leave visibility untouched.
            return num_workers, [{} for _ in range(num_workers)]

        if os.environ.get(DEPLOYMENT_ENV):
            # K8s replicas: every replica pod gets the same visible set.
            assigned = self.assign_chips(num_chips)
            vis = ",".join(map(str, assigned))
            return num_workers, [
                {"TPU_VISIBLE_DEVICES": vis} for _ in range(num_workers)
            ]

        worker_env = []
        for _ in range(num_workers):
            assigned = self.assign_chips(num_chips)
            worker_env.append({"TPU_VISIBLE_DEVICES": ",".join(map(str, assigned))})
        return num_workers, worker_env
