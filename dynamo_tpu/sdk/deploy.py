"""`dynamo-tpu deploy`: manage deployments against the deploy API server.

The reference's `dynamo deploy` pushes built artifacts to its cloud API
server (reference: deploy/dynamo/api-server REST CRUD); this is the client
CLI for the native analogue (dynamo_tpu/deploy/api_server.py):

    dynamo-tpu deploy create  build/deployment.yaml  --server http://host:port
    dynamo-tpu deploy list | get NAME | delete NAME
    dynamo-tpu deploy revisions NAME | rollback NAME REV | manifests NAME

Accepts either a built artifact directory (uses its deployment.yaml) or a
DeploymentSpec YAML/JSON file directly.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.request
from pathlib import Path


class DeployClient:
    def __init__(self, server: str):
        self.base = server.rstrip("/")

    def _req(self, method: str, path: str, body: dict | None = None) -> dict | list:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            f"{self.base}{path}", data=data, method=method,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                payload = resp.read()
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")
            raise SystemExit(f"{method} {path} -> HTTP {e.code}: {detail}")
        return json.loads(payload) if payload else {}

    def create(self, spec: dict):
        return self._req("POST", "/api/v1/deployments", spec)

    def update(self, name: str, spec: dict):
        return self._req("PUT", f"/api/v1/deployments/{name}", spec)

    def list(self):
        return self._req("GET", "/api/v1/deployments")

    def get(self, name: str):
        return self._req("GET", f"/api/v1/deployments/{name}")

    def delete(self, name: str):
        return self._req("DELETE", f"/api/v1/deployments/{name}")

    def revisions(self, name: str):
        return self._req("GET", f"/api/v1/deployments/{name}/revisions")

    def rollback(self, name: str, rev: int):
        return self._req("POST", f"/api/v1/deployments/{name}/rollback/{rev}")

    def manifests(self, name: str):
        return self._req("GET", f"/api/v1/deployments/{name}/manifests")


def load_spec(path: str) -> dict:
    """Spec dict from a built artifact dir, a YAML file, or a JSON file."""
    import yaml

    p = Path(path)
    if p.is_dir():
        p = p / "deployment.yaml"
    text = p.read_text()
    return yaml.safe_load(text)


def main(argv=None) -> int:
    # --server accepted before OR after the action (parents= shares it with
    # every subparser)
    common = argparse.ArgumentParser(add_help=False)
    # SUPPRESS: a subparser must not clobber a --server given before the
    # action with its own default
    common.add_argument("--server", default=argparse.SUPPRESS, help="deploy API server")
    parser = argparse.ArgumentParser(
        prog="dynamo-tpu deploy", description=__doc__, parents=[common]
    )
    sub = parser.add_subparsers(dest="action", required=True)
    c = sub.add_parser("create", parents=[common],
                       help="create/update a deployment from a spec or artifact")
    c.add_argument("spec", help="artifact dir or DeploymentSpec yaml/json")
    u = sub.add_parser("update", parents=[common], help="update an existing deployment")
    u.add_argument("spec")
    sub.add_parser("list", parents=[common], help="list deployments")
    for act in ("get", "delete", "revisions", "manifests"):
        a = sub.add_parser(act, parents=[common])
        a.add_argument("name")
    r = sub.add_parser("rollback", parents=[common])
    r.add_argument("name")
    r.add_argument("rev", type=int)
    args = parser.parse_args(argv)

    client = DeployClient(getattr(args, "server", "http://127.0.0.1:8180"))
    if args.action == "create":
        out = client.create(load_spec(args.spec))
    elif args.action == "update":
        spec = load_spec(args.spec)
        out = client.update(spec["name"], spec)
    elif args.action == "list":
        out = client.list()
    elif args.action == "get":
        out = client.get(args.name)
    elif args.action == "delete":
        out = client.delete(args.name)
    elif args.action == "revisions":
        out = client.revisions(args.name)
    elif args.action == "manifests":
        out = client.manifests(args.name)
    elif args.action == "rollback":
        out = client.rollback(args.name, args.rev)
    print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
