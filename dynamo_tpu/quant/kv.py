"""Int8 KV-cache quantization: per-page (per token row) symmetric scales.

Why quantize the CACHE and not just the weights: the prefill-bound reference
workload reads the whole paged context once per layer per chunk, and decode
re-reads every live sequence's pages each step — with the weights already
int8 (quant/int8.py) the KV stream is the next-largest HBM term. Storing the
pools as int8 halves that traffic and DOUBLES page capacity at the same HBM
budget (bigger batches, deeper prefix cache, cheaper host offload and disagg
transfer). KIVI (Liu et al., 2024) and KVQuant show int8/low-bit KV with
per-block scales preserves generation quality.

Scale placement — one f32 scale per (page, token-row), i.e. a ``[pages,
page_size]`` scale plane next to each ``[pages, page_size, ...]`` int8 pool:

  - quantization is INCREMENTAL: decode appends one row at a time, and a
    per-row scale means a new token never forces requantizing the rows
    already in its page (a single scalar per page would);
  - the scale multiplies factor out of the attention algebra exactly:
    ``q . (s_j * k_j) == s_j * (q . k_j)`` scales the score column and
    ``sum_j p_j * (s_j * v_j) == (p_j * s_j) . v_j`` scales the prob column,
    so the Pallas kernels apply scales to score/prob TILES after the int8
    DMA (HBM reads stay int8; dequant never touches HBM) with lane-axis
    broadcasts only — no sub-128 minor-dim reshapes (Mosaic-safe);
  - a page's scales travel WITH the page: the disagg dataplane ships them in
    the part header and the host offload tier stores them beside the block.

``QuantizedPages`` mirrors ``QuantizedLinear``: a registered pytree node
that rides everywhere the plain pool rode — the layer-scan carry, jit
donation (both leaves alias in place), device_put with a mirrored sharding
tree, and shard_map in_specs. It proxies ``shape``/``dtype``/``ndim`` to the
int8 pool so the geometry probes sprinkled through the engine
(``k_pool.shape[1]``, ``k_pages.ndim == 3``) keep working unchanged.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

#: EngineConfig.kv_cache_dtype values (None means bf16 == the model dtype)
KV_CACHE_DTYPES = ("bf16", "int8")

_INT8_MAX = 127.0


@jax.tree_util.register_pytree_node_class
class QuantizedPages:
    """One int8 KV page pool + its per-row f32 scale plane.

    q: int8 ``[pages, page_size, Hkv, D]`` (or ``[pages, page_size, Hkv*D]``
       folded — same layouts as the bf16 pool it replaces)
    s: f32 ``[pages, page_size]`` — one scale per token row (absmax over the
       row's head values / 127)
    """

    __slots__ = ("q", "s")

    def __init__(self, q, s):
        self.q = q
        self.s = s

    def tree_flatten(self):
        return (self.q, self.s), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    # geometry proxies: engine/model code probes the POOL's shape
    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.q.dtype

    @property
    def ndim(self):
        return self.q.ndim

    def __repr__(self):  # pragma: no cover - debugging aid
        return (
            f"QuantizedPages(q={getattr(self.q, 'shape', None)}, "
            f"s={getattr(self.s, 'shape', None)})"
        )


def quantize_kv_rows(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """``[T, ...]`` fresh K or V rows -> (int8 rows, f32 per-row scales [T]).

    Symmetric per-row absmax over every non-leading axis; the floor keeps an
    all-zero row (padding) dividing cleanly to zeros."""
    x32 = jnp.asarray(x, jnp.float32)
    absmax = jnp.max(jnp.abs(x32), axis=tuple(range(1, x32.ndim)))  # [T]
    scale = jnp.maximum(absmax, 1e-12) / _INT8_MAX
    bshape = (x32.shape[0],) + (1,) * (x32.ndim - 1)
    q = jnp.clip(jnp.round(x32 / scale.reshape(bshape)), -_INT8_MAX, _INT8_MAX)
    return q.astype(jnp.int8), scale


def dequantize_rows(q: jnp.ndarray, s: jnp.ndarray, dtype=jnp.float32) -> jnp.ndarray:
    """Inverse of quantize_kv_rows over any leading batch of rows: ``s``
    broadcasts from the leading axes (q.ndim - s.ndim trailing dims added)."""
    s_b = jnp.asarray(s, jnp.float32).reshape(s.shape + (1,) * (q.ndim - s.ndim))
    return (q.astype(jnp.float32) * s_b).astype(dtype)


def init_quantized_pages(shape: tuple[int, ...]) -> QuantizedPages:
    """Zeroed int8 pool + zeroed scale plane for ``kv_cache_shape`` output."""
    return QuantizedPages(
        q=jnp.zeros(shape, jnp.int8),
        s=jnp.zeros(shape[:2], jnp.float32),
    )


def kv_page_bytes(page_size: int, num_kv_heads: int, head_dim: int,
                  num_layers: int, kv_cache_dtype: str | None,
                  itemsize: int = 2) -> int:
    """HBM bytes ONE allocator page costs across all layers (K and V,
    including the int8 scale planes). The capacity arithmetic behind the
    "~2x pages at the same HBM budget" claim — and the number dynotop and
    the resource gauges render instead of assuming bf16. ``itemsize`` is the
    full-precision element size (2 = bf16 serving; tiny test models run
    f32)."""
    row_vals = num_kv_heads * head_dim
    if kv_cache_dtype == "int8":
        per_row = row_vals * 1 + 4  # int8 values + one f32 scale
    else:
        per_row = row_vals * itemsize
    return 2 * num_layers * page_size * per_row  # x2: K and V


def pages_for_hbm_budget(budget_bytes: int, page_size: int, num_kv_heads: int,
                         head_dim: int, num_layers: int,
                         kv_cache_dtype: str | None, itemsize: int = 2) -> int:
    """How many KV pages fit a device-memory budget at a given cache dtype
    (page 0 is the allocator's reserved trash page, so usable pages are one
    fewer)."""
    return budget_bytes // max(
        1, kv_page_bytes(page_size, num_kv_heads, head_dim, num_layers,
                         kv_cache_dtype, itemsize)
    )


# ---------------- wire helpers ----------------
# Quantized KV travels as {"q": int8 [L, 2, n, ps, ...], "s": f32
# [L, 2, n, ps]} — the scale plane rides next to the data with the SAME page
# axis, so every per-page slicing/concat path (host offload, streamed disagg
# parts, bucketed scatter padding) maps one helper call over both leaves.


def is_quantized_wire(data) -> bool:
    return isinstance(data, dict) and "q" in data and "s" in data


def wire_nbytes(data) -> int:
    """Payload bytes of a wire block (dict or plain ndarray)."""
    if is_quantized_wire(data):
        return int(data["q"].nbytes) + int(data["s"].nbytes)
    return int(data.nbytes)


def wire_concat(blocks: list, axis: int):
    """Concatenate wire blocks along the page axis (dict-aware)."""
    if is_quantized_wire(blocks[0]):
        return {
            "q": np.concatenate([b["q"] for b in blocks], axis=axis),
            "s": np.concatenate([b["s"] for b in blocks], axis=axis),
        }
    return np.concatenate(blocks, axis=axis)


def wire_split(data, axis: int, n: int) -> list:
    """Split a wire block of ``n`` pages into per-page blocks (dict-aware).
    Each block is copied out so dropping one later frees its bytes instead
    of pinning the whole parent gather."""

    def _split(a):
        return [np.ascontiguousarray(b) for b in np.split(a, n, axis=axis)]

    if is_quantized_wire(data):
        return [
            {"q": q, "s": s}
            for q, s in zip(_split(data["q"]), _split(data["s"]))
        ]
    return _split(data)


def wire_pad(data, axis: int, pad: int):
    """Zero-pad ``pad`` pages onto the page axis (dict-aware). Pad pages are
    scatter-dropped by out-of-range ids, so zeros are never read."""
    if pad <= 0:
        return data

    def _pad(a):
        shape = list(a.shape)
        shape[axis] = pad
        return np.concatenate([a, np.zeros(shape, a.dtype)], axis=axis)

    if is_quantized_wire(data):
        return {"q": _pad(data["q"]), "s": _pad(data["s"])}
    return _pad(data)
