"""Quantization for the serving hot path.

``int8_wo`` (weights): symmetric per-output-channel int8 weights with f32
scales, dequantized into the matmul — see dynamo_tpu/quant/int8.py.
``kv_cache_dtype="int8"`` (cache): int8 KV pages with per-(page, token-row)
f32 scales — see dynamo_tpu/quant/kv.py. The two compose independently.
"""

from dynamo_tpu.quant.int8 import (
    QUANT_MODES,
    QuantizedLinear,
    dequantize_int8,
    qlinear,
    qlinear_expert,
    quantize_int8,
    quantize_shardings_int8,
    quantize_tree_int8,
)
from dynamo_tpu.quant.kv import (
    KV_CACHE_DTYPES,
    QuantizedPages,
    dequantize_rows,
    init_quantized_pages,
    kv_page_bytes,
    pages_for_hbm_budget,
    quantize_kv_rows,
)

__all__ = [
    "KV_CACHE_DTYPES",
    "QUANT_MODES",
    "QuantizedLinear",
    "QuantizedPages",
    "dequantize_int8",
    "dequantize_rows",
    "init_quantized_pages",
    "kv_page_bytes",
    "pages_for_hbm_budget",
    "qlinear",
    "qlinear_expert",
    "quantize_int8",
    "quantize_kv_rows",
    "quantize_shardings_int8",
    "quantize_tree_int8",
]
