"""Weight-only quantization for the serving hot path.

``int8_wo`` (the only mode so far): symmetric per-output-channel int8 weights
with f32 scales, dequantized into the matmul — see dynamo_tpu/quant/int8.py.
"""

from dynamo_tpu.quant.int8 import (
    QUANT_MODES,
    QuantizedLinear,
    dequantize_int8,
    qlinear,
    qlinear_expert,
    quantize_int8,
    quantize_shardings_int8,
    quantize_tree_int8,
)

__all__ = [
    "QUANT_MODES",
    "QuantizedLinear",
    "dequantize_int8",
    "qlinear",
    "qlinear_expert",
    "quantize_int8",
    "quantize_shardings_int8",
    "quantize_tree_int8",
]
