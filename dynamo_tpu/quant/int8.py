"""Weight-only int8 quantization: symmetric per-output-channel scales.

Why weight-only, why int8: the decode window is weight-bound — the r5 roofline
decomposition has the bf16 weight stream as the dominant HBM term of every
decode step — so storing the big linear weights as int8 (+ one f32 scale per
output channel) halves the bytes each step reads. The matmul stays on the MXU
at the activation dtype: the int8 weight is converted on the fly inside the
fused dot (XLA folds the convert into the weight read on TPU — the HBM
traffic is the int8 bytes, not the upcast bf16 bytes), accumulated in f32 via
``preferred_element_type``, and the per-channel scale is applied to the f32
product. Per-OUTPUT-channel symmetric scales make that exact algebra:

    h @ dequant(q, s) == (h @ q) * s        (s broadcast over output channels)

so no zero points, no activation quantization, and the scale multiply commutes
with the tensor-parallel psum of row-parallel layers.

Layout contract (matches every weight this framework stores): weights are
``[..., in, out]`` — leading stack axes (layers ``L``, experts ``E``), then
the contracted (input) axis SECOND-TO-LAST, the output-channel axis LAST.
``QuantizedLinear.s`` therefore has the weight's shape with the ``in`` axis
removed, which keeps the container scan-sliceable (``lax.scan`` over the
layer stack slices ``q`` and ``s`` together) and makes the sharding rule
mechanical: ``q`` keeps the bf16 weight's sharding; ``s`` keeps the same spec
minus the contracted-axis entry (so scales follow their weight's
output-channel sharding and replicate everywhere else — in particular they
replicate across tp for row-parallel weights, and shard on the stage axis
under pp exactly like the weight's leading ``[L]`` dim).

What never quantizes: embeddings, the lm_head, norms, biases, MoE routers
(f32 by design), and the MLA k-up/v-up banks (3-D per-head einsum operands,
~1% of bytes). Models list their quantizable leaves in
``QUANT_WEIGHT_NAMES`` (models/llama.py etc.).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

#: modes EngineConfig.quantize / model configs accept (None = full precision)
QUANT_MODES = ("int8_wo",)

_INT8_MAX = 127.0


@jax.tree_util.register_pytree_node_class
class QuantizedLinear:
    """Param container for one weight-only-int8 linear weight.

    q: int8 ``[..., in, out]`` — same layout as the bf16 weight it replaces
    s: f32 ``[..., out]`` — per-output-channel scales (``in`` axis removed)

    Registered as a pytree node so the container rides everything the plain
    weight rode: ``lax.scan`` over layer stacks (both leaves slice on the
    leading axis), ``jax.device_put`` with a mirrored sharding tree
    (quantize_shardings_int8), jit/eval_shape, and the pipeline shard_map's
    in_specs trees.
    """

    __slots__ = ("q", "s")

    def __init__(self, q, s):
        self.q = q
        self.s = s

    def tree_flatten(self):
        return (self.q, self.s), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.q.dtype

    def __repr__(self):  # pragma: no cover - debugging aid
        qs = getattr(self.q, "shape", None)
        return f"QuantizedLinear(q={qs}, s={getattr(self.s, 'shape', None)})"


def quantize_int8(w) -> QuantizedLinear:
    """``[..., in, out]`` weight -> symmetric per-output-channel int8.

    scale[..., o] = max_i |w[..., i, o]| / 127 (floored so an all-zero channel
    divides cleanly); q = round(w / scale) clipped to [-127, 127].
    """
    w32 = jnp.asarray(w, jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=-2)  # [..., out]
    scale = jnp.maximum(absmax, 1e-12) / _INT8_MAX
    q = jnp.clip(jnp.round(w32 / scale[..., None, :]), -_INT8_MAX, _INT8_MAX)
    return QuantizedLinear(q=q.astype(jnp.int8), s=scale)


def dequantize_int8(w: QuantizedLinear, dtype=jnp.float32) -> jnp.ndarray:
    """Materialize the full-precision weight (tests / offline tooling only —
    the hot path never materializes it; see qlinear)."""
    return (w.q.astype(jnp.float32) * w.s[..., None, :]).astype(dtype)


def qlinear(h, w):
    """``h @ w`` for a plain 2-D weight or a (scan-sliced, 2-D) QuantizedLinear.

    Quantized: one fused dot — int8 weight upcast on the fly to the
    activation dtype (HBM reads stay int8 on TPU), f32 accumulation, then the
    per-output-channel scale on the f32 product, cast back to h.dtype. The
    scale multiply is per OUTPUT channel, so under tensor parallelism it is
    correct both before a row-parallel psum (it distributes over the sum) and
    on column-parallel output shards (s shards with the same channels).
    """
    if not isinstance(w, QuantizedLinear):
        return h @ w
    y = jax.lax.dot_general(
        h,
        w.q.astype(h.dtype),
        (((h.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return (y * w.s).astype(h.dtype)


def qlinear_expert(x, w):
    """Batched expert matmul ``[E, C, in] x [E, in, out] -> [E, C, out]`` for
    plain or quantized expert banks (the MoE block's per-expert FFN)."""
    if not isinstance(w, QuantizedLinear):
        return jnp.einsum("eci,eio->eco", x, w)
    y = jnp.einsum(
        "eci,eio->eco",
        x,
        w.q.astype(x.dtype),
        preferred_element_type=jnp.float32,
    )
    return (y * w.s[:, None, :]).astype(x.dtype)


def quantize_tree_int8(group: dict, names) -> dict:
    """Replace the named leaves of one layer-group dict with QuantizedLinear
    containers (idempotent: already-quantized leaves and absent names skip)."""
    out = dict(group)
    for k, v in group.items():
        if k in names and not isinstance(v, QuantizedLinear):
            out[k] = quantize_int8(v)
    return out


def _scale_sharding(ws: NamedSharding) -> NamedSharding:
    """The scale sharding mirroring a weight's: same spec with the contracted
    (second-to-last) axis entry removed. Model shardings in this codebase are
    full-rank PartitionSpecs, so positional deletion is exact."""
    spec = list(ws.spec)
    del spec[-2]
    return NamedSharding(ws.mesh, P(*spec))


def quantize_shardings_int8(group: dict, names) -> dict:
    """Mirror quantize_tree_int8 onto a sharding tree: the named NamedSharding
    leaves become QuantizedLinear(q=<weight sharding>, s=<scale sharding>) so
    jax.device_put sees structurally matching param/sharding trees."""
    out = dict(group)
    for k, v in group.items():
        if k in names and not isinstance(v, QuantizedLinear):
            out[k] = QuantizedLinear(q=v, s=_scale_sharding(v))
    return out
