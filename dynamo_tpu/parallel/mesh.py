"""Device mesh construction + multihost bootstrap.

The TPU replacement for the reference's multi-node engine bootstrap
(reference: lib/llm/src/engines/vllm/ray.rs leader/follower + NCCL env,
SURVEY.md §2.8 row "Multi-node engine bootstrap"): on TPU pods a single SPMD
program spans hosts after ``jax.distributed.initialize``; there is no Ray and
no NCCL — XLA collectives ride ICI/DCN.

Axes convention (any subset may be 1):
  dp — engine replicas (data parallel; usually separate processes instead)
  tp — tensor parallel (attention heads / MLP hidden)
  sp — sequence/context parallel (ring attention prefill)
  ep — expert parallel (MoE expert banks)
  pp — pipeline stages (GPipe rotation, parallel/pipeline.py)
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

import numpy as np
import jax
from jax.sharding import Mesh

from dynamo_tpu.utils import get_logger

log = get_logger("parallel.mesh")


@dataclass
class MeshConfig:
    tp: int = 1
    dp: int = 1
    sp: int = 1
    ep: int = 1
    pp: int = 1

    @property
    def num_devices(self) -> int:
        return self.tp * self.dp * self.sp * self.ep * self.pp

    def axis_sizes(self) -> dict[str, int]:
        return {"dp": self.dp, "pp": self.pp, "sp": self.sp, "ep": self.ep, "tp": self.tp}


def init_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialize the JAX distributed runtime across TPU hosts.

    No-ops on a single host. Values default from DYNTPU_COORDINATOR /
    DYNTPU_NUM_PROCESSES / DYNTPU_PROCESS_ID (set by the serve supervisor or
    the pod launcher).
    """
    coordinator_address = coordinator_address or os.environ.get("DYNTPU_COORDINATOR")
    if not coordinator_address:
        return
    num_processes = num_processes or int(os.environ.get("DYNTPU_NUM_PROCESSES", "1"))
    process_id = process_id if process_id is not None else int(os.environ.get("DYNTPU_PROCESS_ID", "0"))
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    log.info(
        "multihost initialized: process %d/%d, %d global devices",
        process_id, num_processes, len(jax.devices()),
    )


def place_global(tree, shardings):
    """Place a host pytree onto (possibly multi-process) shardings.

    Single process: plain device_put. Multi-process SPMD: every process holds
    the full host value (same PRNG seed / same checkpoint) and contributes its
    addressable shards via make_array_from_callback — device_put cannot
    target non-addressable devices."""
    if jax.process_count() == 1:
        return jax.device_put(tree, shardings)

    def place(x, s):
        x = np.asarray(x)
        return jax.make_array_from_callback(x.shape, s, lambda idx: x[idx])

    return jax.tree.map(place, tree, shardings)


def build_mesh(config: MeshConfig, devices=None) -> Mesh:
    """Mesh with axes (dp, pp, sp, ep, tp); tp innermost so it lands on the
    fastest ICI neighbor links."""
    if devices is None:
        devices = jax.devices()
    n = config.num_devices
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(
        config.dp, config.pp, config.sp, config.ep, config.tp
    )
    return Mesh(arr, ("dp", "pp", "sp", "ep", "tp"))
