"""Pipeline parallelism: GPipe-style stage rotation over a ``pp`` mesh axis.

The reference explicitly leaves pipeline parallel unsupported (forced to 1 in
its disagg path — reference: examples/llm/components/worker.py:76-78); here it
is a first-class scheme, designed around this framework's scan-stacked layers
and flat KV page pool:

  - **Stage sharding is just array sharding.** Every layer weight carries a
    leading ``[L]`` axis and the KV pool is layer-major ``[L * num_pages, ...]``,
    so sharding that leading axis over ``pp`` puts each stage's weights AND its
    layers' KV pages on the same device with no layout change (L % pp == 0).
  - **GPipe microbatch rotation under shard_map.** Prefill splits the token
    chunk into M microbatches; decode splits the batch slots. Each of the
    M + S - 1 rotation steps runs every stage's local layer scan on its
    current microbatch, then ``ppermute``s activations to the next stage over
    ICI. Bubble fraction = (S-1)/(M+S-1).
  - **Causality across token microbatches is free.** Microbatch m's attention
    gathers K/V from the (stage-local) page pool, where microbatches < m have
    already scattered their rows — the position mask does the rest. No
    cross-microbatch attention plumbing at all.

All control flow is static (masked writes route to each layer's trash page
when a stage is idle in the ramp-up/ramp-down steps), so the whole pipeline is
ONE compiled program per shape bucket.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.ops.attention import (
    _tp_shard_map,
    paged_decode_attention,
    paged_prefill_attention,
)


def stage_layer_specs(model, mesh: Mesh, pp_axis: str = "pp"):
    """PartitionSpec pytree for params["layers"]: leading [L] axis over pp,
    composed with the model's own tp column/row sharding when the mesh
    carries a ``tp`` axis (each leaf's spec from model.param_shardings always
    names the leading L dim explicitly, so dim 0 swaps cleanly)."""
    if "tp" in mesh.axis_names:
        base = model.param_shardings(mesh)["layers"]
        return jax.tree.map(lambda s: P(pp_axis, *s.spec[1:]), base)
    shapes = jax.eval_shape(model.init_params, jax.random.key(0))
    return jax.tree.map(
        lambda leaf: P(*((pp_axis,) + (None,) * (len(leaf.shape) - 1))),
        shapes["layers"],
    )


def stage_param_shardings(model, mesh: Mesh, pp_axis: str = "pp") -> dict:
    """NamedSharding pytree: layer-stacked leaves sharded on their leading [L]
    axis over pp (composed with tp when the mesh has a tp axis); the
    pipeline-edge leaves (embed / lm_head / final norm) keep the model's own
    shardings — they run outside the pp shard_map under GSPMD."""
    def ns(spec):
        return NamedSharding(mesh, spec)

    if "tp" in mesh.axis_names:
        shardings = dict(model.param_shardings(mesh))
    else:
        shapes = jax.eval_shape(model.init_params, jax.random.key(0))
        shardings = jax.tree.map(lambda _: ns(P()), shapes)
    shardings["layers"] = jax.tree.map(ns, stage_layer_specs(model, mesh, pp_axis))
    return shardings


def kv_pool_spec(mesh: Mesh, pp_axis: str = "pp", folded: bool = False) -> P:
    """Flat-pool PartitionSpec: layer-major rows over pp, kv heads over tp
    (when present). Folded pools carry heads in the lane dim."""
    tp = "tp" if "tp" in mesh.axis_names else None
    if folded:
        return P(pp_axis, None, tp)
    return P(pp_axis, None, tp, None)


def stage_kv_sharding(mesh: Mesh, pp_axis: str = "pp", folded: bool = False) -> dict:
    """Layer-major pool split over pp (x tp on heads when the mesh has it);
    `folded` = sub-128 head_dim pools ([LP, ps, Hkv*D] — LlamaConfig.kv_folded)."""
    ns = NamedSharding(mesh, kv_pool_spec(mesh, pp_axis, folded))
    return {"k": ns, "v": ns}


def _local_layer_scan(model, local_layers, kp, vp, hidden, positions, phys, offsets, attn_maker, num_pages, rope_positions=None, tp_axis=None, sp_axis=None):
    """Run this stage's layer slice over one microbatch. phys holds per-token
    LOGICAL page ids (trash-routed already); layer offsets are stage-local.
    With ``tp_axis`` set the layers run on their local head shard and psum
    over tp inside model._layer (composed pp x tp shard_map); with
    ``sp_axis`` set the token dim is sp-sharded and the layer all-gathers
    fresh K/V rows over sp before the pool scatter (composed pp x sp)."""
    L_loc = kp.shape[0] // num_pages
    layer_offsets = jnp.arange(L_loc, dtype=jnp.int32) * num_pages
    kwargs = {}
    if tp_axis is not None:
        kwargs["tp_axis"] = tp_axis
    if sp_axis is not None:
        kwargs["sp_axis"] = sp_axis

    def body(carry, xs):
        h, kp_, vp_ = carry
        lp, off = xs
        h, kp_, vp_ = model._layer(
            lp, h, kp_, vp_, positions, off + phys, offsets, attn_maker(off),
            rope_positions=rope_positions, **kwargs,
        )
        return (h, kp_, vp_), None

    (hidden, kp, vp), _ = jax.lax.scan(
        body, (hidden, kp, vp), (local_layers, layer_offsets)
    )
    return hidden, kp, vp


def _gpipe_rotate(mesh, pp_axis, S, M, run_mb, hidden_mbs, kp, vp):
    """The rotation loop shared by prefill and decode.

    run_mb(m_clipped, active, x, kp, vp) -> (y, kp, vp) runs this stage's
    layers on microbatch index m (clipped; ``active`` masks ramp steps).
    Returns (outputs [M, ...] from the last stage, psum-replicated; kp; vp).
    """
    stage = jax.lax.axis_index(pp_axis)
    outputs = jnp.zeros_like(hidden_mbs)
    x_recv = jnp.zeros_like(hidden_mbs[0])

    def step(carry, t):
        x_recv, kp, vp, outputs = carry
        m = t - stage
        active = (m >= 0) & (m < M)
        mc = jnp.clip(m, 0, M - 1)
        x = jnp.where(stage == 0, hidden_mbs[mc], x_recv)
        y, kp, vp = run_mb(mc, active, x, kp, vp)
        write = (stage == S - 1) & active
        outputs = outputs.at[mc].set(jnp.where(write, y, outputs[mc]))
        x_next = jax.lax.ppermute(y, pp_axis, [(i, (i + 1) % S) for i in range(S)])
        return (x_next, kp, vp, outputs), None

    (x_recv, kp, vp, outputs), _ = jax.lax.scan(
        step, (x_recv, kp, vp, outputs), jnp.arange(M + S - 1, dtype=jnp.int32)
    )
    # only the last stage holds real outputs; psum replicates them so the
    # result can leave shard_map with a replicated spec
    outputs = jax.lax.psum(outputs, pp_axis)
    return outputs, kp, vp


def prefill_pipelined(
    model,
    params: dict,
    kv_cache: dict,  # {"k","v"} flat pools sharded stage-major (donated)
    tokens: jnp.ndarray,  # [T] padded chunk, T % M == 0
    positions: jnp.ndarray,  # [T]
    page_table: jnp.ndarray,  # [max_pages] logical page ids
    valid: jnp.ndarray,  # [T]
    last_idx: jnp.ndarray,
    mesh: Mesh,
    pp_axis: str = "pp",
    num_microbatches: int | None = None,
    input_embeds: jnp.ndarray | None = None,  # [T, D] mm overrides
    embeds_mask: jnp.ndarray | None = None,  # [T]
    rope_positions: jnp.ndarray | None = None,  # [T, 3] M-RoPE components
) -> tuple[jnp.ndarray, dict]:
    """Pipelined single-sequence prefill. Returns (logits[V] at last_idx, kv)."""
    c = model.config
    S = mesh.shape[pp_axis]
    M = num_microbatches or S
    T = tokens.shape[0]
    assert c.num_layers % S == 0, f"L={c.num_layers} not divisible by pp={S}"
    assert T % M == 0, f"chunk {T} not divisible by microbatches {M}"
    Tm = T // M

    k_pool, v_pool = kv_cache["k"], kv_cache["v"]
    page_size = k_pool.shape[1]
    num_pages = k_pool.shape[0] // c.num_layers
    phys = jnp.where(valid, page_table[positions // page_size], 0)
    offsets = jnp.where(valid, positions % page_size, 0)

    hidden = params["embed"][tokens].astype(c.dtype)
    if input_embeds is not None:
        hidden = jnp.where(embeds_mask[:, None], input_embeds.astype(c.dtype), hidden)
    hidden_mbs = hidden.reshape(M, Tm, -1)
    pos_mbs = positions.reshape(M, Tm)
    phys_mbs = phys.reshape(M, Tm)
    off_mbs = offsets.reshape(M, Tm)
    # M-RoPE components ride alongside (equal components for pure text)
    rp3 = (
        rope_positions
        if rope_positions is not None
        else jnp.stack([positions] * 3, axis=-1)
    )
    rp_mbs = rp3.reshape(M, Tm, 3)

    folded = getattr(model.config, "kv_folded", False)
    spec_pool = kv_pool_spec(mesh, pp_axis, folded)
    tp_axis = "tp" if "tp" in mesh.axis_names else None
    layer_specs = stage_layer_specs(model, mesh, pp_axis)
    rep = P()

    @partial(
        _tp_shard_map,  # jax.shard_map across the pre/post-0.8 API split
        mesh=mesh,
        in_specs=(layer_specs, spec_pool, spec_pool, rep, rep, rep, rep, rep, rep),
        out_specs=(rep, spec_pool, spec_pool),
    )
    def run(local_layers, kp, vp, hidden_mbs, pos_mbs, phys_mbs, off_mbs, rp_mbs, page_table):
        def run_mb(mc, active, x, kp, vp):
            pos = pos_mbs[mc]
            # idle ramp steps write to the layer trash page (logical 0)
            phys_mb = jnp.where(active, phys_mbs[mc], 0)
            off_mb = jnp.where(active, off_mbs[mc], 0)

            def attn_maker(off):
                def attn_fn(q, k_new, v_new, kp_, vp_):
                    return paged_prefill_attention(q, kp_, vp_, off + page_table, pos)

                return attn_fn

            return _local_layer_scan(
                model, local_layers, kp, vp, x, pos, phys_mb, off_mb, attn_maker, num_pages,
                rope_positions=rp_mbs[mc], tp_axis=tp_axis,
            )

        return _gpipe_rotate(mesh, pp_axis, S, M, run_mb, hidden_mbs, kp, vp)

    outputs, k_pool, v_pool = run(
        params["layers"], k_pool, v_pool, hidden_mbs, pos_mbs, phys_mbs, off_mbs, rp_mbs, page_table
    )
    hidden_out = outputs.reshape(T, -1)
    logits = model._unembed(params, hidden_out[last_idx][None, :])[0]
    return logits, {"k": k_pool, "v": v_pool}


def prefill_pipelined_ring(
    model,
    params: dict,
    kv_cache: dict,  # {"k","v"} flat pools sharded stage-major (donated)
    tokens: jnp.ndarray,  # [T] padded FULL prompt, start at pos 0, T % sp == 0
    positions: jnp.ndarray,  # [T] == arange(T)
    page_table: jnp.ndarray,  # [max_pages] logical page ids
    valid: jnp.ndarray,  # [T]
    last_idx: jnp.ndarray,
    mesh: Mesh,
    pp_axis: str = "pp",
    sp_axis: str = "sp",
) -> tuple[jnp.ndarray, dict]:
    """Composed pp x sp whole-prompt prefill: GPipe stage rotation over pp
    with the token axis sharded over sp and ring attention inside each stage
    (the 70B long-context mesh — depth over pp, length over sp — that the
    round-4 design left mutually exclusive; no reference analogue, the
    reference has no sequence parallelism at all).

    Single microbatch (M=1): ring attention consumes the chunk's fresh K/V
    rows directly, which is only causal when the whole prompt is one
    microbatch — cross-microbatch attention would need a paged+ring softmax
    merge. The price is a (S-1)/S pipeline bubble on this one chunk; decode
    (the throughput phase) microbatches as usual. Fresh K/V rows all-gather
    over sp inside each layer so every sp peer's stage pool replica stays
    identical (model._layer sp_axis).

    Returns (logits[V] at last_idx, updated kv)."""
    from dynamo_tpu.ops.ring_attention import _ring_attention_local

    c = model.config
    S = mesh.shape[pp_axis]
    sp = mesh.shape[sp_axis]
    T = tokens.shape[0]
    assert c.num_layers % S == 0, f"L={c.num_layers} not divisible by pp={S}"
    assert T % sp == 0, f"chunk {T} not divisible by sp={sp}"

    k_pool, v_pool = kv_cache["k"], kv_cache["v"]
    page_size = k_pool.shape[1]
    num_pages = k_pool.shape[0] // c.num_layers
    phys = jnp.where(valid, page_table[positions // page_size], 0)
    offsets = jnp.where(valid, positions % page_size, 0)

    hidden = params["embed"][tokens].astype(c.dtype)

    folded = getattr(model.config, "kv_folded", False)
    spec_pool = kv_pool_spec(mesh, pp_axis, folded)
    tp_axis = "tp" if "tp" in mesh.axis_names else None
    layer_specs = stage_layer_specs(model, mesh, pp_axis)
    seq = P(sp_axis)  # token-dim sharding over the ring
    seq2 = P(sp_axis, None)  # [T, D] hidden
    seq3 = P(None, sp_axis, None)  # [M=1, Tloc, D] rotation outputs

    @partial(
        _tp_shard_map,
        mesh=mesh,
        in_specs=(layer_specs, spec_pool, spec_pool, seq2, seq, seq, seq),
        out_specs=(seq3, spec_pool, spec_pool),
    )
    def run(local_layers, kp, vp, hidden_loc, pos_loc, phys_loc, off_loc):
        def run_mb(mc, active, x, kp, vp):
            # idle ramp steps write to the layer trash page (logical 0)
            phys_mb = jnp.where(active, phys_loc, 0)
            off_mb = jnp.where(active, off_loc, 0)

            def attn_maker(off):
                def attn_fn(q, k_new, v_new, kp_, vp_):
                    # ring over the sp axis on the chunk's fresh rows; the
                    # pool is write-only on this path
                    return _ring_attention_local(q, k_new, v_new, axis_name=sp_axis)

                return attn_fn

            return _local_layer_scan(
                model, local_layers, kp, vp, x, pos_loc, phys_mb, off_mb,
                attn_maker, num_pages, tp_axis=tp_axis, sp_axis=sp_axis,
            )

        return _gpipe_rotate(mesh, pp_axis, S, 1, run_mb, hidden_loc[None], kp, vp)

    outputs, k_pool, v_pool = run(
        params["layers"], k_pool, v_pool, hidden, positions, phys, offsets
    )
    hidden_out = outputs[0]  # [T, D] (sp-sharded on T under GSPMD outside)
    logits = model._unembed(params, hidden_out[last_idx][None, :])[0]
    return logits, {"k": k_pool, "v": v_pool}


def decode_pipelined(
    model,
    params: dict,
    kv_cache: dict,
    tokens: jnp.ndarray,  # [B]
    positions: jnp.ndarray,  # [B]
    page_tables: jnp.ndarray,  # [B, max_pages]
    active: jnp.ndarray,  # [B]
    mesh: Mesh,
    pp_axis: str = "pp",
    num_microbatches: int | None = None,
    rope_deltas: jnp.ndarray | None = None,  # [B] M-RoPE offsets
) -> tuple[jnp.ndarray, dict]:
    """Pipelined batched decode step: batch slots split into microbatches.
    Returns (logits [B, V], kv)."""
    c = model.config
    S = mesh.shape[pp_axis]
    M = num_microbatches or S
    B = tokens.shape[0]
    assert c.num_layers % S == 0
    assert B % M == 0, f"batch {B} not divisible by microbatches {M}"
    Bm = B // M

    k_pool, v_pool = kv_cache["k"], kv_cache["v"]
    page_size = k_pool.shape[1]
    num_pages = k_pool.shape[0] // c.num_layers
    logical = positions // page_size
    phys = jnp.where(active, page_tables[jnp.arange(B), logical], 0)
    offsets = jnp.where(active, positions % page_size, 0)

    hidden = params["embed"][tokens].astype(c.dtype)
    hidden_mbs = hidden.reshape(M, Bm, -1)
    pos_mbs = positions.reshape(M, Bm)
    phys_mbs = phys.reshape(M, Bm)
    off_mbs = offsets.reshape(M, Bm)
    pt_mbs = page_tables.reshape(M, Bm, -1)
    act_mbs = active.reshape(M, Bm)
    rp = positions + (rope_deltas if rope_deltas is not None else 0)
    rp_mbs = jnp.stack([rp] * 3, axis=-1).reshape(M, Bm, 3)

    folded = getattr(model.config, "kv_folded", False)
    spec_pool = kv_pool_spec(mesh, pp_axis, folded)
    tp_axis = "tp" if "tp" in mesh.axis_names else None
    layer_specs = stage_layer_specs(model, mesh, pp_axis)
    rep = P()

    @partial(
        _tp_shard_map,
        mesh=mesh,
        in_specs=(layer_specs, spec_pool, spec_pool) + (rep,) * 7,
        out_specs=(rep, spec_pool, spec_pool),
    )
    def run(local_layers, kp, vp, hidden_mbs, pos_mbs, phys_mbs, off_mbs, pt_mbs, act_mbs, rp_mbs):
        def run_mb(mc, pipe_active, x, kp, vp):
            pos = pos_mbs[mc]
            row_active = act_mbs[mc] & pipe_active
            phys_mb = jnp.where(row_active, phys_mbs[mc], 0)
            off_mb = jnp.where(row_active, off_mbs[mc], 0)
            pts = pt_mbs[mc]

            def attn_maker(off):
                def attn_fn(q, k_new, v_new, kp_, vp_):
                    return paged_decode_attention(q, kp_, vp_, off + pts, pos)

                return attn_fn

            return _local_layer_scan(
                model, local_layers, kp, vp, x, pos, phys_mb, off_mb, attn_maker, num_pages,
                rope_positions=rp_mbs[mc], tp_axis=tp_axis,
            )

        return _gpipe_rotate(mesh, pp_axis, S, M, run_mb, hidden_mbs, kp, vp)

    outputs, k_pool, v_pool = run(
        params["layers"], k_pool, v_pool, hidden_mbs, pos_mbs, phys_mbs, off_mbs, pt_mbs, act_mbs, rp_mbs
    )
    hidden_out = outputs.reshape(B, -1)
    logits = model._unembed(params, hidden_out)
    return logits, {"k": k_pool, "v": v_pool}
