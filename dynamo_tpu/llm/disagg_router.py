"""Disaggregation decision: local vs remote prefill.

Mirrors the reference DisaggregatedRouter (reference: lib/llm/src/
disagg_router.rs:38-259): prefill goes remote iff

    prefill_length - prefix_hit_length > max_local_prefill_length

and (queue not too deep). The threshold is live-reloadable via a control-plane
watch at ``disagg_router/models/chat/{model}`` (reference threshold key:
public/components/disagg_router/models/chat/<model>).
"""

from __future__ import annotations

import asyncio
import json
from dataclasses import dataclass
from typing import Optional

from dynamo_tpu.utils import get_logger

log = get_logger("disagg_router")


def config_key(model: str) -> str:
    return f"disagg_router/models/chat/{model}"


@dataclass
class DisaggRouterConf:
    max_local_prefill_length: int = 512
    max_prefill_queue_size: int = 64

    @classmethod
    def from_wire(cls, raw: bytes) -> "DisaggRouterConf":
        d = json.loads(raw)
        return cls(
            max_local_prefill_length=int(d.get("max_local_prefill_length", 512)),
            max_prefill_queue_size=int(d.get("max_prefill_queue_size", 64)),
        )


class DisaggregatedRouter:
    def __init__(
        self,
        model: str,
        conf: Optional[DisaggRouterConf] = None,
        cplane=None,
    ):
        self.model = model
        self.conf = conf or DisaggRouterConf()
        self._cplane = cplane
        self._watcher = None
        self._watch_task: Optional[asyncio.Task] = None

    async def start_watching(self) -> "DisaggregatedRouter":
        """Live-reload the threshold from the control plane
        (reference: disagg_router.rs from_etcd_with_watcher)."""
        if self._cplane is None:
            return self
        key = config_key(self.model)
        raw = await self._cplane.kv_get(key)
        if raw:
            self.conf = DisaggRouterConf.from_wire(raw)
        self._watcher = await self._cplane.kv_get_and_watch_prefix(key)
        self._watch_task = asyncio.create_task(self._watch_loop())
        return self

    async def stop(self) -> None:
        if self._watch_task:
            self._watch_task.cancel()
        if self._watcher:
            try:
                await self._watcher.stop()
            except Exception:
                pass

    async def _watch_loop(self) -> None:
        try:
            async for ev in self._watcher.events():
                if ev.kind == "put" and ev.value:
                    try:
                        self.conf = DisaggRouterConf.from_wire(ev.value)
                        log.info(
                            "disagg threshold reloaded: local<=%d queue<=%d",
                            self.conf.max_local_prefill_length,
                            self.conf.max_prefill_queue_size,
                        )
                    except Exception:
                        log.exception("bad disagg config")
        except asyncio.CancelledError:
            pass

    def prefill_remote(
        self, prefill_length: int, prefix_hit_length: int, queue_depth: int = 0
    ) -> bool:
        """reference: disagg_router.rs:239-249 prefill_remote."""
        if queue_depth >= self.conf.max_prefill_queue_size:
            return False
        return prefill_length - prefix_hit_length > self.conf.max_local_prefill_length
