"""KV cache events: engine -> router state channel.

Mirrors the reference protocol (reference: lib/llm/src/kv_router/protocols.rs:35-100):
``KvCacheEvent::Stored{parent_hash, blocks[{block_hash, tokens_hash}]}`` and
``KvCacheEvent::Removed{block_hashes}``. ``tokens_hash`` is the *unchained*
local chunk hash used for radix matching; ``block_hash`` is the engine's block
identity (we use the chained sequence hash).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Optional

_event_counter = itertools.count()


@dataclass(frozen=True)
class StoredBlock:
    block_hash: int
    tokens_hash: int
    parent_hash: Optional[int] = None


@dataclass(frozen=True)
class KvCacheEvent:
    event_id: int
    kind: str  # "stored" | "removed"
    parent_hash: Optional[int] = None
    blocks: tuple[StoredBlock, ...] = ()
    block_hashes: tuple[int, ...] = ()

    @classmethod
    def stored(cls, parent_hash: Optional[int], blocks: list[StoredBlock]) -> "KvCacheEvent":
        return cls(
            event_id=next(_event_counter),
            kind="stored",
            parent_hash=parent_hash,
            blocks=tuple(blocks),
        )

    @classmethod
    def removed(cls, block_hashes: list[int]) -> "KvCacheEvent":
        return cls(
            event_id=next(_event_counter),
            kind="removed",
            block_hashes=tuple(block_hashes),
        )

    def to_wire(self) -> dict:
        if self.kind == "stored":
            return {
                "event_id": self.event_id,
                "stored": {
                    "parent_hash": self.parent_hash,
                    "blocks": [
                        {
                            "block_hash": b.block_hash,
                            "tokens_hash": b.tokens_hash,
                        }
                        for b in self.blocks
                    ],
                },
            }
        return {"event_id": self.event_id, "removed": {"block_hashes": list(self.block_hashes)}}

    @classmethod
    def from_wire(cls, d: dict) -> "KvCacheEvent":
        if "stored" in d:
            s = d["stored"]
            return cls(
                event_id=d["event_id"],
                kind="stored",
                parent_hash=s.get("parent_hash"),
                blocks=tuple(
                    StoredBlock(block_hash=b["block_hash"], tokens_hash=b["tokens_hash"])
                    for b in s["blocks"]
                ),
            )
        return cls(
            event_id=d["event_id"],
            kind="removed",
            block_hashes=tuple(d["removed"]["block_hashes"]),
        )
