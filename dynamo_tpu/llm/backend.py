"""Detokenizing backend: engine token stream -> text deltas with stop-condition
jailing and finish reasons.

Mirrors the reference Backend (reference: lib/llm/src/backend.rs:66-508):
wraps a tokens-in/tokens-out engine, performs incremental detokenization via a
DecodeStream, holds back ("jails") text that could be the start of a stop
sequence, and truncates at the stop match.
"""

from __future__ import annotations

from typing import AsyncIterator, Optional

from dynamo_tpu.engine.scheduler import EngineRequest
from dynamo_tpu.llm.protocols.common import BackendOutput, PreprocessedRequest
from dynamo_tpu.llm.tokenizer import DecodeStream, Tokenizer
from dynamo_tpu.utils import get_logger

log = get_logger("llm.backend")


class _StopJail:
    """Holds back text while it could still be completing a stop string."""

    def __init__(self, stops: tuple[str, ...]):
        self.stops = [s for s in stops if s]
        self.pending = ""

    def push(self, text: str) -> tuple[str, bool]:
        """Returns (emit_now, stopped)."""
        if not self.stops:
            return text, False
        self.pending += text
        # full stop match: emit everything before it and signal stop
        best = None
        for s in self.stops:
            idx = self.pending.find(s)
            if idx != -1 and (best is None or idx < best[0]):
                best = (idx, s)
        if best is not None:
            return self.pending[: best[0]], True
        # hold back the longest tail that is a proper prefix of any stop string
        hold = 0
        for s in self.stops:
            for k in range(min(len(s) - 1, len(self.pending)), 0, -1):
                if self.pending.endswith(s[:k]):
                    hold = max(hold, k)
                    break
        emit = self.pending[: len(self.pending) - hold] if hold else self.pending
        self.pending = self.pending[len(emit) :]
        return emit, False

    def flush(self) -> str:
        out, self.pending = self.pending, ""
        return out


class Backend:
    """ExecutionContext wrapper: PreprocessedRequest -> BackendOutput stream."""

    def __init__(self, engine, tokenizer: Tokenizer):
        self.engine = engine
        self.tokenizer = tokenizer

    def availability(self) -> dict:
        """Pre-admission serving probe the HTTP layer consults BEFORE any
        response bytes (so a stream=true request still gets a plain JSON
        status). A draining engine that cannot migrate its load is a
        *retriable* condition — the client should back off and retry once
        the drain completes or a replacement registers — not a hard error.
        With migration enabled the engine keeps serving through its drain
        (in-flight sequences move to peers; the router stops sending new
        work), so no 503 is needed."""
        health = getattr(self.engine, "health", None)
        cfg = getattr(self.engine, "config", None)
        if health is None or cfg is None:
            return {"servable": True}
        state = getattr(health, "state", "ready")
        if state in ("draining", "migrating") and not getattr(cfg, "migration", True):
            # Retry-After from the MEASURED queue drain rate when the engine
            # exposes it (utils/qos.DrainRateEstimator, clamped [1, 30] s) —
            # the same estimator the QoS 429 path prices from; engines
            # without one keep the old constant
            retry_after = 10
            bp_fn = getattr(self.engine, "backpressure_snapshot", None)
            if bp_fn is not None:
                try:
                    retry_after = bp_fn().get("retry_after_s", retry_after)
                except Exception:
                    pass
            return {
                "servable": False,
                "retriable": True,
                "reason": f"engine is {state} and live migration is disabled",
                "retry_after_s": retry_after,
            }
        return {"servable": True, "state": state}

    def backpressure(self) -> Optional[dict]:
        """Engine queue pressure for the frontend's QoS shed check: queue
        depth x measured drain rate -> estimated wait for a NEW request
        (utils/qos.py). None when the engine has no backpressure surface
        (remote/external engines)."""
        bp_fn = getattr(self.engine, "backpressure_snapshot", None)
        if bp_fn is None:
            return None
        try:
            return bp_fn()
        except Exception:
            return None

    def _token_repr(self, token_id: int) -> tuple[str, list[int]]:
        text = self.tokenizer.decode([token_id], skip_special_tokens=False)
        return text, list(text.encode("utf-8"))

    def _logprob_entry(self, step) -> dict:
        """StepOutput logprobs -> OpenAI-shaped entry (token strings decoded
        here, next to the tokenizer)."""
        tok_str, tok_bytes = self._token_repr(step.token)
        entry = {"token": tok_str, "logprob": step.logprob, "bytes": tok_bytes}
        if step.top_logprobs is not None:
            top = []
            for tid, lp in step.top_logprobs:
                t_str, t_bytes = self._token_repr(tid)
                top.append({"token": t_str, "logprob": lp, "bytes": t_bytes})
            entry["top"] = top
        return entry

    async def generate(self, request: PreprocessedRequest) -> AsyncIterator[BackendOutput]:
        eos_ids = tuple(request.eos_token_ids) or tuple(self.tokenizer.eos_token_ids)
        engine_req = EngineRequest(
            request_id=request.request_id,
            token_ids=list(request.token_ids),
            sampling=request.sampling,
            eos_token_ids=eos_ids,
            images=list(request.images),
            logprobs=request.logprobs,
            kv_holder_addr=getattr(request, "kv_holder_addr", ""),
            kv_holder_blocks=getattr(request, "kv_holder_blocks", 0),
            lora_name=getattr(request, "lora_name", ""),
            tenant=getattr(request, "tenant", ""),
            scenario=getattr(request, "scenario", ""),
            priority=getattr(request, "priority", ""),
        )
        decoder = DecodeStream(
            self.tokenizer,
            prompt_ids=request.token_ids,
            skip_special_tokens=getattr(request, "skip_special_tokens", True),
        )
        jail = _StopJail(request.stop_strings)
        count = 0
        cached = 0
        # engine windows arrive as StepOutput batches (decode_steps tokens per
        # thread crossing — and a speculative engine emits whole accepted
        # chunks); one detok + one BackendOutput per batch collapses the
        # per-token overhead that halved HTTP-level throughput. Engines
        # without a batched API (echo, remote proxies) stream singletons.
        # Stop strings still ride the batched stream, but scan per token
        # WITHIN each chunk (see below): a stop can complete on any token of
        # a multi-token window, and token_ids/usage/logprobs must end AT the
        # matching token, not at the window boundary.
        if hasattr(self.engine, "generate_batched"):
            stream = self.engine.generate_batched(engine_req)
        else:
            async def _singletons():
                async for s in self.engine.generate(engine_req):
                    yield [s]

            stream = _singletons()
        if jail.stops:
            async for out in self._generate_with_stops(
                request, stream, decoder, jail, eos_ids
            ):
                yield out
            return
        async for steps in stream:
            ids: list[int] = []
            detok_ids: list[int] = []
            lp_entries = None
            finished = False
            finish_reason = None
            for step in steps:
                if step.token is not None:
                    count += 1
                    ids.append(step.token)
                    # suppress eos token text
                    if not (step.finish_reason == "stop" and step.token in eos_ids):
                        detok_ids.append(step.token)
                    if step.logprob is not None:
                        if lp_entries is None:
                            lp_entries = []
                        lp_entries.append(self._logprob_entry(step))
                cached = max(cached, step.cached_tokens)
                if step.finished:
                    finished = True
                    finish_reason = step.finish_reason
                    break
            text = (decoder.step_many(detok_ids) or "") if detok_ids else ""

            emit, stopped = jail.push(text) if text else ("", False)
            if stopped:
                yield BackendOutput(
                    request_id=request.request_id,
                    text=emit,
                    token_ids=ids,
                    finish_reason="stop",
                    cumulative_tokens=count,
                    cached_tokens=cached,
                    logprobs=lp_entries,
                )
                return
            if finished:
                # flush only if no stop strings were configured mid-jail; a
                # partial stop prefix at end-of-stream is emitted (it never
                # completed the stop sequence)
                emit += jail.flush()
                yield BackendOutput(
                    request_id=request.request_id,
                    text=emit,
                    token_ids=ids,
                    finish_reason=finish_reason,
                    cumulative_tokens=count,
                    cached_tokens=cached,
                    logprobs=lp_entries,
                )
                return
            if emit or ids:
                yield BackendOutput(
                    request_id=request.request_id,
                    text=emit,
                    token_ids=ids,
                    cumulative_tokens=count,
                    cached_tokens=cached,
                    logprobs=lp_entries,
                )

    async def _generate_with_stops(
        self, request, stream, decoder: DecodeStream, jail: _StopJail, eos_ids
    ) -> AsyncIterator[BackendOutput]:
        """Stop-string stream: one BackendOutput per engine window, but detok
        + jail scanning walk token by token WITHIN each multi-token chunk, so
        a stop sequence completing mid-chunk truncates text, token_ids, and
        usage at exactly the matching token (never just the newest one)."""
        count = 0
        cached = 0
        async for steps in stream:
            ids: list[int] = []
            parts: list[str] = []
            lp_entries = None
            finished = False
            finish_reason = None
            stopped = False
            for step in steps:
                if step.token is not None:
                    count += 1
                    ids.append(step.token)
                    if step.logprob is not None:
                        if lp_entries is None:
                            lp_entries = []
                        lp_entries.append(self._logprob_entry(step))
                    # suppress eos token text
                    if step.finish_reason == "stop" and step.token in eos_ids:
                        piece = None
                    else:
                        piece = decoder.step(step.token)
                    if piece:
                        emit, stopped = jail.push(piece)
                        if emit:
                            parts.append(emit)
                        if stopped:
                            break
                cached = max(cached, step.cached_tokens)
                if step.finished:
                    finished = True
                    finish_reason = step.finish_reason
                    break
            if stopped or finished:
                if finished and not stopped:
                    # a partial stop prefix at end-of-stream never completed
                    # the stop sequence: emit it
                    parts.append(jail.flush())
                yield BackendOutput(
                    request_id=request.request_id,
                    text="".join(parts),
                    token_ids=ids,
                    finish_reason="stop" if stopped else finish_reason,
                    cumulative_tokens=count,
                    cached_tokens=cached,
                    logprobs=lp_entries,
                )
                return
            if parts or ids:
                yield BackendOutput(
                    request_id=request.request_id,
                    text="".join(parts),
                    token_ids=ids,
                    cumulative_tokens=count,
                    cached_tokens=cached,
                    logprobs=lp_entries,
                )
