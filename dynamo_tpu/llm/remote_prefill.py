"""Remote-prefill protocol: decode worker <-> prefill worker.

Mirrors the reference protocol (reference: patch remote_prefill.py
RemotePrefillRequest{request_id, prompt_token_ids, sampling_params, block_ids,
engine_id} + completion notification). The KV payload itself travels over the
TCP call-home data plane to the decode worker's ``prefill_result`` endpoint —
the ICI/DCN replacement for NIXL RDMA WRITE + notification.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class RemotePrefillRequest:
    request_id: str
    token_ids: list[int]
    # sampling for the single first token the prefill worker produces
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    # where the result must land
    decode_worker_id: int = 0
    decode_endpoint: str = ""  # dyn://ns.comp.endpoint of the decode worker's prefill_result
    # pages allocated on the decode side that must receive KV (logical order),
    # excluding any shared prefix pages the decode side already has
    skip_leading_tokens: int = 0
    # decode worker's dedicated KV data-plane listener (host:port). When set
    # and the prefill worker is NOT in the same process, the bulk KV payload
    # rides this socket (disagg/dataplane.py) instead of the control-plane
    # result message — the NIXL RDMA-WRITE analogue. Empty = legacy inline.
    kv_addr: str = ""
    # per-request data-plane nonce minted by the decode side's expect(): the
    # KV server only accepts a payload carrying it, so a network peer that
    # merely learns a request_id cannot inject KV into the decode cache
    kv_token: str = ""
    # observability: the edge-stamped trace id. The work queue bypasses the
    # RPC envelope's context propagation, so the id rides this message and the
    # prefill worker re-enters the request context from it — stitching both
    # workers' spans (and logs) of one request onto one timeline
    trace_id: str = ""
    # fleet prefix cache: the router-attached remote holder for this prompt.
    # The PREFILL worker pulls the matching leading blocks from the holder
    # before recomputing (same timeout -> recompute fallback as the decode
    # side's FETCHING_KV path); empty = recompute as always.
    kv_holder_addr: str = ""
    kv_holder_blocks: int = 0

    def to_wire(self) -> dict:
        return self.__dict__.copy()

    @classmethod
    def from_wire(cls, d: dict) -> "RemotePrefillRequest":
        return cls(**{k: v for k, v in d.items() if k in cls.__dataclass_fields__})


@dataclass
class PrefillResult:
    request_id: str
    first_token: int
    prompt_len: int
    skip_leading_tokens: int
    kv_shape: tuple  # [L, 2, n_pages, page_size, Hkv, D]
    kv_dtype: str
    kv_bytes: bytes
    # same-pod (ICI) handoff: when set, the KV payload is a device array parked
    # in dynamo_tpu.disagg.ici under this id and kv_bytes stays empty — the
    # decode side reshards it onto its mesh instead of deserializing bytes
    kv_transfer_id: str = ""
    # how the KV payload travels: "inline" (kv_bytes in this message — legacy
    # / tiny transfers), "ici" (device-array hub, same process), or "socket"
    # (dedicated data-plane TCP stream; this message is the completion
    # notification for a payload arriving on the decode worker's kv_addr)
    kv_mode: str = "inline"
    # streamed socket transfers: how many v2 parts the payload was split into
    # (dataplane.py stream_part_plan); 0 = monolithic (kv_shape describes the
    # single payload). With parts > 0 the decode side scatters each part as
    # it lands and the final adopt only waits on the tail part.
    kv_parts: int = 0
    # int8 KV caches on the legacy inline path: kv_bytes holds the int8 page
    # data (half the bf16 bytes) and the per-page f32 scale plane travels in
    # these fields; kv_array() then reconstructs the {"q","s"} wire dict
    # (quant/kv.py). Empty on full-precision transfers.
    kv_scales_bytes: bytes = b""
    kv_scales_shape: tuple = ()
    kv_scales_dtype: str = ""

    def to_wire(self) -> dict:
        return {
            "request_id": self.request_id,
            "first_token": self.first_token,
            "prompt_len": self.prompt_len,
            "skip_leading_tokens": self.skip_leading_tokens,
            "kv_shape": list(self.kv_shape),
            "kv_dtype": self.kv_dtype,
            "kv_bytes": self.kv_bytes,
            "kv_transfer_id": self.kv_transfer_id,
            "kv_mode": self.kv_mode,
            "kv_parts": self.kv_parts,
            "kv_scales_bytes": self.kv_scales_bytes,
            "kv_scales_shape": list(self.kv_scales_shape),
            "kv_scales_dtype": self.kv_scales_dtype,
        }

    @classmethod
    def from_wire(cls, d: dict) -> "PrefillResult":
        return cls(
            request_id=d["request_id"],
            first_token=d["first_token"],
            prompt_len=d["prompt_len"],
            skip_leading_tokens=d["skip_leading_tokens"],
            kv_shape=tuple(d["kv_shape"]),
            kv_dtype=d["kv_dtype"],
            kv_bytes=d["kv_bytes"],
            kv_transfer_id=d.get("kv_transfer_id", ""),
            kv_mode=d.get("kv_mode", "ici" if d.get("kv_transfer_id") else "inline"),
            kv_parts=int(d.get("kv_parts", 0)),
            kv_scales_bytes=d.get("kv_scales_bytes", b""),
            kv_scales_shape=tuple(d.get("kv_scales_shape", ())),
            kv_scales_dtype=d.get("kv_scales_dtype", ""),
        )

    def kv_array(self):
        data = np.frombuffer(self.kv_bytes, dtype=_np_dtype(self.kv_dtype)).reshape(self.kv_shape)
        if self.kv_scales_bytes:
            scales = np.frombuffer(
                self.kv_scales_bytes, dtype=_np_dtype(self.kv_scales_dtype)
            ).reshape(self.kv_scales_shape)
            return {"q": data, "s": scales}
        return data


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # bfloat16 et al (jax dependency)

        return np.dtype(getattr(ml_dtypes, name))


def prefill_queue_name(namespace: str, model: str) -> str:
    """reference: examples/llm/utils/prefill_queue.py queue naming."""
    return f"{namespace}.prefill_queue.{model}"
