"""OpenAI-compatible HTTP service (aiohttp).

Mirrors the reference HTTP service (reference: lib/llm/src/http/service/
service_v2.rs:24-90, openai.rs:132,214, service.rs:58 ModelManager): models
attach/detach dynamically; requests always stream internally and are
aggregated for ``stream=false``; SSE framing with a final ``data: [DONE]``.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import AsyncIterator, Callable, Optional

from aiohttp import web

from dynamo_tpu.runtime.context import new_context, use_context
from dynamo_tpu.llm.protocols.aggregator import (
    aggregate_chat_stream,
    aggregate_completion_stream,
)
from dynamo_tpu.llm.protocols.openai import (
    ChatCompletionRequest,
    ChatDeltaGenerator,
    CompletionDeltaGenerator,
    CompletionRequest,
    ProtocolError,
    Usage,
)
from dynamo_tpu.llm.http.metrics import Metrics
from dynamo_tpu.utils.goodput import MAX_ITL_SAMPLES
from dynamo_tpu.llm.protocols import sse
from dynamo_tpu.llm.tools import ToolCallError, ToolCallingMatcher
from dynamo_tpu.utils import events, get_logger, tracing

log = get_logger("http")


class ModelPipeline:
    """Everything needed to serve one model: preprocessor + backend."""

    def __init__(self, name: str, preprocessor, backend, model_type: str = "chat"):
        self.name = name
        self.preprocessor = preprocessor
        self.backend = backend
        self.model_type = model_type  # chat | completion | both

    @property
    def serves_chat(self) -> bool:
        return self.model_type in ("chat", "both")

    @property
    def serves_completion(self) -> bool:
        return self.model_type in ("completion", "both")


class ModelManager:
    def __init__(self):
        self._models: dict[str, ModelPipeline] = {}

    def add(self, pipeline: ModelPipeline) -> None:
        self._models[pipeline.name] = pipeline

    def remove(self, name: str) -> Optional[ModelPipeline]:
        return self._models.pop(name, None)

    def get(self, name: Optional[str]) -> Optional[ModelPipeline]:
        if name in self._models:
            return self._models[name]
        if name is None and len(self._models) == 1:
            return next(iter(self._models.values()))
        return None

    def list_models(self) -> list[str]:
        return sorted(self._models)


class HttpService:
    def __init__(
        self,
        manager: Optional[ModelManager] = None,
        host: str = "0.0.0.0",
        port: int = 8080,
        extra_metrics: Optional[Callable[[], str]] = None,
        slo=None,  # Optional[SloTracker]: rolling TTFT/ITL SLO state
        readiness: Optional[Callable[[], tuple]] = None,
        step_source: Optional[Callable[..., dict]] = None,
        qos=None,  # Optional[AdmissionController]: multi-tenant QoS plane
        cost_source: Optional[Callable[[str], Optional[dict]]] = None,
    ):
        self.manager = manager or ModelManager()
        self.host = host
        self.port = port
        self.metrics = Metrics()
        # SLO tracker (utils/slo.py): fed TTFT/ITL alongside the histograms,
        # rendered into /metrics, and surfaced on /ready. Default tracker has
        # targets from the DYNTPU_SLO_*_MS env knobs (untargeted metrics
        # still report percentiles).
        if slo is None:
            from dynamo_tpu.utils.slo import SloTracker, targets_from_env

            slo = SloTracker(targets_from_env())
        self.slo = slo
        # goodput plane (utils/goodput.py): one RequestOutcome per served
        # request — TTFT + the per-chunk ITL series + tenant/adapter tags —
        # rendered as dynamo_goodput_* on /metrics. Budgets default to the
        # SLO targets; untargeted frontends still count errors.
        from dynamo_tpu.utils.goodput import GoodputTracker

        self.goodput = GoodputTracker(
            ttft_budget_s=self.slo.targets.get("ttft"),
            itl_budget_s=self.slo.targets.get("itl"),
        )
        # multi-tenant QoS plane (utils/qos.py): priority classes from the
        # x-priority header or per-tenant/adapter policy, per-tenant token
        # budgets answering retriable 429 + Retry-After BEFORE any SSE
        # bytes, and an engine-backpressure check that sheds batch-class
        # load first. Default controller comes from the DYNTPU_QOS_BUDGETS /
        # DYNTPU_QOS_PRIORITIES env specs; with neither set it carries no
        # budgets (nothing throttles) but still classifies and counts.
        if qos is None:
            from dynamo_tpu.utils.qos import AdmissionController, QosPolicy

            qos = AdmissionController(QosPolicy.from_env())
        self.qos = qos
        # readiness provider: () -> (ok: bool, detail: dict). None = always
        # ready (a bare service with no downstream dependency to gate on).
        # FrontendService wires downstream-worker liveness through this; the
        # colocated engine frontend wires the engine's HealthMonitor.
        self._readiness = readiness
        self._extra_metrics = extra_metrics
        # step-anatomy source for a colocated engine: (limit=, kind=) ->
        # {"records": [...], "summary": {...}} (AsyncJaxEngine.debug_steps)
        self._step_source = step_source
        # cost-footer source for a colocated engine: (request_id) -> the
        # MeterLedger footer (device-ms by dispatch kind + peak KV bytes per
        # tier) or None (AsyncJaxEngine.request_cost). Merged into
        # /debug/requests/{id} under a "cost" key.
        self._cost_source = cost_source
        self._runner: Optional[web.AppRunner] = None
        self.app = web.Application()
        self.app.router.add_post("/v1/chat/completions", self._chat)
        self.app.router.add_post("/v1/completions", self._completions)
        self.app.router.add_get("/v1/models", self._models)
        self.app.router.add_get("/metrics", self._metrics)
        self.app.router.add_get("/trace", self._trace)
        self.app.router.add_get("/debug/steps", self._debug_steps)
        self.app.router.add_get("/debug/requests/{rid}", self._debug_request)
        self.app.router.add_get("/health", self._health)
        # probe split: /live answers "is this process running" and must never
        # block on (or 503 because of) the model manager or any downstream;
        # /ready answers "should a load balancer send traffic here"
        self.app.router.add_get("/live", self._live)
        self.app.router.add_get("/ready", self._ready)

    # ---------------- lifecycle ----------------

    async def start(self) -> int:
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        log.info("http service listening on %s:%d", self.host, self.port)
        return self.port

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()

    async def run_forever(self) -> None:
        await self.start()
        while True:
            await asyncio.sleep(3600)

    # ---------------- handlers ----------------

    async def _health(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "ok", "models": self.manager.list_models()})

    async def _live(self, request: web.Request) -> web.Response:
        # static by design: liveness must stay 200 while readiness flaps
        return web.json_response({"status": "live"})

    def set_readiness(self, provider: Callable[[], tuple]) -> None:
        self._readiness = provider

    async def _ready(self, request: web.Request) -> web.Response:
        ok, detail = True, {}
        if self._readiness is not None:
            try:
                result = self._readiness()
                if asyncio.iscoroutine(result):
                    result = await result
                ok, detail = result
            except Exception as e:
                ok, detail = False, {"error": str(e)}
        slo = self.slo.snapshot()
        body = {
            "status": "ready" if ok else "unready",
            "models": self.manager.list_models(),
            # informational: an exhausted error budget degrades, it does not
            # pull the pod out of rotation (that would shed the very traffic
            # the SLO exists for)
            "slo_ok": slo["ok"],
            # how many frontend replicas this door's admission buckets are
            # split across (1 = it holds the whole fleet budget itself)
            "qos_fleet_replicas": max(1, int(self.qos.policy.fleet_replicas)),
            **detail,
        }
        return web.json_response(body, status=200 if ok else 503)

    async def _models(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "object": "list",
                "data": [
                    {"id": name, "object": "model", "owned_by": "dynamo-tpu"}
                    for name in self.manager.list_models()
                ],
            }
        )

    async def _metrics(self, request: web.Request) -> web.Response:
        extra = (self.slo.render_metrics() + self.slo.render_burn_metrics()
                 + self.goodput.render_metrics() + self.qos.render_metrics()
                 + events.JOURNAL.render_metrics())
        if self._extra_metrics:
            extra += self._extra_metrics()
        return web.Response(text=self.metrics.render(extra), content_type="text/plain")

    async def _trace(self, request: web.Request) -> web.Response:
        """Debug endpoint: the in-memory span ring as a Perfetto-loadable
        Chrome-trace document. ``?trace_id=`` / ``?request_id=`` filter to one
        request's stitched timeline; empty unless tracing is enabled
        (DYNTPU_TRACE=<path> or tracing.enable())."""
        doc = tracing.export()
        tid = request.query.get("trace_id")
        rid = request.query.get("request_id")
        if tid or rid:
            doc["traceEvents"] = tracing.events(trace_id=tid, request_id=rid)
        return web.json_response(doc)

    async def _debug_steps(self, request: web.Request) -> web.Response:
        """Debug endpoint: the colocated engine's recent step-anatomy records
        (utils/step_anatomy.py) — per-dispatch host-prep/dispatch/device-wait/
        reconcile milliseconds plus the host/roofline summary fractions.
        ``?limit=`` caps the record count, ``?kind=`` filters to one dispatch
        kind (decode_window, prefill_packed, ...). Frontends with no engine
        attached answer with an empty record list."""
        if self._step_source is None:
            return web.json_response({"records": [], "summary": {}})
        try:
            limit = int(request.query.get("limit", 128))
        except ValueError:
            limit = 128
        kind = request.query.get("kind") or None
        return web.json_response(self._step_source(limit=limit, kind=kind))

    async def _debug_request(self, request: web.Request) -> web.Response:
        """Per-request forensics: the flight recorder's causally ordered
        event chain for one request id, with inter-event durations
        (``dt_ms``) and the pin verdict. Served from the live journal merged
        with the capture ring, so over-budget/erroring requests stay
        reconstructable after ring eviction (utils/events.py). A colocated
        engine with metering on appends the request's cost footer
        (utils/metering.py): device-ms by dispatch kind + peak resident KV
        bytes per tier — what this request COST, alongside what happened."""
        rid = request.match_info["rid"]
        doc = events.JOURNAL.timeline(rid)
        if self._cost_source is not None:
            try:
                cost = self._cost_source(rid)
            except Exception:
                cost = None
            if cost is not None:
                doc["cost"] = cost
        return web.json_response(doc)

    def _error(
        self, status: int, message: str, code: str | None = None,
        headers: dict | None = None,
    ) -> web.Response:
        err = {"message": message, "type": "invalid_request_error"}
        if code:
            err["code"] = code  # e.g. context_length_exceeded
        return web.json_response({"error": err}, status=status, headers=headers)

    async def _chat(self, request: web.Request) -> web.StreamResponse:
        return await self._handle(request, kind="chat")

    async def _completions(self, request: web.Request) -> web.StreamResponse:
        return await self._handle(request, kind="completion")

    async def _handle(self, request: web.Request, kind: str) -> web.StreamResponse:
        endpoint = "chat_completions" if kind == "chat" else "completions"
        t0 = time.monotonic()
        try:
            body = await request.json()
        except Exception:
            self.metrics.inc_request("unknown", endpoint, "unary", "400")
            return self._error(400, "invalid JSON body")
        try:
            req = (
                ChatCompletionRequest.from_dict(body)
                if kind == "chat"
                else CompletionRequest.from_dict(body)
            )
        except ProtocolError as e:
            self.metrics.inc_request(str(body.get("model")), endpoint, "unary", "400")
            return self._error(400, str(e), code=e.code)

        pipeline = self.manager.get(req.model)
        if pipeline is None:
            # structured OpenAI 404 (error.code model_not_found) on BOTH
            # unary and stream paths: the model/adapter check runs before any
            # SSE response starts, so a stream=true request naming an unknown
            # LoRA adapter gets a plain JSON error, never SSE bytes
            self.metrics.inc_request(str(req.model), endpoint, "unary", "404")
            return self._error(
                404, f"model {req.model!r} not found", code="model_not_found"
            )
        if kind == "chat" and not pipeline.serves_chat:
            return self._error(400, f"model {req.model!r} does not serve chat")
        if kind == "completion" and not pipeline.serves_completion:
            return self._error(400, f"model {req.model!r} does not serve completions")

        model = pipeline.name
        rtype = "stream" if req.stream else "unary"

        # pre-admission availability: a draining backend that cannot migrate
        # its load answers a RETRIABLE 503 with Retry-After — on both the
        # unary and stream paths, and always BEFORE any SSE bytes (the check
        # runs ahead of preprocessing and the stream response), so clients
        # and load balancers can re-dispatch instead of surfacing an error
        avail_fn = getattr(pipeline.backend, "availability", None)
        if avail_fn is not None:
            try:
                avail = avail_fn()
                if asyncio.iscoroutine(avail):
                    avail = await avail
            except Exception:
                avail = None
            if avail and not avail.get("servable", True) and avail.get("retriable"):
                self.metrics.inc_request(model, endpoint, rtype, "503")
                retry_after = int(avail.get("retry_after_s", 10))
                return self._error(
                    503,
                    avail.get("reason", f"model {model!r} is draining; retry"),
                    code="model_draining",
                    headers={"Retry-After": str(retry_after)},
                )

        # ---------- multi-tenant QoS admission (utils/qos.py) ----------
        # priority class: explicit x-priority header wins (strict parse — an
        # unknown class is a 400, not a silent downgrade), else the policy's
        # per-tenant/adapter default
        tenant = request.headers.get("x-tenant", "")
        adapter = model.split(":", 1)[1] if ":" in model and "{" not in model else ""
        from dynamo_tpu.utils.qos import parse_priority

        try:
            priority = parse_priority(request.headers.get("x-priority"))
        except ValueError as e:
            self.metrics.inc_request(model, endpoint, rtype, "400")
            return self._error(400, str(e), code="invalid_priority")
        if not request.headers.get("x-priority"):
            priority = self.qos.policy.priority_for(tenant, adapter)

        # seeded admission chaos knob (DYNTPU_FAULT_ADMISSION): deterministic
        # retriable 429s / injected delays so client retry/backoff and the
        # shed path are testable without real overload
        from dynamo_tpu.disagg.faults import admission_plan

        fault = admission_plan()
        if fault is not None:
            delay = fault.delay_s()
            if delay > 0:
                await asyncio.sleep(delay)
            if fault.should_reject():
                # shed happens before the preprocessor stamps a request id:
                # a client-supplied x-request-id keeps the shed chain
                # reconstructable via /debug/requests/{id}
                rid = request.headers.get("x-request-id", "")
                self.qos.record_shed(tenant, priority, request_id=rid)
                if rid:
                    events.JOURNAL.pin(rid, "shed")
                self.metrics.inc_request(model, endpoint, rtype, "429")
                return self._error(
                    429, "admission fault injected (DYNTPU_FAULT_ADMISSION)",
                    code="rate_limited", headers={"Retry-After": "1"},
                )

        # engine backpressure: estimated queue wait (depth x measured drain
        # rate) against the TTFT budget — batch-class load sheds FIRST with
        # a retriable 429, always before any SSE bytes, so interactive
        # classes keep their budgets through an overload
        if priority == "batch":
            bp_fn = getattr(pipeline.backend, "backpressure", None)
            bp = None
            if bp_fn is not None:
                try:
                    bp = bp_fn()
                    if asyncio.iscoroutine(bp):
                        bp = await bp
                except Exception:
                    bp = None
            if bp and bp.get("est_wait_s") is not None:
                budget = self.slo.targets.get("ttft") or self.qos.policy.shed_wait_s
                if bp["est_wait_s"] > budget:
                    rid = request.headers.get("x-request-id", "")
                    self.qos.record_shed(tenant, priority, request_id=rid)
                    if rid:
                        events.JOURNAL.pin(rid, "shed")
                    self.metrics.inc_request(model, endpoint, rtype, "429")
                    return self._error(
                        429,
                        f"engine overloaded (estimated wait "
                        f"{bp['est_wait_s']:.1f}s exceeds the "
                        f"{budget:.1f}s budget); batch-class load shed",
                        code="overloaded",
                        headers={"Retry-After": str(bp.get("retry_after_s", 10))},
                    )
        try:
            # off the event loop: chat-template render + BPE encode are
            # CPU-bound (the tokenizer's Rust encode releases the GIL), and a
            # request burst otherwise serializes its preprocessing ahead of
            # every stream's first token (r5: ~160 ms of the burst TTFT gap
            # between the HTTP and engine-loop legs at bs32). The dedicated
            # small pool (not the default executor) bounds thread-local
            # tokenizer loads to its worker count — see
            # llm/tokenizer.py:preprocessing_executor.
            from dynamo_tpu.llm.tokenizer import preprocessing_executor

            loop = asyncio.get_running_loop()
            t_pre = time.monotonic()
            if kind == "chat":
                pre, annotations = await loop.run_in_executor(
                    preprocessing_executor(), pipeline.preprocessor.preprocess_chat, req
                )
            else:
                pre, annotations = await loop.run_in_executor(
                    preprocessing_executor(), pipeline.preprocessor.preprocess_completion, req
                )
            t_pre_end = time.monotonic()
        except ProtocolError as e:
            # includes the preprocessor's context-length rejection: the
            # client gets a structured 400 with error.code
            # "context_length_exceeded", not a 500 or an SSE abort (the
            # check runs before any stream response starts)
            self.metrics.inc_request(model, endpoint, rtype, "400")
            return self._error(400, str(e), code=e.code)

        # per-tenant token-rate budget: charge prompt tokens + the output
        # budget against the tenant's bucket; an exhausted budget answers a
        # structured retriable 429 whose Retry-After says when the bucket
        # will hold this request's cost — before any SSE bytes
        cost = len(pre.token_ids) + max(0, pre.sampling.max_tokens)
        decision = self.qos.admit(
            tenant, priority, cost,
            request_id=getattr(pre, "request_id", "") or "",
        )
        if not decision.admitted:
            self.metrics.inc_request(model, endpoint, rtype, "429")
            return self._error(
                429, decision.reason + "; retry later", code="rate_limited",
                headers={"Retry-After": str(decision.retry_after_s)},
            )

        tool_matcher = None
        if kind == "chat" and req.tool_choice not in (None, "none") and not req.tools:
            self.metrics.inc_request(model, endpoint, rtype, "400")
            return self._error(400, "tool_choice requires a non-empty tools list")
        if kind == "chat" and req.tools and req.tool_choice != "none":
            try:
                tool_matcher = ToolCallingMatcher(req.tool_choice)
            except ValueError as e:
                self.metrics.inc_request(model, endpoint, rtype, "400")
                return self._error(400, str(e))
            if tool_matcher.forced_name is not None:
                known = {
                    (t.get("function") or {}).get("name")
                    for t in req.tools
                    if isinstance(t, dict)
                }
                if tool_matcher.forced_name not in known:
                    self.metrics.inc_request(model, endpoint, rtype, "400")
                    return self._error(
                        400,
                        f"tool_choice function {tool_matcher.forced_name!r} "
                        "is not in tools",
                    )

        # ambient request context: the trace/request ids stamped here ride
        # every downstream hop this request makes (workers, routers — see
        # dynamo_tpu/runtime/context.py); use_context resets on exit so
        # keep-alive connections (same task across requests) can't leak it
        meta = {"endpoint": endpoint, "model": model}
        if request.headers.get("x-request-id"):
            meta["x-request-id"] = request.headers["x-request-id"]
        ctx = new_context(request_id=getattr(pre, "request_id", None), metadata=meta)
        # the edge stamps the trace id: every downstream hop (processor,
        # workers) inherits it through the context's metadata bag, so one
        # request's spans stitch into a single multi-hop timeline
        ctx.ensure_trace_id()
        if tracing.enabled():
            tracing.record_span(
                "http.preprocess", t_pre, end=t_pre_end,
                request_id=ctx.request_id, trace_id=ctx.trace_id,
                attrs={"tokens": len(pre.token_ids)},
            )

        self.metrics.inflight(model, 1)
        try:
            with use_context(ctx):
                # completions echo: the prompt text leads the output stream
                # (token-id prompts echo their detokenization)
                echo_text = None
                if kind == "completion" and getattr(req, "echo", False):
                    if pre.logprobs is not None:
                        # OpenAI returns logprobs for echoed prompt tokens;
                        # prompt logprobs aren't computed here, so reject the
                        # combination explicitly rather than return a response
                        # that silently omits them
                        self.metrics.inc_request(model, endpoint, rtype, "400")
                        return self._error(
                            400, "echo with logprobs is not supported"
                        )
                    if isinstance(req.prompt, str):
                        echo_text = req.prompt
                    else:
                        echo_text = pipeline.preprocessor.tokenizer.decode(
                            pre.token_ids,
                            skip_special_tokens=pre.skip_special_tokens,
                        )
                # goodput/QoS tags: tenant/scenario/priority ride the
                # PreprocessedRequest to the engine so BOTH trackers (this
                # frontend's and the engine's) attribute the request and the
                # scheduler serves it at the admitted class
                pre.tenant = tenant
                pre.scenario = request.headers.get("x-scenario", "")
                pre.priority = priority
                chunks = self._generate_chunks(
                    pipeline, pre, kind, model, annotations, tool_matcher,
                    echo_text=echo_text,
                    tenant=pre.tenant,
                    priority=priority,
                )
                if req.stream:
                    return await self._stream_response(request, chunks, model, endpoint, t0)
                if kind == "chat":
                    result = await aggregate_chat_stream(chunks)
                else:
                    result = await aggregate_completion_stream(chunks)
            self.metrics.inc_request(model, endpoint, rtype, "200")
            return web.json_response(result)
        except ToolCallError as e:
            # model output did not satisfy a required/forced tool choice
            self.metrics.inc_request(model, endpoint, rtype, "422")
            return self._error(422, str(e))
        except Exception:
            log.exception("request failed")
            self.metrics.inc_request(model, endpoint, rtype, "500")
            return self._error(500, "internal error")
        finally:
            self.metrics.inflight(model, -1)
            self.metrics.observe_duration(model, endpoint, time.monotonic() - t0)
            tracing.record_span(
                "http.request", t0, end=time.monotonic(),
                request_id=ctx.request_id, trace_id=ctx.trace_id,
                attrs={"endpoint": endpoint, "model": model},
            )

    async def _generate_chunks(
        self,
        pipeline: ModelPipeline,
        pre,
        kind: str,
        model: str,
        annotations: dict,
        tool_matcher: Optional[ToolCallingMatcher] = None,
        echo_text: Optional[str] = None,
        tenant: str = "",
        priority: str = "",
    ) -> AsyncIterator[dict]:
        gen = (
            ChatDeltaGenerator(model) if kind == "chat" else CompletionDeltaGenerator(model)
        )
        usage = Usage(prompt_tokens=len(pre.token_ids))
        # requested annotations ride the SSE stream as named events, ahead of
        # the first delta (reference: protocols/annotated.rs envelope)
        for name, value in annotations.items():
            yield {"__event__": name, "data": value}
        if echo_text:
            yield gen.text_chunk(echo_text)
        want_timing = "timing" in pre.annotations
        t_start = time.monotonic()
        t_first = None
        t_prev = None  # last output-chunk arrival, for inter-token latency
        # goodput outcome accounting: the per-token gap series (amortized
        # over each chunk's tokens, same as the ITL histogram) + the
        # adapter suffix of a base:adapter LoRA model name
        itl_gaps: list = []
        adapter = model.split(":", 1)[1] if ":" in model and "{" not in model else ""
        # With tools active the full text must be buffered so a tool-call JSON
        # response never leaks as content deltas (tool calls are matched on
        # complete messages, llm/tools.py).
        buffered: list[str] = []
        buffered_lp: list = []
        async for out in pipeline.backend.generate(pre):
            usage.completion_tokens = out.cumulative_tokens
            if t_first is None and out.token_ids:
                t_first = t_prev = time.monotonic()
                self.metrics.observe_ttft(model, t_first - t_start)
                self.slo.observe(
                    "ttft", t_first - t_start, tenant=tenant, priority=priority
                )
                # OpenAI semantics: the role delta leads the stream at first-
                # token time. Also the client's only honest TTFT signal — the
                # first CONTENT delta can lag several tokens behind while the
                # detokenizer waits for a stable byte sequence.
                role = getattr(gen, "role_chunk", None)
                if role is not None and not gen._sent_role:
                    yield role()
            elif t_prev is not None and out.token_ids:
                # engine windows arrive as multi-token chunks: the honest
                # per-token number is the chunk gap amortized over its tokens
                now = time.monotonic()
                gap = (now - t_prev) / len(out.token_ids)
                self.metrics.observe_itl(model, gap)
                self.slo.observe("itl", gap, tenant=tenant, priority=priority)
                if len(itl_gaps) < MAX_ITL_SAMPLES:
                    itl_gaps.extend([gap] * min(
                        len(out.token_ids), MAX_ITL_SAMPLES - len(itl_gaps)
                    ))
                t_prev = now
            if tool_matcher is not None:
                if out.text:
                    buffered.append(out.text)
                if out.logprobs:
                    buffered_lp.extend(out.logprobs)
            elif out.text or out.logprobs:
                yield gen.text_chunk(out.text, logprobs=out.logprobs)
            if out.finished:
                finish = out.finish_reason or "stop"
                self._record_outcome(
                    pre, model, tenant, adapter, finish, t_start, t_first,
                    itl_gaps, usage, out.cached_tokens,
                )
                if tool_matcher is not None:
                    text = "".join(buffered)
                    calls = tool_matcher.get_calls(text)
                    if calls:
                        yield gen.tool_calls_chunk(calls)
                        finish = "tool_calls"
                    elif text:
                        yield gen.text_chunk(text, logprobs=buffered_lp or None)
                if want_timing:
                    total = time.monotonic() - t_start
                    ttft = (t_first - t_start) if t_first is not None else None
                    decode_s = (time.monotonic() - t_first) if t_first is not None else 0.0
                    yield {
                        "__event__": "timing",
                        "data": {
                            "ttft_ms": round(ttft * 1e3, 1) if ttft is not None else None,
                            "total_ms": round(total * 1e3, 1),
                            "output_tokens": usage.completion_tokens,
                            "cached_tokens": out.cached_tokens,
                            "decode_tok_per_s": (
                                round((usage.completion_tokens - 1) / decode_s, 1)
                                if usage.completion_tokens > 1 and decode_s > 0
                                else None
                            ),
                        },
                    }
                yield gen.finish_chunk(finish, usage)
                return

    def _record_outcome(
        self, pre, model: str, tenant: str, adapter: str, finish: str,
        t_start: float, t_first, itl_gaps: list, usage, cached_tokens: int,
    ) -> None:
        """One RequestOutcome per served request into the frontend goodput
        plane (error finishes count as SLO misses)."""
        from dynamo_tpu.utils.goodput import RequestOutcome

        try:
            self.goodput.observe(RequestOutcome(
                request_id=getattr(pre, "request_id", "") or "",
                scenario=getattr(pre, "scenario", "") or "",
                tenant=tenant,
                adapter=adapter,
                ttft_s=(t_first - t_start) if t_first is not None else None,
                itl_s=tuple(itl_gaps),
                prompt_tokens=usage.prompt_tokens,
                output_tokens=usage.completion_tokens,
                cached_tokens=cached_tokens,
                duration_s=time.monotonic() - t_start,
                finish_reason=finish,
                error=finish == "error",
            ))
        except Exception:
            log.exception("goodput outcome failed")

    async def _stream_response(
        self, request: web.Request, chunks: AsyncIterator[dict], model: str, endpoint: str, t0: float
    ) -> web.StreamResponse:
        resp = web.StreamResponse(
            status=200,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "Connection": "keep-alive",
            },
        )
        await resp.prepare(request)
        status = "200"
        try:
            async for chunk in chunks:
                if "__event__" in chunk:
                    await resp.write(sse.encode_event(chunk["__event__"], chunk.get("data")))
                    continue
                await resp.write(sse.encode_data(chunk))
            await resp.write(sse.encode_done())
        except (asyncio.CancelledError, ConnectionResetError):
            status = "499"
            raise
        except ToolCallError as e:
            status = "422"
            err = json.dumps({"error": {"message": str(e), "type": "tool_call_error"}})
            await resp.write(f"data: {err}\n\ndata: [DONE]\n\n".encode())
        except Exception:
            log.exception("stream failed")
            status = "500"
            await resp.write(
                b'data: {"error": {"message": "internal error"}}\n\ndata: [DONE]\n\n'
            )
        finally:
            self.metrics.inc_request(model, endpoint, "stream", status)
        await resp.write_eof()
        return resp
