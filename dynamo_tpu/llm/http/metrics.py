"""Prometheus metrics for the HTTP service (hand-rolled text exposition, no
external client library).

Metric names mirror the reference (reference: lib/llm/src/http/service/
metrics.rs:82-120): ``llm_http_service_requests_total``,
``llm_http_service_inflight_requests``, ``llm_http_service_request_duration_seconds``
labeled by model/endpoint/request_type/status — plus the per-stage serving
latency histograms the reference frontend publishes:
``llm_http_service_time_to_first_token_seconds`` and
``llm_http_service_inter_token_latency_seconds``.

Exposition conformance (promtool-checkable): every family renders its own
HELP/TYPE pair ahead of its samples, and ``le`` bucket labels use canonical
float formatting (utils/prometheus.py), never ``repr()``.
"""

from __future__ import annotations

import threading
from collections import defaultdict

from dynamo_tpu.utils.prometheus import Histogram, fmt_labels, render_family

_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
# TTFT spans sub-ms (cache hits on tiny models) to tens of seconds (deep queues)
_TTFT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
# inter-token latency is ms-scale on healthy decode
_ITL_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0, 2.5)


class Metrics:
    PREFIX = "llm_http_service"

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[tuple, float] = defaultdict(float)
        self._inflight: dict[tuple, int] = defaultdict(int)
        p = self.PREFIX
        self.duration = Histogram(
            f"{p}_request_duration_seconds", "request duration",
            _BUCKETS, ("endpoint", "model"),
        )
        self.ttft = Histogram(
            f"{p}_time_to_first_token_seconds",
            "time from request arrival to the first generated token",
            _TTFT_BUCKETS, ("model",),
        )
        self.itl = Histogram(
            f"{p}_inter_token_latency_seconds",
            "per-token latency between successive output chunks "
            "(chunk gap / tokens in chunk)",
            _ITL_BUCKETS, ("model",),
        )

    def inc_request(self, model: str, endpoint: str, request_type: str, status: str) -> None:
        key = (model, endpoint, request_type, status)
        with self._lock:
            self._counters[key] += 1

    def inflight(self, model: str, delta: int) -> None:
        with self._lock:
            self._inflight[(model,)] += delta

    def observe_duration(self, model: str, endpoint: str, seconds: float) -> None:
        self.duration.observe(seconds, (endpoint, model))

    def observe_ttft(self, model: str, seconds: float) -> None:
        self.ttft.observe(seconds, (model,))

    def observe_itl(self, model: str, seconds: float) -> None:
        self.itl.observe(seconds, (model,))

    def render(self, extra: str = "") -> str:
        p = self.PREFIX
        with self._lock:
            counters = sorted(self._counters.items())
            inflight = sorted(self._inflight.items())
        out = render_family(
            f"{p}_requests_total", "counter",
            "total requests by model/endpoint/type/status",
            [
                (
                    {"model": m, "endpoint": e, "request_type": t, "status": s},
                    int(v),
                )
                for (m, e, t, s), v in counters
            ],
        )
        out += render_family(
            f"{p}_inflight_requests", "gauge", "currently in-flight requests",
            [({"model": m}, v) for (m,), v in inflight],
        )
        out += self.duration.render()
        out += self.ttft.render()
        out += self.itl.render()
        if extra:
            out += extra
        return out


def _fmt_labels(labels: dict[str, str]) -> str:
    # kept for callers that built label strings through this module
    return fmt_labels(labels)
