"""Prometheus metrics for the HTTP service (hand-rolled text exposition, no
external client library).

Metric names mirror the reference (reference: lib/llm/src/http/service/
metrics.rs:82-120): ``llm_http_service_requests_total``,
``llm_http_service_inflight_requests``, ``llm_http_service_request_duration_seconds``
labeled by model/endpoint/request_type/status.
"""

from __future__ import annotations

import threading
from collections import defaultdict

_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Metrics:
    PREFIX = "llm_http_service"

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[tuple, float] = defaultdict(float)
        self._inflight: dict[tuple, int] = defaultdict(int)
        self._hist_counts: dict[tuple, list[int]] = {}
        self._hist_sum: dict[tuple, float] = defaultdict(float)
        self._hist_total: dict[tuple, int] = defaultdict(int)

    def inc_request(self, model: str, endpoint: str, request_type: str, status: str) -> None:
        key = (model, endpoint, request_type, status)
        with self._lock:
            self._counters[key] += 1

    def inflight(self, model: str, delta: int) -> None:
        with self._lock:
            self._inflight[(model,)] += delta

    def observe_duration(self, model: str, endpoint: str, seconds: float) -> None:
        key = (model, endpoint)
        with self._lock:
            if key not in self._hist_counts:
                self._hist_counts[key] = [0] * len(_BUCKETS)
            for i, b in enumerate(_BUCKETS):
                if seconds <= b:
                    self._hist_counts[key][i] += 1
            self._hist_sum[key] += seconds
            self._hist_total[key] += 1

    def render(self, extra: str = "") -> str:
        p = self.PREFIX
        lines = [
            f"# HELP {p}_requests_total total requests by model/endpoint/type/status",
            f"# TYPE {p}_requests_total counter",
        ]
        with self._lock:
            for (model, endpoint, rtype, status), v in sorted(self._counters.items()):
                labels = _fmt_labels(
                    {"model": model, "endpoint": endpoint, "request_type": rtype, "status": status}
                )
                lines.append(f"{p}_requests_total{labels} {int(v)}")
            lines += [
                f"# HELP {p}_inflight_requests currently in-flight requests",
                f"# TYPE {p}_inflight_requests gauge",
            ]
            for (model,), v in sorted(self._inflight.items()):
                lines.append(f"{p}_inflight_requests{_fmt_labels({'model': model})} {v}")
            lines += [
                f"# HELP {p}_request_duration_seconds request duration",
                f"# TYPE {p}_request_duration_seconds histogram",
            ]
            for (model, endpoint), counts in sorted(self._hist_counts.items()):
                base = {"model": model, "endpoint": endpoint}
                for b, c in zip(_BUCKETS, counts):
                    labels = _fmt_labels({**base, "le": repr(b)})
                    lines.append(f"{p}_request_duration_seconds_bucket{labels} {c}")
                labels = _fmt_labels({**base, "le": "+Inf"})
                lines.append(
                    f"{p}_request_duration_seconds_bucket{labels} {self._hist_total[(model, endpoint)]}"
                )
                lines.append(
                    f"{p}_request_duration_seconds_sum{_fmt_labels(base)} {self._hist_sum[(model, endpoint)]:.6f}"
                )
                lines.append(
                    f"{p}_request_duration_seconds_count{_fmt_labels(base)} {self._hist_total[(model, endpoint)]}"
                )
        out = "\n".join(lines) + "\n"
        if extra:
            out += extra
        return out
