"""External-engine adapter: host an arbitrary user-supplied Python engine
behind the full serving stack (frontend, preprocessor, router, disagg
machinery).

The reference's headline identity is engine-agnostic serving: its launcher
hosts user engines via ``out=pytok:file.py`` / ``out=pystr:file.py`` — a
Python module exposing an async generator that takes a request and yields
tokens (reference: lib/llm/src/engines/python.rs:105-146, the generic
Python engine behind both schemes). dynamo-tpu's native engine is JAX, but
the same slot exists here: ``out=pytok:module:fn`` resolves ``fn`` in
``module`` and adapts it to the engine protocol every frontend/router/
backend component speaks (``generate(EngineRequest) -> AsyncIterator[
StepOutput]``).

The user function contract (tokens-in/tokens-out):

    async def fn(token_ids: list[int], sampling: dict, request_id: str):
        yield 42                      # one token id
        yield [43, 44]                # or several at once
        yield {"token_ids": [45], "finish_reason": "stop"}  # or a dict

- ints and lists of ints are emitted as generated tokens
- a dict may carry ``token_ids`` plus an optional ``finish_reason``
  ("stop" ends the stream even below max_tokens)
- the adapter enforces ``sampling["max_tokens"]`` and emits the final
  StepOutput with ``finished=True`` / a finish_reason, so a user engine
  never has to re-implement the termination bookkeeping
"""

from __future__ import annotations

import importlib
import inspect
from typing import AsyncIterator

from dynamo_tpu.engine.scheduler import EngineRequest, StepOutput
from dynamo_tpu.utils import get_logger

log = get_logger("llm.external")


def resolve_spec(spec: str):
    """Resolve ``module:qualname`` into the callable it names."""
    module_name, sep, qualname = spec.partition(":")
    if not sep or not module_name or not qualname:
        raise ValueError(
            f"external engine spec {spec!r} must be 'module:function'"
        )
    module = importlib.import_module(module_name)
    obj = module
    for part in qualname.split("."):
        obj = getattr(obj, part)
    if not callable(obj):
        raise TypeError(f"external engine {spec!r} resolved to non-callable {obj!r}")
    return obj


class ExternalTokenEngine:
    """Adapts a user async-generator function to the engine protocol
    (``pytok:`` scheme — tokens in, tokens out)."""

    def __init__(self, spec_or_fn):
        if isinstance(spec_or_fn, str):
            self.fn = resolve_spec(spec_or_fn)
            self.spec = spec_or_fn
        else:
            self.fn = spec_or_fn
            self.spec = getattr(spec_or_fn, "__name__", repr(spec_or_fn))
        if not inspect.isasyncgenfunction(self.fn):
            raise TypeError(
                f"external engine {self.spec!r} must be an async generator "
                "function (async def ... with yield)"
            )

    async def generate(self, request: EngineRequest) -> AsyncIterator[StepOutput]:
        import dataclasses

        sampling = dataclasses.asdict(request.sampling)
        max_tokens = request.sampling.max_tokens
        agen = self.fn(list(request.token_ids), sampling, request.request_id)
        emitted = 0
        finish_reason = None
        try:
            async for item in agen:
                if isinstance(item, dict):
                    tokens = list(item.get("token_ids", ()))
                    finish_reason = item.get("finish_reason") or finish_reason
                elif isinstance(item, int):
                    tokens = [item]
                else:
                    tokens = list(item)
                for j, tok in enumerate(tokens):
                    emitted += 1
                    natural_end = finish_reason is not None and j == len(tokens) - 1
                    done = emitted >= max_tokens or natural_end
                    # a user finish_reason only applies when its item was
                    # FULLY delivered; a stream cut mid-item by max_tokens is
                    # a truncation and must report "length" even if the
                    # truncated item carried finish_reason="stop"
                    yield StepOutput(
                        request_id=request.request_id,
                        token=int(tok),
                        finished=done,
                        finish_reason=(
                            (finish_reason if natural_end else "length")
                            if done
                            else None
                        ),
                    )
                    if done:
                        return
                if finish_reason is not None:
                    # dict carried a finish_reason but no tokens: end now
                    yield StepOutput(
                        request_id=request.request_id,
                        token=None,
                        finished=True,
                        finish_reason=finish_reason,
                    )
                    return
        finally:
            await agen.aclose()
        # generator exhausted without declaring a reason: natural stop
        yield StepOutput(
            request_id=request.request_id,
            token=None,
            finished=True,
            finish_reason=finish_reason or "stop",
        )

    async def shutdown(self) -> None:
        closer = getattr(self.fn, "shutdown", None)
        if closer is not None:
            result = closer()
            if inspect.iscoroutine(result):
                await result

    def metrics(self):
        from dynamo_tpu.engine.engine import ForwardPassMetrics

        return ForwardPassMetrics()
