"""Token sequences -> fixed-size blocks with chained sequence hashes.

This is the canonical block identity used for KV reuse and KV-aware routing.
Semantics mirror the reference (reference: lib/llm/src/tokens.rs:27-388 and
lib/llm/src/kv_router/indexer.rs:62-133):

  - ``compute_hash(data) = xxh3_64(data, seed=1337)``
  - block hash  = hash of the block's token ids as little-endian u32 bytes
  - sequence hash (chained): first full block's sequence hash is its block hash;
    block i's sequence hash = hash of ``[parent_sequence_hash, block_hash]`` as
    two little-endian u64s
  - ``compute_block_hash_for_seq`` = *unchained* per-chunk hashes over complete
    chunks only (used by the router's radix-tree matching)
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Optional, Sequence

import xxhash

XXH3_SEED = 1337

Token = int
SequenceHash = int
BlockHash = int


def compute_hash(data: bytes) -> int:
    return xxhash.xxh3_64_intdigest(data, seed=XXH3_SEED)


def _tokens_bytes(tokens: Sequence[int]) -> bytes:
    return struct.pack(f"<{len(tokens)}I", *tokens)


def compute_block_hash(tokens: Sequence[int], salt: int = 0) -> BlockHash:
    """``salt`` (e.g. a LoRA adapter uid, lora/adapter.py lora_uid) prefixes
    the hashed bytes so salted identities never collide with unsalted ones;
    0 = the classic unsalted hash (bit-compatible with the reference)."""
    data = _tokens_bytes(tokens)
    if salt:
        data = struct.pack("<Q", salt & 0xFFFFFFFFFFFFFFFF) + data
    return compute_hash(data)


def compute_block_hash_for_seq(
    tokens: Sequence[int], kv_block_size: int, salt: int = 0
) -> list[BlockHash]:
    """Unchained per-chunk hashes of complete chunks (router matching identity).

    Reference: lib/llm/src/kv_router/indexer.rs:123-133. ``salt`` folds into
    the FIRST chunk's hash only: every later chunk is reachable solely
    through its salted ancestor in the radix tree, so one diverged root
    isolates the whole adapter-specific prefix line while deeper chunk
    hashes stay shared-computation-friendly.
    """
    return [
        compute_block_hash(tokens[i : i + kv_block_size], salt if i == 0 else 0)
        for i in range(0, len(tokens) - kv_block_size + 1, kv_block_size)
    ]


def chain_hash(parent: SequenceHash, block_hash: BlockHash) -> SequenceHash:
    return compute_hash(struct.pack("<QQ", parent, block_hash))


@dataclass(frozen=True)
class TokenBlock:
    """A complete block of ``block_size`` tokens with its chained identity."""

    tokens: tuple[int, ...]
    block_hash: BlockHash
    sequence_hash: SequenceHash
    parent_sequence_hash: Optional[SequenceHash]


@dataclass
class PartialTokenBlock:
    """The trailing incomplete block of a sequence."""

    tokens: list[int] = field(default_factory=list)
    parent_sequence_hash: Optional[SequenceHash] = None


class TokenSequence:
    """Incremental splitter of a token stream into hashed blocks.

    Mirrors reference TokenSequence/split_tokens (lib/llm/src/tokens.rs:180-260):
    the first block's sequence hash equals its block hash; later blocks chain.

    ``salt`` (LoRA adapter uid) folds into the first block's BLOCK hash, so
    the whole chained line — and therefore every engine block identity, KV
    event, and fleet pull key derived from it — is adapter-specific without
    changing the chain structure (parent of block 0 stays None).
    """

    def __init__(self, tokens: Sequence[int] = (), block_size: int = 16, salt: int = 0):
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.block_size = block_size
        self.salt = salt
        self.blocks: list[TokenBlock] = []
        self.current = PartialTokenBlock()
        self.extend(tokens)

    def __len__(self) -> int:
        return len(self.blocks) * self.block_size + len(self.current.tokens)

    @property
    def tokens(self) -> list[int]:
        out: list[int] = []
        for b in self.blocks:
            out.extend(b.tokens)
        out.extend(self.current.tokens)
        return out

    def push_token(self, token: int) -> Optional[TokenBlock]:
        """Append one token; returns the newly completed TokenBlock if any."""
        cur = self.current
        cur.tokens.append(token)
        if len(cur.tokens) < self.block_size:
            return None
        block_hash = compute_block_hash(
            cur.tokens, self.salt if cur.parent_sequence_hash is None else 0
        )
        if cur.parent_sequence_hash is None:
            sequence_hash = block_hash
        else:
            sequence_hash = chain_hash(cur.parent_sequence_hash, block_hash)
        block = TokenBlock(
            tokens=tuple(cur.tokens),
            block_hash=block_hash,
            sequence_hash=sequence_hash,
            parent_sequence_hash=cur.parent_sequence_hash,
        )
        self.blocks.append(block)
        self.current = PartialTokenBlock(parent_sequence_hash=sequence_hash)
        return block

    def extend(self, tokens: Sequence[int]) -> list[TokenBlock]:
        completed = []
        for t in tokens:
            block = self.push_token(t)
            if block is not None:
                completed.append(block)
        return completed

    def sequence_hashes(self) -> list[SequenceHash]:
        return [b.sequence_hash for b in self.blocks]
