"""Model registration in the control plane: the glue between workers/llmctl and
HTTP frontends.

Mirrors the reference's etcd ModelEntry registrations that the http frontend
watches (reference: launch/llmctl/src/main.rs:115-310, lib/llm/src/http/
service/discovery.rs:1-145). Keys:

    models/{model_type}/{name} -> msgpack ModelEntry{name, endpoint, card}
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import msgpack

from dynamo_tpu.llm.model_card import ModelDeploymentCard

MODELS_PREFIX = "models"


@dataclass
class ModelEntry:
    name: str
    endpoint: str  # dyn://ns.comp.ep serving PreprocessedRequest -> BackendOutput
    model_type: str = "chat"  # chat | completion
    card: Optional[ModelDeploymentCard] = None

    def key(self) -> str:
        return f"{MODELS_PREFIX}/{self.model_type}/{self.name}"

    def to_wire(self) -> bytes:
        return msgpack.packb(
            {
                "name": self.name,
                "endpoint": self.endpoint,
                "model_type": self.model_type,
                "card": self.card.to_wire() if self.card else None,
            }
        )

    @classmethod
    def from_wire(cls, raw: bytes) -> "ModelEntry":
        d = msgpack.unpackb(raw, raw=False)
        card = ModelDeploymentCard.from_wire(d["card"]) if d.get("card") else None
        return cls(name=d["name"], endpoint=d["endpoint"], model_type=d["model_type"], card=card)


async def register_model(cplane, entry: ModelEntry, lease_id: int = 0) -> None:
    await cplane.kv_put(entry.key(), entry.to_wire(), lease_id=lease_id)


async def unregister_model(cplane, model_type: str, name: str) -> bool:
    return await cplane.kv_delete(f"{MODELS_PREFIX}/{model_type}/{name}")


async def list_models(cplane) -> list[ModelEntry]:
    items = await cplane.kv_get_prefix(MODELS_PREFIX + "/")
    return [ModelEntry.from_wire(i.value) for i in items]
