"""Model registration in the control plane: the glue between workers/llmctl and
HTTP frontends.

Mirrors the reference's etcd ModelEntry registrations that the http frontend
watches (reference: launch/llmctl/src/main.rs:115-310, lib/llm/src/http/
service/discovery.rs:1-145). Keys:

    models/{model_type}/{name} -> msgpack ModelEntry{name, endpoint, card}
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import msgpack

from dynamo_tpu.llm.model_card import ModelDeploymentCard

MODELS_PREFIX = "models"


@dataclass
class ModelEntry:
    name: str
    endpoint: str  # dyn://ns.comp.ep serving PreprocessedRequest -> BackendOutput
    model_type: str = "chat"  # chat | completion
    card: Optional[ModelDeploymentCard] = None

    def key(self) -> str:
        return f"{MODELS_PREFIX}/{self.model_type}/{self.name}"

    def to_wire(self) -> bytes:
        return msgpack.packb(
            {
                "name": self.name,
                "endpoint": self.endpoint,
                "model_type": self.model_type,
                "card": self.card.to_wire() if self.card else None,
            }
        )

    @classmethod
    def from_wire(cls, raw: bytes) -> "ModelEntry":
        d = msgpack.unpackb(raw, raw=False)
        card = ModelDeploymentCard.from_wire(d["card"]) if d.get("card") else None
        return cls(name=d["name"], endpoint=d["endpoint"], model_type=d["model_type"], card=card)


async def register_model(cplane, entry: ModelEntry, lease_id: int = 0) -> None:
    await cplane.kv_put(entry.key(), entry.to_wire(), lease_id=lease_id)


class ModelRegistration:
    """Keep a model card registered while its worker lives.

    The reference republishes cards into a TTL bucket so a dead engine's card
    expires (reference: lib/llm/src/model_card/model.rs:70-80). Here the card
    key is LEASE-TIED (dies with the registering worker's connection) and a
    refresh loop re-puts it periodically — so when the lease-owning worker of
    a multi-worker model dies, any surviving worker's next refresh restores
    the card within one interval instead of leaving it gone (or, with no
    lease at all, leaving a stale card forever in the durable broker KV)."""

    def __init__(self, cplane, entry: ModelEntry, lease_id: int, interval: float = 5.0):
        import asyncio

        self._cplane = cplane
        self.entry = entry
        self.lease_id = lease_id
        self.interval = interval
        self._task: "asyncio.Task | None" = None

    async def start(self) -> "ModelRegistration":
        import asyncio

        await register_model(self._cplane, self.entry, lease_id=self.lease_id)
        self._task = asyncio.create_task(self._refresh_loop())
        return self

    async def _refresh_loop(self) -> None:
        import asyncio

        from dynamo_tpu.utils import get_logger

        log = get_logger("llm.model_registry")
        while True:
            await asyncio.sleep(self.interval)
            try:
                await register_model(self._cplane, self.entry, lease_id=self.lease_id)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                log.warning("model card refresh failed for %s: %s", self.entry.name, e)

    async def stop(self, unregister: bool = True) -> None:
        if self._task is not None:
            import asyncio
            import contextlib

            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._task
        if unregister:
            try:
                await unregister_model(self._cplane, self.entry.model_type, self.entry.name)
            except Exception:
                pass


async def unregister_model(cplane, model_type: str, name: str) -> bool:
    return await cplane.kv_delete(f"{MODELS_PREFIX}/{model_type}/{name}")


async def list_models(cplane) -> list[ModelEntry]:
    items = await cplane.kv_get_prefix(MODELS_PREFIX + "/")
    return [ModelEntry.from_wire(i.value) for i in items]
