"""Echo engines for tests/debugging: stream the prompt back.

Mirrors the reference echo engines (reference: launch/dynamo-run/src/output/
echo_core.rs:1-70 — token-level echo used to exercise the full pre/post
processing pipeline with no model).
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator

from dynamo_tpu.engine.scheduler import EngineRequest, StepOutput


class EchoEngine:
    """Token-level echo: emits the prompt tokens back one by one."""

    def __init__(self, delay_s: float = 0.0):
        self.delay_s = delay_s

    async def generate(self, request: EngineRequest) -> AsyncIterator[StepOutput]:
        n = min(len(request.token_ids), request.sampling.max_tokens)
        for i, tok in enumerate(request.token_ids[:n]):
            if self.delay_s:
                await asyncio.sleep(self.delay_s)
            last = i == n - 1
            yield StepOutput(
                request_id=request.request_id,
                token=int(tok),
                finished=last,
                finish_reason="length" if last else None,
            )

    async def shutdown(self) -> None:
        return None

    def metrics(self):
        from dynamo_tpu.engine.engine import ForwardPassMetrics

        return ForwardPassMetrics()
