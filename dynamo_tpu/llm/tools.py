"""Tool (function) calling: request-side choice parsing + response matching.

reference: lib/llm/src/preprocessor/tools.rs (ToolCallingMatcher.get_call,
CalledFunctionParameters/CalledFunctionArguments forms) and
preprocessor/tools/request.rs (ToolChoice none | auto | forced tool).

The matcher parses a completed model response as JSON in any of four shapes —
``{"name", "parameters"}``, ``{"name", "arguments"}``, or a list of either —
and normalizes to OpenAI ``tool_calls`` entries. Parsing happens on the full
generated text (the reference does the same: tool calls are matched on
complete messages, not streamed argument fragments).
"""

from __future__ import annotations

import json
import uuid
from typing import Any, Optional

TOOL_CHOICE_NONE = "none"
TOOL_CHOICE_AUTO = "auto"
TOOL_CHOICE_REQUIRED = "required"


class ToolCallError(ValueError):
    """Raised when a forced tool choice produced no parseable call."""


def parse_tool_choice(raw: Any) -> tuple[str, Optional[str]]:
    """Normalize an OpenAI ``tool_choice`` value.

    Returns (mode, forced_name): mode is none|auto|required; forced_name is
    set when a specific function was requested (mode becomes ``required``).
    """
    if raw is None or raw == TOOL_CHOICE_AUTO:
        return TOOL_CHOICE_AUTO, None
    if raw == TOOL_CHOICE_NONE:
        return TOOL_CHOICE_NONE, None
    if raw == TOOL_CHOICE_REQUIRED:
        return TOOL_CHOICE_REQUIRED, None
    if isinstance(raw, dict):
        name = (raw.get("function") or {}).get("name")
        if raw.get("type") == "function" and name:
            return TOOL_CHOICE_REQUIRED, name
    raise ValueError(f"invalid tool_choice: {raw!r}")


def _normalize_one(obj: Any) -> Optional[dict]:
    """{"name", "parameters"|"arguments"} -> tool_calls entry, else None."""
    if not isinstance(obj, dict) or not isinstance(obj.get("name"), str):
        return None
    args = obj.get("parameters") if "parameters" in obj else obj.get("arguments")
    if not isinstance(args, dict):
        return None
    return {
        "id": f"call-{uuid.uuid4()}",
        "type": "function",
        "function": {
            "name": obj["name"],
            "arguments": json.dumps(args, separators=(",", ":")),
        },
    }


class ToolCallingMatcher:
    """Matches tool-call patterns in completed LLM responses."""

    def __init__(self, tool_choice: Any = TOOL_CHOICE_AUTO):
        self.mode, self.forced_name = parse_tool_choice(tool_choice)

    def get_calls(self, message: str) -> list[dict]:
        """Parse ``message`` into tool_calls entries ([] when none match).

        Raises ToolCallError when the choice demanded a call (required /
        forced tool) but the text is not a tool call.
        """
        calls: list[dict] = []
        if self.mode != TOOL_CHOICE_NONE:
            text = message.strip()
            # models frequently wrap the JSON in a markdown fence
            if text.startswith("```"):
                text = text.strip("`")
                if text.startswith("json"):
                    text = text[4:]
                text = text.strip()
            try:
                parsed = json.loads(text)
            except (json.JSONDecodeError, UnicodeDecodeError):
                parsed = None
            if isinstance(parsed, list):
                normalized = [_normalize_one(o) for o in parsed]
                if normalized and all(c is not None for c in normalized):
                    calls = normalized
            else:
                one = _normalize_one(parsed)
                if one is not None:
                    calls = [one]
        if self.mode == TOOL_CHOICE_REQUIRED and not calls:
            raise ToolCallError("tool choice was required but no tools were called")
        if self.forced_name and all(
            c["function"]["name"] != self.forced_name for c in calls
        ):
            raise ToolCallError(
                f"tool choice required a call to {self.forced_name!r}"
            )
        return calls
