"""KV-cache-aware routing: global radix index fed by worker events + cost-based
worker selection (reference: lib/llm/src/kv_router/)."""

from dynamo_tpu.llm.kv_router.indexer import KvIndexer, OverlapScores, RadixTree, RouterEvent
from dynamo_tpu.llm.kv_router.scheduler import KvScheduler, ProcessedEndpoints, WorkerLoad
from dynamo_tpu.llm.kv_router.router import KvRouter
