"""Router-side metrics aggregation: periodically scrape every worker's
ForwardPassMetrics via the stats broadcast.

Mirrors the reference aggregator (reference: lib/llm/src/kv_router/
metrics_aggregator.rs:1-171 collect_endpoints_task).
"""

from __future__ import annotations

import asyncio
from typing import Optional

from dynamo_tpu.llm.kv_router.scheduler import WorkerLoad
from dynamo_tpu.runtime.service import collect_service_stats
from dynamo_tpu.utils import get_logger

log = get_logger("kv_router.metrics")


class KvMetricsAggregator:
    def __init__(
        self,
        cplane,
        namespace: str,
        component: str,
        interval: float = 1.0,
        scrape_timeout: float = 0.3,
    ):
        self.cplane = cplane
        self.namespace = namespace
        self.component = component
        self.interval = interval
        self.scrape_timeout = scrape_timeout
        self._latest: list[WorkerLoad] = []
        self._latest_raw: list[tuple[int, dict]] = []  # (instance_id, stats data)
        self._task: Optional[asyncio.Task] = None
        self._on_update = None

    def on_update(self, cb) -> None:
        self._on_update = cb

    async def start(self) -> None:
        self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()

    async def scrape_once(self) -> list[WorkerLoad]:
        stats = await collect_service_stats(
            self.cplane, self.namespace, self.component, timeout=self.scrape_timeout
        )
        loads = []
        for ep in stats.endpoints:
            kv = ep.data.get("kv_metrics")
            if kv is not None:
                loads.append(WorkerLoad.from_wire(ep.instance_id, kv))
        self._latest = loads
        self._latest_raw = [(ep.instance_id, ep.data) for ep in stats.endpoints]
        if self._on_update is not None:
            self._on_update(loads)
        return loads

    def get_metrics(self) -> list[WorkerLoad]:
        return list(self._latest)

    def get_raw(self) -> list[tuple[int, dict]]:
        """Full stats payloads of the last scrape, beyond kv_metrics — e.g.
        per-stage latency attribution (stage_seconds) and disagg counters."""
        return list(self._latest_raw)

    async def _loop(self) -> None:
        try:
            while True:
                try:
                    await self.scrape_once()
                except Exception:
                    log.exception("metrics scrape failed")
                await asyncio.sleep(self.interval)
        except asyncio.CancelledError:
            pass
