"""Router-side metrics aggregation: periodically scrape every worker's
ForwardPassMetrics via the stats broadcast, and keep a fleet view with
per-worker freshness and health.

Mirrors the reference aggregator (reference: lib/llm/src/kv_router/
metrics_aggregator.rs:1-171 collect_endpoints_task), with the fleet-health
layer on top:

  - workers that stop replying are aged out after ``max_missed_scrapes``
    rounds instead of living in ``_latest`` forever; a worker missing >= 1
    round is *stale* (still listed in ``worker_views`` for status surfaces,
    excluded from routing/scaling once aged or unservable)
  - workers whose scraped ``health.state`` is draining/dead are excluded from
    ``get_metrics``/``get_raw`` immediately — routers and planners must not
    hand them new work even while their stats keep flowing
  - scrape failures are logged once per state change (fail -> recover), not
    a full exception stack every interval
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Optional

from dynamo_tpu.llm.kv_router.scheduler import WorkerLoad
from dynamo_tpu.runtime.service import collect_service_stats
from dynamo_tpu.utils import get_logger
from dynamo_tpu.utils.health import is_snapshot_servable

log = get_logger("kv_router.metrics")


@dataclass
class WorkerView:
    """One worker's last-known stats + freshness, for fleet status surfaces."""

    instance_id: int
    data: dict = field(default_factory=dict)
    load: Optional[WorkerLoad] = None
    last_seen: float = 0.0  # monotonic, aggregator clock
    last_seen_wall: float = 0.0  # wall clock, for cross-process display
    missed_scrapes: int = 0

    @property
    def stale(self) -> bool:
        return self.missed_scrapes > 0

    @property
    def health(self) -> Optional[dict]:
        h = self.data.get("health")
        return h if isinstance(h, dict) else None

    @property
    def servable(self) -> bool:
        """Eligible for new work: fresh enough AND not draining/dead."""
        return is_snapshot_servable(self.health)

    def age_s(self, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        return max(0.0, now - self.last_seen)


class KvMetricsAggregator:
    def __init__(
        self,
        cplane,
        namespace: str,
        component: str,
        interval: float = 1.0,
        scrape_timeout: float = 0.3,
        max_missed_scrapes: int = 3,
    ):
        self.cplane = cplane
        self.namespace = namespace
        self.component = component
        self.interval = interval
        self.scrape_timeout = scrape_timeout
        self.max_missed_scrapes = max_missed_scrapes
        self._workers: dict[int, WorkerView] = {}
        self._task: Optional[asyncio.Task] = None
        self._on_update = None
        self._scrape_failing = False  # log once per state change, not per round

    def on_update(self, cb) -> None:
        self._on_update = cb

    async def start(self) -> None:
        self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()

    # ---------------- scraping ----------------

    async def scrape_once(self) -> list[WorkerLoad]:
        """One scrape round. Returns the servable loads (the routing view)."""
        stats = await collect_service_stats(
            self.cplane, self.namespace, self.component, timeout=self.scrape_timeout
        )
        now = time.monotonic()
        wall = time.time()
        seen: set[int] = set()
        for ep in stats.endpoints:
            seen.add(ep.instance_id)
            view = self._workers.get(ep.instance_id)
            if view is None:
                view = self._workers[ep.instance_id] = WorkerView(ep.instance_id)
            view.data = ep.data
            view.last_seen = now
            view.last_seen_wall = wall
            view.missed_scrapes = 0
            kv = ep.data.get("kv_metrics")
            view.load = (
                WorkerLoad.from_wire(ep.instance_id, kv) if kv is not None else None
            )
        self._age_unseen(seen)
        loads = self.get_metrics()
        if self._on_update is not None:
            self._on_update(loads)
        return loads

    def _age_unseen(self, seen: set[int]) -> None:
        """Bump the miss counter of every known worker absent from this round
        and drop the ones past the age-out threshold."""
        for instance_id in list(self._workers):
            if instance_id in seen:
                continue
            view = self._workers[instance_id]
            view.missed_scrapes += 1
            if view.missed_scrapes > self.max_missed_scrapes:
                log.info(
                    "worker %x aged out after %d missed scrapes",
                    instance_id, view.missed_scrapes,
                )
                del self._workers[instance_id]

    # ---------------- views ----------------

    def get_metrics(self) -> list[WorkerLoad]:
        """Loads of workers eligible for new work: not aged out, not
        draining/dead. Routers and the planner consume this view."""
        return [
            v.load
            for v in self._workers.values()
            if v.load is not None and v.servable
        ]

    def get_raw(self) -> list[tuple[int, dict]]:
        """Full stats payloads of servable workers, beyond kv_metrics — e.g.
        per-stage latency attribution (stage_seconds) and disagg counters."""
        return [
            (v.instance_id, v.data) for v in self._workers.values() if v.servable
        ]

    def raw_for(self, instance_id: int) -> Optional[dict]:
        """One servable worker's full stats payload (e.g. its ``kv_pull``
        advertisement for the fleet prefix cache); None when unknown or
        draining/dead — a fetch must never target a worker routing skips."""
        view = self._workers.get(instance_id)
        if view is None or not view.servable:
            return None
        return view.data

    def worker_views(self) -> list[WorkerView]:
        """Every tracked worker including stale ones — the ``/cluster/status``
        source (status surfaces must SHOW a dying worker, not hide it)."""
        return sorted(self._workers.values(), key=lambda v: v.instance_id)

    # ---------------- loop ----------------

    async def _loop(self) -> None:
        try:
            while True:
                try:
                    await self.scrape_once()
                    if self._scrape_failing:
                        self._scrape_failing = False
                        log.info(
                            "metrics scrape recovered for %s/%s",
                            self.namespace, self.component,
                        )
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    # a failed round means nobody was seen: age everyone so a
                    # dead control plane can't freeze the last snapshot in
                    # place forever
                    self._age_unseen(set())
                    if not self._scrape_failing:
                        self._scrape_failing = True
                        log.warning(
                            "metrics scrape failing for %s/%s: %s "
                            "(suppressing until recovery)",
                            self.namespace, self.component, e,
                        )
                await asyncio.sleep(self.interval)
        except asyncio.CancelledError:
            pass
