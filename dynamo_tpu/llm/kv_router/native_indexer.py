"""ctypes bindings for the native C++ radix tree (native/src/radix_tree.cc).

Drop-in replacement for the pure-Python RadixTree used by KvIndexer when the
native library is available (DYNTPU_NATIVE=0 disables). Same event semantics;
hashes are computed in Python (xxh3 via the C-backed xxhash wheel) and passed
as u64 arrays.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional, Sequence

from dynamo_tpu.llm.kv_router.indexer import OverlapScores, RouterEvent, WorkerId
from dynamo_tpu.utils import get_logger

log = get_logger("kv_router.native")

_lib = None
_load_failed = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    if os.environ.get("DYNTPU_NATIVE", "1") == "0":
        _load_failed = True
        return None
    try:
        import sys
        from pathlib import Path

        repo_root = Path(__file__).resolve().parents[3]
        sys.path.insert(0, str(repo_root / "native"))
        try:
            import build as native_build  # native/build.py
        finally:
            sys.path.pop(0)
        lib = ctypes.CDLL(str(native_build.build()))
        lib.rtree_new.restype = ctypes.c_void_p
        lib.rtree_free.argtypes = [ctypes.c_void_p]
        lib.rtree_apply_stored.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint64, ctypes.c_int,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.rtree_apply_removed.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.rtree_remove_worker.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.rtree_stats.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ]
        lib.rtree_find_matches.restype = ctypes.c_int64
        lib.rtree_find_matches.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ]
        _lib = lib
    except Exception as e:  # toolchain missing etc. — fall back to Python
        log.warning("native radix tree unavailable (%s); using Python tree", e)
        _load_failed = True
    return _lib


def native_available() -> bool:
    return _load() is not None


def _u64_array(values: Sequence[int]):
    return (ctypes.c_uint64 * len(values))(*[v & 0xFFFFFFFFFFFFFFFF for v in values])


class NativeRadixTree:
    """Same interface as dynamo_tpu.llm.kv_router.indexer.RadixTree (minus
    frequency tracking, which stays Python-side when enabled)."""

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._handle = ctypes.c_void_p(lib.rtree_new())

    def __del__(self):
        if getattr(self, "_handle", None) and self._lib is not None:
            self._lib.rtree_free(self._handle)
            self._handle = None

    def apply_event(self, event: RouterEvent) -> None:
        ev = event.event
        if ev.kind == "stored":
            blocks = ev.blocks
            self._lib.rtree_apply_stored(
                self._handle,
                event.worker_id,
                (ev.parent_hash or 0) & 0xFFFFFFFFFFFFFFFF,
                0 if ev.parent_hash is None else 1,
                len(blocks),
                _u64_array([b.block_hash for b in blocks]),
                _u64_array([b.tokens_hash for b in blocks]),
            )
        elif ev.kind == "removed":
            self._lib.rtree_apply_removed(
                self._handle, event.worker_id, len(ev.block_hashes), _u64_array(ev.block_hashes)
            )

    def remove_worker(self, worker: WorkerId) -> None:
        self._lib.rtree_remove_worker(self._handle, worker)

    def stats(self) -> tuple[int, int]:
        """(num_nodes, num_workers)."""
        nodes = ctypes.c_int64()
        workers = ctypes.c_int64()
        self._lib.rtree_stats(self._handle, ctypes.byref(nodes), ctypes.byref(workers))
        return nodes.value, workers.value

    def find_matches(self, sequence: Sequence[int], early_exit: bool = False) -> OverlapScores:
        max_out = 4096
        out_w = (ctypes.c_int64 * max_out)()
        out_s = (ctypes.c_int64 * max_out)()
        n = self._lib.rtree_find_matches(
            self._handle, len(sequence), _u64_array(sequence),
            1 if early_exit else 0, out_w, out_s, max_out,
        )
        if n < 0:
            raise RuntimeError("too many workers in match result")
        return OverlapScores(scores={out_w[i]: out_s[i] for i in range(n)})
