"""Cost-based worker selection for KV-aware routing.

Formula mirrors the reference (reference: lib/llm/src/kv_router/scheduler.rs:215-316):

  cost = alpha * load_deviation + (1 - alpha) * normalized_new_tokens
         + gamma * request_load_ratio

with alpha = 0.7 when in balance mode (load_std > 0.1 * load_avg) else 0.3,
gamma = 0.1; workers at slot or block capacity are excluded; the chosen
worker's counters are bumped optimistically; a KVHitRateEvent is emitted.

One deliberate fix vs the reference: load_avg/load_std are computed over KV
*usage ratios* (the reference mixes absolute block counts into an average that
is then compared against ratios, scoring.rs:32-49).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

from dynamo_tpu.llm.kv_router.indexer import OverlapScores, WorkerId
from dynamo_tpu.utils import get_logger

log = get_logger("kv_router.scheduler")

BALANCE_THRESHOLD = 0.1
ALPHA_BALANCE = 0.7
ALPHA_NORMAL = 0.3
GAMMA = 0.1


class NoWorkersError(RuntimeError):
    pass


class AllWorkersBusyError(RuntimeError):
    pass


@dataclass
class WorkerLoad:
    """ForwardPassMetrics snapshot for one worker
    (reference: kv_router/protocols.rs:19-33)."""

    worker_id: WorkerId
    request_active_slots: int = 0
    request_total_slots: int = 1
    kv_active_blocks: int = 0
    kv_total_blocks: int = 1
    num_requests_waiting: int = 0
    gpu_cache_usage_perc: float = 0.0
    gpu_prefix_cache_hit_rate: float = 0.0

    @property
    def kv_load_ratio(self) -> float:
        return self.kv_active_blocks / max(1, self.kv_total_blocks)

    @property
    def request_load_ratio(self) -> float:
        return self.request_active_slots / max(1, self.request_total_slots)

    @classmethod
    def from_wire(cls, worker_id: int, d: dict) -> "WorkerLoad":
        return cls(worker_id=worker_id, **{
            k: d[k] for k in (
                "request_active_slots", "request_total_slots", "kv_active_blocks",
                "kv_total_blocks", "num_requests_waiting", "gpu_cache_usage_perc",
                "gpu_prefix_cache_hit_rate",
            ) if k in d
        })


@dataclass
class ProcessedEndpoints:
    """Load snapshot + aggregate stats (reference: kv_router/scoring.rs)."""

    workers: list[WorkerLoad] = field(default_factory=list)
    load_avg: float = 0.0
    load_std: float = 0.0

    @classmethod
    def new(cls, workers: Sequence[WorkerLoad]) -> "ProcessedEndpoints":
        loads = [w.kv_load_ratio for w in workers]
        if loads:
            avg = sum(loads) / len(loads)
            std = math.sqrt(sum((x - avg) ** 2 for x in loads) / len(loads))
        else:
            avg = std = 0.0
        return cls(workers=list(workers), load_avg=avg, load_std=std)


@dataclass
class KVHitRateEvent:
    worker_id: WorkerId
    isl_blocks: int
    overlap_blocks: int


def select_worker(
    endpoints: ProcessedEndpoints,
    isl_tokens: int,
    overlap: OverlapScores,
    kv_block_size: int,
    event_sink: Optional[Callable[[KVHitRateEvent], None]] = None,
) -> WorkerId:
    if not endpoints.workers:
        raise NoWorkersError("no endpoints")

    balance_mode = endpoints.load_std > BALANCE_THRESHOLD * endpoints.load_avg
    alpha = ALPHA_BALANCE if balance_mode else ALPHA_NORMAL

    best: Optional[WorkerLoad] = None
    best_cost = math.inf
    for w in endpoints.workers:
        if w.request_active_slots >= w.request_total_slots:
            continue
        if w.kv_active_blocks >= w.kv_total_blocks:
            continue
        load_deviation = w.kv_load_ratio - endpoints.load_avg
        overlap_tokens = overlap.scores.get(w.worker_id, 0) * kv_block_size
        new_tokens = max(0, isl_tokens - overlap_tokens)
        normalized_new_tokens = new_tokens / max(1, isl_tokens)
        cost = (
            alpha * load_deviation
            + (1.0 - alpha) * normalized_new_tokens
            + GAMMA * w.request_load_ratio
        )
        log.debug(
            "worker %x: dev=%.3f new=%.3f req=%.3f cost=%.4f",
            w.worker_id, load_deviation, normalized_new_tokens, w.request_load_ratio, cost,
        )
        if cost < best_cost:
            best_cost = cost
            best = w

    if best is None:
        raise AllWorkersBusyError("all workers at capacity")

    # optimistic bump until the next metrics scrape refreshes the snapshot
    best.request_active_slots += 1
    best.kv_active_blocks += max(1, isl_tokens // kv_block_size)

    if event_sink is not None:
        event_sink(
            KVHitRateEvent(
                worker_id=best.worker_id,
                isl_blocks=isl_tokens // kv_block_size,
                overlap_blocks=overlap.scores.get(best.worker_id, 0),
            )
        )
    return best.worker_id


class KvScheduler:
    """Holds the rolling load snapshot and applies select_worker."""

    def __init__(self, kv_block_size: int, event_sink: Optional[Callable[[KVHitRateEvent], None]] = None):
        self.kv_block_size = kv_block_size
        self.event_sink = event_sink
        self._endpoints = ProcessedEndpoints()

    def update_endpoints(self, workers: Sequence[WorkerLoad]) -> None:
        self._endpoints = ProcessedEndpoints.new(workers)

    @property
    def endpoints(self) -> ProcessedEndpoints:
        return self._endpoints

    def schedule(self, isl_tokens: int, overlap: OverlapScores) -> WorkerId:
        return select_worker(
            self._endpoints, isl_tokens, overlap, self.kv_block_size, self.event_sink
        )
