"""KvRouter: the KV-aware worker-selection service.

Mirrors the reference KvRouter (reference: lib/llm/src/kv_router.rs:57-143):
subscribes to the component's ``kv_events`` subject feeding the radix indexer,
keeps a load snapshot via the metrics aggregator, and schedules requests with
the cost function. Worker death (instance key deletion) removes the worker
from the index.
"""

from __future__ import annotations

import asyncio
from typing import Optional, Sequence

from dynamo_tpu.llm.kv_router.indexer import KvIndexer, RouterEvent
from dynamo_tpu.llm.kv_router.metrics_aggregator import KvMetricsAggregator
from dynamo_tpu.llm.kv_router.scheduler import KVHitRateEvent, KvScheduler, WorkerLoad
from dynamo_tpu.runtime.component import INSTANCE_PREFIX
from dynamo_tpu.utils import get_logger

log = get_logger("kv_router")

KV_HIT_RATE_SUBJECT = "kv-hit-rate"


class KvRouter:
    def __init__(
        self,
        drt,
        namespace: str,
        component: str,
        kv_block_size: int = 16,
        metrics_interval: float = 1.0,
    ):
        self.drt = drt
        self.namespace = namespace
        self.component = component
        self.kv_block_size = kv_block_size
        self.indexer = KvIndexer(kv_block_size)
        self.scheduler = KvScheduler(kv_block_size, event_sink=self._emit_hit_rate)
        self.aggregator = KvMetricsAggregator(
            drt.cplane, namespace, component, interval=metrics_interval
        )
        self.aggregator.on_update(self.scheduler.update_endpoints)
        self._watcher = None
        self._watch_task: Optional[asyncio.Task] = None

    # ---------------- lifecycle ----------------

    async def start(self) -> "KvRouter":
        subject = f"{self.namespace}|{self.component}.kv_events"
        await self.drt.cplane.subscribe(subject, self._on_kv_event)
        await self.aggregator.start()
        # instance watch: remove dead workers from the index
        prefix = f"{INSTANCE_PREFIX}/{self.namespace}/components/{self.component}/"
        self._watcher = await self.drt.cplane.kv_get_and_watch_prefix(prefix)
        self._watch_task = asyncio.create_task(self._watch_instances())
        return self

    async def stop(self) -> None:
        await self.aggregator.stop()
        if self._watch_task:
            self._watch_task.cancel()
        if self._watcher:
            try:
                await self._watcher.stop()
            except Exception:
                pass

    # ---------------- event feeds ----------------

    def _on_kv_event(self, msg: dict) -> None:
        try:
            self.indexer.apply_event(RouterEvent.from_wire(msg["payload"]))
        except Exception:
            log.exception("bad kv event")

    async def _watch_instances(self) -> None:
        try:
            async for ev in self._watcher.events():
                if ev.kind == "delete":
                    worker_id = int(ev.key.rsplit(":", 1)[1], 16)
                    log.info("worker %x gone; pruning index", worker_id)
                    self.indexer.remove_worker(worker_id)
        except asyncio.CancelledError:
            pass

    def _emit_hit_rate(self, event: KVHitRateEvent) -> None:
        asyncio.ensure_future(
            self.drt.cplane.publish(
                f"{self.namespace}.{KV_HIT_RATE_SUBJECT}",
                {
                    "worker_id": event.worker_id,
                    "isl_blocks": event.isl_blocks,
                    "overlap_blocks": event.overlap_blocks,
                },
            )
        )

    # ---------------- scheduling ----------------

    async def schedule(self, token_ids: Sequence[int]) -> int:
        """Pick the best worker for these prompt tokens
        (reference: kv_router.rs:131 schedule)."""
        overlap = self.indexer.find_matches_for_request(token_ids)
        if not self.scheduler.endpoints.workers:
            await self.aggregator.scrape_once()
        return self.scheduler.schedule(len(token_ids), overlap)

    def prefix_hit_tokens(self, token_ids: Sequence[int], worker_id: int) -> int:
        overlap = self.indexer.find_matches_for_request(token_ids)
        return overlap.scores.get(worker_id, 0) * self.kv_block_size
