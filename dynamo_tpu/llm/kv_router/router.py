"""KvRouter: the KV-aware worker-selection service.

Mirrors the reference KvRouter (reference: lib/llm/src/kv_router.rs:57-143):
subscribes to the component's ``kv_events`` subject feeding the radix indexer,
keeps a load snapshot via the metrics aggregator, and schedules requests with
the cost function. Worker death (instance key deletion) removes the worker
from the index.
"""

from __future__ import annotations

import asyncio
from typing import Optional, Sequence

from dynamo_tpu.llm.kv_router.indexer import KvIndexer, OverlapScores, RouterEvent
from dynamo_tpu.llm.kv_router.metrics_aggregator import KvMetricsAggregator
from dynamo_tpu.llm.kv_router.scheduler import KVHitRateEvent, KvScheduler
from dynamo_tpu.llm.tokens import compute_block_hash
from dynamo_tpu.runtime.component import INSTANCE_PREFIX
from dynamo_tpu.utils import get_logger

log = get_logger("kv_router")

KV_HIT_RATE_SUBJECT = "kv-hit-rate"


class KvRouter:
    def __init__(
        self,
        drt,
        namespace: str,
        component: str,
        kv_block_size: int = 16,
        metrics_interval: float = 1.0,
    ):
        self.drt = drt
        self.namespace = namespace
        self.component = component
        self.kv_block_size = kv_block_size
        self.indexer = KvIndexer(kv_block_size)
        self.scheduler = KvScheduler(kv_block_size, event_sink=self._emit_hit_rate)
        self.aggregator = KvMetricsAggregator(
            drt.cplane, namespace, component, interval=metrics_interval
        )
        self.aggregator.on_update(self._on_loads)
        # workers already pruned from the radix for being unservable: prune
        # once per transition, not every scrape round
        self._pruned_unservable: set[int] = set()
        self._watcher = None
        self._watch_task: Optional[asyncio.Task] = None
        # one-entry overlap memo: schedule() and the callers that want the
        # same prompt's prefix-hit/remote-holder view right after it used to
        # each walk the radix tree again — cache the OverlapScores keyed by a
        # cheap prompt fingerprint and reuse it
        self._last_overlap: Optional[tuple[tuple[int, int], OverlapScores]] = None

    # ---------------- lifecycle ----------------

    async def start(self) -> "KvRouter":
        subject = f"{self.namespace}|{self.component}.kv_events"
        await self.drt.cplane.subscribe(subject, self._on_kv_event)
        await self.aggregator.start()
        # instance watch: remove dead workers from the index
        prefix = f"{INSTANCE_PREFIX}/{self.namespace}/components/{self.component}/"
        self._watcher = await self.drt.cplane.kv_get_and_watch_prefix(prefix)
        self._watch_task = asyncio.create_task(self._watch_instances())
        return self

    async def stop(self) -> None:
        await self.aggregator.stop()
        if self._watch_task:
            self._watch_task.cancel()
        if self._watcher:
            try:
                await self._watcher.stop()
            except Exception:
                pass

    # ---------------- event feeds ----------------

    def _on_kv_event(self, msg: dict) -> None:
        try:
            self.indexer.apply_event(RouterEvent.from_wire(msg["payload"]))
            # the tree changed: the overlap memo is only exact while it hasn't
            self._last_overlap = None
        except Exception:
            log.exception("bad kv event")

    async def _watch_instances(self) -> None:
        try:
            async for ev in self._watcher.events():
                if ev.kind == "delete":
                    worker_id = int(ev.key.rsplit(":", 1)[1], 16)
                    log.info("worker %x gone; pruning index", worker_id)
                    self.indexer.remove_worker(worker_id)
                    self._last_overlap = None
        except asyncio.CancelledError:
            pass

    def _on_loads(self, loads) -> None:
        """Scrape-round hook: feed the scheduler its endpoint view, then make
        the radix index FOLLOW migrating sequences — a worker that reports
        draining/migrating/dead stops being a prefix holder immediately, so
        new placements (and fleet pulls) land on the peers its sequences are
        moving to. The destinations' own ``stored`` KV events re-advertise
        the migrated blocks there; a pruned worker that later returns to
        ready re-advertises as it re-caches."""
        self.scheduler.update_endpoints(loads)
        for view in self.aggregator.worker_views():
            wid = view.instance_id
            if not view.servable:
                if wid not in self._pruned_unservable:
                    log.info("worker %x unservable; pruning radix index", wid)
                    self.indexer.remove_worker(wid)
                    self._last_overlap = None
                    self._pruned_unservable.add(wid)
            else:
                self._pruned_unservable.discard(wid)

    def _emit_hit_rate(self, event: KVHitRateEvent) -> None:
        asyncio.ensure_future(
            self.drt.cplane.publish(
                f"{self.namespace}.{KV_HIT_RATE_SUBJECT}",
                {
                    "worker_id": event.worker_id,
                    "isl_blocks": event.isl_blocks,
                    "overlap_blocks": event.overlap_blocks,
                    # index health rides along so the metrics plane sees
                    # resident nodes/bytes/evictions without a second subject
                    "radix": self.indexer.radix_stats(),
                },
            )
        )

    # ---------------- scheduling ----------------

    def _overlap_key(self, token_ids: Sequence[int], salt: int = 0) -> tuple[int, int, int, int]:
        # the indexer generation makes the memo eviction-truthful: any
        # structural deletion (LRU eviction, removed-event prune,
        # remove_worker) bumps it, so a memoized score for a now-evicted
        # subtree can never be returned — even when the deletion happened
        # outside the explicit invalidation sites below
        return (len(token_ids), compute_block_hash(token_ids), salt, self.indexer.generation)

    def _find_overlap(self, token_ids: Sequence[int], salt: int = 0) -> OverlapScores:
        """Radix walk with a one-entry memo: back-to-back calls for the same
        prompt (schedule -> prefix_hit_tokens / remote-holder selection)
        reuse ONE tree walk instead of recomputing it. ``salt`` = the
        request's LoRA adapter uid (0 = base): it keys the memo AND the walk,
        so an adapter's overlap never reads another adapter's blocks."""
        key = self._overlap_key(token_ids, salt)
        if self._last_overlap is not None and self._last_overlap[0] == key:
            return self._last_overlap[1]
        overlap = self.indexer.find_matches_for_request(token_ids, salt=salt)
        self._last_overlap = (key, overlap)
        return overlap

    async def schedule(self, token_ids: Sequence[int], salt: int = 0) -> int:
        """Pick the best worker for these prompt tokens
        (reference: kv_router.rs:131 schedule)."""
        worker_id, _ = await self.schedule_with_overlap(token_ids, salt=salt)
        return worker_id

    async def schedule_with_overlap(
        self, token_ids: Sequence[int], salt: int = 0
    ) -> tuple[int, OverlapScores]:
        """schedule() that also returns the OverlapScores the decision used,
        so callers can derive prefix-hit and remote-holder metadata without a
        second radix walk."""
        overlap = self._find_overlap(token_ids, salt)
        if not self.scheduler.endpoints.workers:
            await self.aggregator.scrape_once()
        return self.scheduler.schedule(len(token_ids), overlap), overlap

    def prefix_hit_tokens(
        self,
        token_ids: Sequence[int],
        worker_id: int,
        overlap: Optional[OverlapScores] = None,
        salt: int = 0,
    ) -> int:
        overlap = overlap if overlap is not None else self._find_overlap(token_ids, salt)
        return overlap.scores.get(worker_id, 0) * self.kv_block_size

    # ---------------- fleet-wide prefix cache ----------------

    def best_remote_holder(
        self,
        overlap: OverlapScores,
        chosen_worker: int,
        min_advantage_blocks: int = 1,
    ) -> Optional[tuple[int, int]]:
        """The peer whose cached prefix most exceeds the chosen worker's —
        the pull target for a placement miss. Returns (holder_worker_id,
        holder_blocks) or None when no peer clears the advantage bar."""
        local = overlap.scores.get(chosen_worker, 0)
        best: Optional[tuple[int, int]] = None
        for wid, blocks in overlap.scores.items():
            if wid == chosen_worker:
                continue
            if best is None or blocks > best[1]:
                best = (wid, blocks)
        if best is None or best[1] - local < max(1, min_advantage_blocks):
            return None
        return best

    def pull_address(self, worker_id: int) -> str:
        """The holder's KV pull-server address, from its stats broadcast
        (workers advertise it under ``kv_pull.address``). Empty when the
        worker is unknown, unservable, or runs without a pull server."""
        data = self.aggregator.raw_for(worker_id)
        if not data:
            return ""
        kv_pull = data.get("kv_pull")
        if not isinstance(kv_pull, dict):
            return ""
        return str(kv_pull.get("address", "") or "")
