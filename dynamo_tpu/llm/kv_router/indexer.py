"""Global radix/prefix tree over cached KV blocks, built solely from worker
events.

Semantics mirror the reference indexer (reference: lib/llm/src/kv_router/
indexer.rs:187-560):
  - tree children are keyed by the *unchained* tokens hash (LocalBlockHash);
    worker claims live on each node
  - a per-worker lookup table block_hash -> node allows events to attach
    children at any depth in O(1)
  - ``find_matches`` walks a sequence of local hashes accumulating
    OverlapScores {worker_id -> matched block count}, with optional early exit
    and optional frequency tracking with expiry
  - ``remove_worker`` drops a worker from every node it appears on

Beyond the reference, this tree is **bounded**: every node carries a
last-hit LRU position, node/entry counts are maintained incrementally, and
when a configured cap (``max_nodes`` / ``max_bytes``) is exceeded the
least-recently-hit *leaves* are deleted until the tree fits — parents become
evictable as their children go, so cold subtrees drain bottom-up while a hot
prefix spine survives arbitrary churn (the RadixAttention eviction order).
Eviction and ``removed``/``remove_worker`` pruning actually delete nodes (the
unbounded ancestor of this file only discarded worker ids, leaking childless
worker-less chains forever), and every structural deletion bumps a
``generation`` counter so the router's one-entry overlap memo can never
return a score for an evicted subtree.

The ``KvIndexer`` facade optionally splits the index into N independent
pure-Python shards keyed by the *first* block's tokens hash
(``shard_index``): event application and lookups touch exactly one shard,
each shard bounds independently, and — because the block hash is a seeded
xxh3 of the token bytes — the same request lands on the same shard in every
process. The native C++ tree knows neither caps nor shards, so requesting
either forces the pure-Python path.

The reference pins its Rc/RefCell tree to a dedicated single-threaded runtime;
here the tree is plain Python owned by the asyncio loop (single-threaded by
construction) — same concurrency-by-isolation property.
"""

from __future__ import annotations

import os
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Optional, Sequence

from dynamo_tpu.llm.kv_events import KvCacheEvent
from dynamo_tpu.llm.tokens import compute_block_hash_for_seq
from dynamo_tpu.utils import get_logger

log = get_logger("kv_router.indexer")

WorkerId = int

#: resident-size accounting constants: a slotted node with its two dicts and
#: an LRU slot costs ~320 bytes, each (worker claim + reverse-lookup) entry
#: ~200 bytes on CPython 3.11/x86-64. Estimates, not measurements — the cap
#: is a budget knob, not an allocator contract.
_NODE_BYTES = 320
_ENTRY_BYTES = 200

#: eviction hysteresis: when a cap trips, evict down to this fraction of it
#: so the O(resident) leaf sweep amortizes over many inserts instead of
#: firing on every stored event at the boundary
_EVICT_TO = 0.875


def shard_index(tokens_hash: int, num_shards: int) -> int:
    """Shard owning a prefix line, from its FIRST block's tokens hash. The
    hash is a seeded xxh3 of the token bytes (tokens.py XXH3_SEED), so this
    is deterministic across processes and restarts — every frontend routes
    the same request to the same shard without coordination."""
    return tokens_hash % num_shards


@dataclass
class RouterEvent:
    """A KvCacheEvent attributed to a worker (reference: indexer.rs:139)."""

    worker_id: WorkerId
    event: KvCacheEvent

    def to_wire(self) -> dict:
        return {"worker_id": self.worker_id, "event": self.event.to_wire()}

    @classmethod
    def from_wire(cls, d: dict) -> "RouterEvent":
        return cls(worker_id=d["worker_id"], event=KvCacheEvent.from_wire(d["event"]))


@dataclass
class OverlapScores:
    scores: dict[WorkerId, int] = field(default_factory=dict)
    frequencies: list[int] = field(default_factory=list)

    def update(self, workers) -> None:
        for w in workers:
            self.scores[w] = self.scores.get(w, 0) + 1


class _Node:
    # refs maps worker -> the block_hash it claims this node under (the
    # back-reference that lets eviction clear the per-worker lookup tables);
    # parent/key let pruning walk upward; recent_uses is allocated lazily —
    # only frequency-tracking trees pay for the deque
    __slots__ = ("children", "refs", "recent_uses", "parent", "key")

    def __init__(self, parent: Optional["_Node"] = None, key: int = 0):
        self.children: dict[int, _Node] = {}  # tokens_hash -> node
        self.refs: dict[WorkerId, int] = {}  # worker -> block_hash
        self.recent_uses: Optional[deque[float]] = None
        self.parent = parent
        self.key = key

    @property
    def workers(self):
        return self.refs.keys()


class RadixTree:
    def __init__(
        self,
        expiration_duration: Optional[float] = None,
        max_nodes: Optional[int] = None,
        max_bytes: Optional[int] = None,
    ):
        self.root = _Node()
        # worker -> block_hash (engine identity) -> node
        self.lookup: dict[WorkerId, dict[int, _Node]] = {}
        self.expiration_duration = expiration_duration
        self.max_nodes = max_nodes
        self.max_bytes = max_bytes
        # incremental counters — stats() must be O(1), not a health-probe tax
        self.node_count = 0
        self.entry_count = 0
        self.evictions_total = 0
        # bumped on ANY structural deletion (eviction, removed-event prune,
        # remove_worker): consumers that memoize walk results key on this
        self.generation = 0
        # last-hit LRU over every non-root node, oldest first; nodes hash by
        # identity so the OrderedDict doubles as the recency list
        self._lru: OrderedDict[_Node, None] = OrderedDict()

    @property
    def byte_count(self) -> int:
        return self.node_count * _NODE_BYTES + self.entry_count * _ENTRY_BYTES

    def stats(self) -> tuple[int, int]:
        """(indexed block entries, workers) in O(1)."""
        return (self.entry_count, len(self.lookup))

    # ---------------- matching ----------------

    def find_matches(self, sequence: Sequence[int], early_exit: bool = False) -> OverlapScores:
        scores = OverlapScores()
        current = self.root
        tracking = self.expiration_duration is not None
        now = time.monotonic() if tracking else 0.0
        lru = self._lru
        for tokens_hash in sequence:
            node = current.children.get(tokens_hash)
            if node is None:
                break
            scores.update(node.refs)
            lru.move_to_end(node)
            if tracking:
                uses = node.recent_uses
                if uses is None:
                    uses = node.recent_uses = deque()
                while uses and now - uses[0] > self.expiration_duration:
                    uses.popleft()
                scores.frequencies.append(len(uses))
                uses.append(now)
            if early_exit and len(node.refs) == 1:
                break
            current = node
        return scores

    # ---------------- event application ----------------

    def apply_event(self, event: RouterEvent) -> None:
        worker = event.worker_id
        ev = event.event
        worker_lookup = self.lookup.setdefault(worker, {})
        if ev.kind == "stored":
            if ev.parent_hash is None:
                parent = self.root
            else:
                parent = worker_lookup.get(ev.parent_hash)
                if parent is None:
                    log.debug(
                        "worker %x stored event with unknown parent %x; attaching to root",
                        worker,
                        ev.parent_hash,
                    )
                    parent = self.root
            for block in ev.blocks:
                node = parent.children.get(block.tokens_hash)
                if node is None:
                    node = _Node(parent, block.tokens_hash)
                    parent.children[block.tokens_hash] = node
                    self.node_count += 1
                    self._lru[node] = None
                else:
                    self._lru.move_to_end(node)
                old = node.refs.get(worker)
                if old is None:
                    self.entry_count += 1
                elif old != block.block_hash:
                    # re-stored under a new engine identity: retire the stale
                    # reverse-lookup entry instead of leaking it
                    worker_lookup.pop(old, None)
                node.refs[worker] = block.block_hash
                worker_lookup[block.block_hash] = node
                parent = node
            self._maybe_evict()
        elif ev.kind == "removed":
            changed = False
            for block_hash in ev.block_hashes:
                node = worker_lookup.pop(block_hash, None)
                if node is None:
                    continue
                if node.refs.get(worker) == block_hash:
                    del node.refs[worker]
                    self.entry_count -= 1
                    changed = True
                    self._prune_chain(node)
            if not worker_lookup:
                self.lookup.pop(worker, None)
            if changed:
                self.generation += 1

    def remove_worker(self, worker: WorkerId) -> None:
        table = self.lookup.pop(worker, None)
        if not table:
            return
        for node in table.values():
            if node.refs.pop(worker, None) is not None:
                self.entry_count -= 1
                self._prune_chain(node)
        self.generation += 1

    # ---------------- deletion / bounding ----------------

    def _prune_chain(self, node: _Node) -> None:
        """Delete a chain of childless, claim-less nodes bottom-up. A node
        that still has children stays even with no claims — a deeper block
        some worker still owns must stay reachable from the root."""
        while node is not self.root and not node.children and not node.refs:
            parent = node.parent
            self._unlink(node)
            node = parent

    def _unlink(self, node: _Node) -> None:
        parent = node.parent
        if parent is not None and parent.children.get(node.key) is node:
            del parent.children[node.key]
        for w, bh in node.refs.items():
            t = self.lookup.get(w)
            if t is not None and t.get(bh) is node:
                del t[bh]
                if not t:
                    del self.lookup[w]
        self.entry_count -= len(node.refs)
        node.refs.clear()
        node.parent = None
        self._lru.pop(node, None)
        self.node_count -= 1

    def _over_cap(self, slack: float = 1.0) -> bool:
        if self.max_nodes is not None and self.node_count > self.max_nodes * slack:
            return True
        if self.max_bytes is not None and self.byte_count > self.max_bytes * slack:
            return True
        return False

    def _maybe_evict(self) -> None:
        if (self.max_nodes is None and self.max_bytes is None) or not self._over_cap():
            return
        # oldest-first over the LRU, leaves only: deleting a leaf exposes its
        # parent, so cold chains drain bottom-up across passes while anything
        # recently walked by find_matches/apply_event survives
        while self._over_cap(_EVICT_TO):
            progressed = False
            for node in list(self._lru):
                if not self._over_cap(_EVICT_TO):
                    break
                if node.children:
                    continue
                self._unlink(node)
                self.evictions_total += 1
                progressed = True
            if not progressed:  # pathological all-interior tree; give up
                break
        self.generation += 1

    def radix_stats(self) -> dict:
        return {
            "nodes": self.node_count,
            "bytes": self.byte_count,
            "entries": self.entry_count,
            "workers": len(self.lookup),
            "max_nodes": self.max_nodes,
            "max_bytes": self.max_bytes,
            "evictions_total": self.evictions_total,
            "generation": self.generation,
        }


class KvIndexer:
    """Event-driven index facade (reference: indexer.rs:499 KvIndexer).

    Uses the native C++ tree (native/src/radix_tree.cc via ctypes) when built
    and no bounding/sharding/frequency tracking is requested; otherwise one
    pure-Python ``RadixTree`` per shard. Caps and shard count default from
    DYNTPU_ROUTER_RADIX_{MAX_NODES,MAX_BYTES,SHARDS} (0/unset = unbounded,
    single shard — the historical behavior). Lookup hit/miss accounting lives
    here so both backends price the same way.
    """

    def __init__(
        self,
        kv_block_size: int,
        expiration_duration: Optional[float] = None,
        use_native: Optional[bool] = None,
        max_nodes: Optional[int] = None,
        max_bytes: Optional[int] = None,
        num_shards: Optional[int] = None,
    ):
        self.kv_block_size = kv_block_size
        env = os.environ
        if max_nodes is None:
            max_nodes = int(env.get("DYNTPU_ROUTER_RADIX_MAX_NODES", "0") or 0) or None
        if max_bytes is None:
            max_bytes = int(env.get("DYNTPU_ROUTER_RADIX_MAX_BYTES", "0") or 0) or None
        if num_shards is None:
            num_shards = max(1, int(env.get("DYNTPU_ROUTER_RADIX_SHARDS", "1") or 1))
        bounded = max_nodes is not None or max_bytes is not None
        if use_native is None:
            use_native = (
                expiration_duration is None
                and not bounded
                and num_shards == 1
                and self._native_available()
            )
        self.lookups_total = 0
        self.hits_total = 0
        if use_native:
            from dynamo_tpu.llm.kv_router.native_indexer import NativeRadixTree

            self.shards: list = [NativeRadixTree()]
        else:
            per_nodes = max(1, max_nodes // num_shards) if max_nodes else None
            per_bytes = max(1, max_bytes // num_shards) if max_bytes else None
            self.shards = [
                RadixTree(expiration_duration, max_nodes=per_nodes, max_bytes=per_bytes)
                for _ in range(num_shards)
            ]
        self.num_shards = len(self.shards)

    @property
    def tree(self):
        """Back-compat single-tree view (tests/tools reach for ``.tree``)."""
        return self.shards[0]

    @property
    def generation(self) -> int:
        """Sum of shard generations: changes whenever ANY shard deleted
        nodes, so memoized walk results can be keyed eviction-truthfully.
        The native tree never evicts and reports no generation (0)."""
        return sum(getattr(t, "generation", 0) for t in self.shards)

    @staticmethod
    def _native_available() -> bool:
        try:
            from dynamo_tpu.llm.kv_router.native_indexer import native_available

            return native_available()
        except Exception:
            return False

    def _shard_for(self, tokens_hash: int):
        return self.shards[shard_index(tokens_hash, self.num_shards)]

    def _shard_holding(self, worker: WorkerId, block_hash: int):
        """The shard whose per-worker lookup knows this engine block hash
        (O(shards); shard counts are single-digit)."""
        for t in self.shards:
            if block_hash in t.lookup.get(worker, {}):
                return t
        return None

    def stats(self) -> tuple[int, int]:
        """(approx indexed blocks, workers) — emptiness/health probe, O(1)
        per shard via the incremental counters."""
        if self.num_shards == 1:
            return self.shards[0].stats()
        entries = 0
        workers: set[WorkerId] = set()
        for t in self.shards:
            entries += t.entry_count
            workers.update(t.lookup)
        return (entries, len(workers))

    def apply_event(self, event: RouterEvent) -> None:
        if self.num_shards == 1:
            self.shards[0].apply_event(event)
            return
        ev = event.event
        if ev.kind == "stored":
            if ev.parent_hash is not None:
                shard = self._shard_holding(event.worker_id, ev.parent_hash)
                if shard is not None:
                    shard.apply_event(event)
                    return
                # unknown parent: fall through to first-block routing; the
                # owning shard logs the root-attach exactly like before
            if ev.blocks:
                self._shard_for(ev.blocks[0].tokens_hash).apply_event(event)
        elif ev.kind == "removed":
            # a removed batch may span shards (chains split at eviction
            # boundaries); group the hashes by owning shard
            by_shard: dict[int, tuple] = {}
            for bh in ev.block_hashes:
                shard = self._shard_holding(event.worker_id, bh)
                if shard is None:
                    continue
                by_shard.setdefault(id(shard), (shard, []))[1].append(bh)
            for shard, hashes in by_shard.values():
                shard.apply_event(
                    RouterEvent(
                        worker_id=event.worker_id,
                        event=KvCacheEvent(
                            event_id=ev.event_id, kind="removed", block_hashes=tuple(hashes)
                        ),
                    )
                )

    def remove_worker(self, worker: WorkerId) -> None:
        for t in self.shards:
            t.remove_worker(worker)

    def find_matches(self, sequence: Sequence[int], early_exit: bool = False) -> OverlapScores:
        self.lookups_total += 1
        if not sequence:
            return OverlapScores()
        tree = self.shards[0] if self.num_shards == 1 else self._shard_for(sequence[0])
        scores = tree.find_matches(sequence, early_exit)
        if scores.scores:
            self.hits_total += 1
        return scores

    def find_matches_for_request(
        self, token_ids: Sequence[int], early_exit: bool = False, salt: int = 0
    ) -> OverlapScores:
        """Token ids -> local block hashes -> radix walk
        (reference: indexer.rs:648 find_matches_for_request). ``salt`` (LoRA
        adapter uid) folds into the first chunk hash exactly like the engine
        side does, so adapter-specific prefix lines diverge at the radix
        root and never cross-match another adapter's (or the base model's)
        cached blocks."""
        hashes = compute_block_hash_for_seq(token_ids, self.kv_block_size, salt)
        return self.find_matches(hashes, early_exit)

    def radix_stats(self) -> dict:
        """Aggregated index health across shards — the payload the router
        piggybacks on its hit-rate broadcast and dynotop/Prometheus render."""
        nodes = nbytes = entries = evictions = generation = workers = 0
        max_nodes = max_bytes = 0
        per_worker: dict[str, int] = {}
        for t in self.shards:
            if isinstance(t, RadixTree):
                s = t.radix_stats()
                nodes += s["nodes"]
                nbytes += s["bytes"]
                entries += s["entries"]
                evictions += s["evictions_total"]
                generation += s["generation"]
                max_nodes += s["max_nodes"] or 0
                max_bytes += s["max_bytes"] or 0
                for w, table in t.lookup.items():
                    key = f"{w:x}"
                    per_worker[key] = per_worker.get(key, 0) + len(table)
            else:  # native: (nodes, workers) only; bytes are estimated
                n, w = t.stats()
                nodes += n
                entries += n
                workers += w
                nbytes += n * (_NODE_BYTES + _ENTRY_BYTES)
        if per_worker:
            workers = len(per_worker)
        return {
            "nodes": nodes,
            "workers": workers,
            "bytes": nbytes,
            "entries": entries,
            "max_nodes": max_nodes or None,
            "max_bytes": max_bytes or None,
            "evictions_total": evictions,
            "hits_total": self.hits_total,
            "lookups_total": self.lookups_total,
            "shards": self.num_shards,
            "generation": generation,
            "per_worker": per_worker,
        }


def render_radix_metrics(stats: dict, namespace: str = "", component: str = "") -> str:
    """The ``dynamo_router_radix_*`` exposition block from a
    ``KvIndexer.radix_stats()`` dict (possibly relayed over the hit-rate
    subject). The single emitting site for these families — callers
    (components.metrics) compose it rather than re-spelling the names."""
    from dynamo_tpu.utils.prometheus import render_family

    base: dict = {}
    if namespace:
        base["namespace"] = namespace
    if component:
        base["component"] = component
    out = render_family(
        "dynamo_router_radix_nodes",
        "gauge",
        "Resident radix-index nodes across shards (cap: DYNTPU_ROUTER_RADIX_MAX_NODES)",
        [({**base, "shards": stats.get("shards", 1)}, int(stats.get("nodes", 0)))],
    )
    out += render_family(
        "dynamo_router_radix_bytes",
        "gauge",
        "Estimated resident bytes of the radix index (cap: DYNTPU_ROUTER_RADIX_MAX_BYTES)",
        [({**base, "shards": stats.get("shards", 1)}, int(stats.get("bytes", 0)))],
    )
    out += render_family(
        "dynamo_router_radix_evictions_total",
        "counter",
        "Radix nodes deleted by LRU eviction to stay under the configured cap",
        [(base, int(stats.get("evictions_total", 0)))],
    )
    out += render_family(
        "dynamo_router_radix_hits_total",
        "counter",
        "Radix lookups that matched at least one cached block (vs lookups_total)",
        [
            ({**base, "result": "hit"}, int(stats.get("hits_total", 0))),
            (
                {**base, "result": "miss"},
                max(0, int(stats.get("lookups_total", 0)) - int(stats.get("hits_total", 0))),
            ),
        ],
    )
    return out
