"""Global radix/prefix tree over cached KV blocks, built solely from worker
events.

Semantics mirror the reference indexer (reference: lib/llm/src/kv_router/
indexer.rs:187-560):
  - tree children are keyed by the *unchained* tokens hash (LocalBlockHash);
    worker sets live on each node
  - a per-worker lookup table block_hash -> node allows events to attach
    children at any depth in O(1)
  - ``find_matches`` walks a sequence of local hashes accumulating
    OverlapScores {worker_id -> matched block count}, with optional early exit
    and optional frequency tracking with expiry
  - ``remove_worker`` drops a worker from every node it appears on

The reference pins its Rc/RefCell tree to a dedicated single-threaded runtime;
here the tree is plain Python owned by the asyncio loop (single-threaded by
construction) — same concurrency-by-isolation property.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional, Sequence

from dynamo_tpu.llm.kv_events import KvCacheEvent
from dynamo_tpu.llm.tokens import compute_block_hash_for_seq
from dynamo_tpu.utils import get_logger

log = get_logger("kv_router.indexer")

WorkerId = int


@dataclass
class RouterEvent:
    """A KvCacheEvent attributed to a worker (reference: indexer.rs:139)."""

    worker_id: WorkerId
    event: KvCacheEvent

    def to_wire(self) -> dict:
        return {"worker_id": self.worker_id, "event": self.event.to_wire()}

    @classmethod
    def from_wire(cls, d: dict) -> "RouterEvent":
        return cls(worker_id=d["worker_id"], event=KvCacheEvent.from_wire(d["event"]))


@dataclass
class OverlapScores:
    scores: dict[WorkerId, int] = field(default_factory=dict)
    frequencies: list[int] = field(default_factory=list)

    def update(self, workers: set[WorkerId]) -> None:
        for w in workers:
            self.scores[w] = self.scores.get(w, 0) + 1


class _Node:
    __slots__ = ("children", "workers", "recent_uses")

    def __init__(self):
        self.children: dict[int, _Node] = {}  # tokens_hash -> node
        self.workers: set[WorkerId] = set()
        self.recent_uses: deque[float] = deque()


class RadixTree:
    def __init__(self, expiration_duration: Optional[float] = None):
        self.root = _Node()
        # worker -> block_hash (engine identity) -> node
        self.lookup: dict[WorkerId, dict[int, _Node]] = {}
        self.expiration_duration = expiration_duration

    # ---------------- matching ----------------

    def find_matches(self, sequence: Sequence[int], early_exit: bool = False) -> OverlapScores:
        scores = OverlapScores()
        current = self.root
        now = time.monotonic()
        for tokens_hash in sequence:
            node = current.children.get(tokens_hash)
            if node is None:
                break
            scores.update(node.workers)
            if self.expiration_duration is not None:
                while node.recent_uses and now - node.recent_uses[0] > self.expiration_duration:
                    node.recent_uses.popleft()
                scores.frequencies.append(len(node.recent_uses))
                node.recent_uses.append(now)
            if early_exit and len(node.workers) == 1:
                break
            current = node
        return scores

    # ---------------- event application ----------------

    def apply_event(self, event: RouterEvent) -> None:
        worker = event.worker_id
        ev = event.event
        worker_lookup = self.lookup.setdefault(worker, {})
        if ev.kind == "stored":
            if ev.parent_hash is None:
                parent = self.root
            else:
                parent = worker_lookup.get(ev.parent_hash)
                if parent is None:
                    log.debug(
                        "worker %x stored event with unknown parent %x; attaching to root",
                        worker,
                        ev.parent_hash,
                    )
                    parent = self.root
            for block in ev.blocks:
                node = parent.children.get(block.tokens_hash)
                if node is None:
                    node = _Node()
                    parent.children[block.tokens_hash] = node
                node.workers.add(worker)
                worker_lookup[block.block_hash] = node
                parent = node
        elif ev.kind == "removed":
            for block_hash in ev.block_hashes:
                node = worker_lookup.pop(block_hash, None)
                if node is not None:
                    node.workers.discard(worker)

    def remove_worker(self, worker: WorkerId) -> None:
        table = self.lookup.pop(worker, None)
        if not table:
            return
        for node in table.values():
            node.workers.discard(worker)


class KvIndexer:
    """Event-driven index facade (reference: indexer.rs:499 KvIndexer).

    Uses the native C++ tree (native/src/radix_tree.cc via ctypes) when built
    and frequency tracking is off; the pure-Python tree otherwise.
    """

    def __init__(
        self,
        kv_block_size: int,
        expiration_duration: Optional[float] = None,
        use_native: Optional[bool] = None,
    ):
        self.kv_block_size = kv_block_size
        if use_native is None:
            use_native = expiration_duration is None and self._native_available()
        if use_native:
            from dynamo_tpu.llm.kv_router.native_indexer import NativeRadixTree

            self.tree = NativeRadixTree()
        else:
            self.tree = RadixTree(expiration_duration)

    @staticmethod
    def _native_available() -> bool:
        try:
            from dynamo_tpu.llm.kv_router.native_indexer import native_available

            return native_available()
        except Exception:
            return False

    def stats(self) -> tuple[int, int]:
        """(approx nodes, workers) — emptiness/health probe."""
        if hasattr(self.tree, "stats"):
            return self.tree.stats()
        tree = self.tree
        return (sum(len(d) for d in tree.lookup.values()), len(tree.lookup))

    def apply_event(self, event: RouterEvent) -> None:
        self.tree.apply_event(event)

    def remove_worker(self, worker: WorkerId) -> None:
        self.tree.remove_worker(worker)

    def find_matches(self, sequence: Sequence[int], early_exit: bool = False) -> OverlapScores:
        return self.tree.find_matches(sequence, early_exit)

    def find_matches_for_request(
        self, token_ids: Sequence[int], early_exit: bool = False, salt: int = 0
    ) -> OverlapScores:
        """Token ids -> local block hashes -> radix walk
        (reference: indexer.rs:648 find_matches_for_request). ``salt`` (LoRA
        adapter uid) folds into the first chunk hash exactly like the engine
        side does, so adapter-specific prefix lines diverge at the radix
        root and never cross-match another adapter's (or the base model's)
        cached blocks."""
        hashes = compute_block_hash_for_seq(token_ids, self.kv_block_size, salt)
        return self.find_matches(hashes, early_exit)
