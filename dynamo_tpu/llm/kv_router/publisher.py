"""Worker-side publishers: KV cache events + load metrics.

Mirrors the reference publisher pair (reference: lib/llm/src/kv_router/
publisher.rs:33-130): KvEventPublisher forwards engine block store/evict events
onto the component's ``kv_events`` subject; KvMetricsPublisher exposes
ForwardPassMetrics through the endpoint's stats handler so the aggregator's
$SRV.STATS scrape picks them up.
"""

from __future__ import annotations

import asyncio
from typing import Callable, Optional

from dynamo_tpu.llm.kv_events import KvCacheEvent
from dynamo_tpu.llm.kv_router.indexer import RouterEvent
from dynamo_tpu.utils import get_logger

log = get_logger("kv_router.publisher")


class KvEventPublisher:
    """Bridges engine KV events (any thread) onto the cplane subject."""

    def __init__(self, cplane, subject: str, worker_id: int, loop: Optional[asyncio.AbstractEventLoop] = None):
        self.cplane = cplane
        self.subject = subject
        self.worker_id = worker_id
        self._loop = loop or asyncio.get_event_loop()

    def publish(self, event: KvCacheEvent) -> None:
        """Thread-safe fire-and-forget publish (engine thread calls this)."""
        wire = RouterEvent(worker_id=self.worker_id, event=event).to_wire()

        def _go() -> None:
            asyncio.ensure_future(self.cplane.publish(self.subject, wire))

        self._loop.call_soon_threadsafe(_go)

    # direct coroutine form for same-loop callers
    async def publish_async(self, event: KvCacheEvent) -> None:
        wire = RouterEvent(worker_id=self.worker_id, event=event).to_wire()
        await self.cplane.publish(self.subject, wire)


class KvMetricsPublisher:
    """Holds the latest ForwardPassMetrics; plugs into the endpoint stats
    handler (reference: publisher.rs:76 create_endpoint w/ stats handler)."""

    def __init__(self, metrics_fn: Callable[[], dict]):
        self.metrics_fn = metrics_fn

    def stats_handler(self) -> dict:
        try:
            return {"kv_metrics": self.metrics_fn()}
        except Exception:
            log.exception("metrics_fn failed")
            return {}
