"""OpenAI request -> tokenized PreprocessedRequest.

Mirrors the reference preprocessor (reference: lib/llm/src/preprocessor.rs:63-200,
preprocessor/prompt/): renders the chat template (tokenizer-owned jinja),
tokenizes, applies model defaults, maps sampling options, and surfaces
``formatted_prompt`` / ``token_ids`` annotations when requested via ext.
"""

from __future__ import annotations

import uuid
from typing import Optional

from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.llm.protocols.common import PreprocessedRequest
from dynamo_tpu.llm.protocols.openai import (
    ChatCompletionRequest,
    CompletionRequest,
    ContextLengthError,
    ProtocolError,
)
from dynamo_tpu.llm.tokenizer import Tokenizer

ANNOTATION_FORMATTED_PROMPT = "formatted_prompt"
ANNOTATION_TOKEN_IDS = "token_ids"


class OpenAIPreprocessor:
    def __init__(
        self,
        tokenizer: Tokenizer,
        model_name: str,
        max_model_len: int = 2048,
        default_max_tokens: Optional[int] = None,
        default_temperature: float = 1.0,
        mm: Optional[dict] = None,  # model card mm block (multimodal models)
        media_root: Optional[str] = None,  # allowlisted root for file image paths
    ):
        import os

        self.tokenizer = tokenizer
        self.model_name = model_name
        self.max_model_len = max_model_len
        self.default_max_tokens = default_max_tokens
        self.default_temperature = default_temperature
        self.mm = mm
        self.media_root = media_root or os.environ.get("DYNTPU_MEDIA_ROOT")

    # ---------------- internals ----------------

    def _sampling(self, req, prompt_len: int) -> SamplingParams:
        temperature = req.temperature
        if temperature is None:
            temperature = self.default_temperature
        if req.ext.greedy:
            temperature = 0.0
        budget = max(1, self.max_model_len - prompt_len)
        max_tokens = req.max_tokens
        if max_tokens is None:
            max_tokens = self.default_max_tokens or budget
        max_tokens = min(max_tokens, budget)
        return SamplingParams(
            temperature=float(temperature),
            top_k=int(req.ext.top_k or 0),
            top_p=float(req.top_p if req.top_p is not None else 1.0),
            min_p=float(req.min_p or 0.0),
            max_tokens=int(max_tokens),
            min_tokens=int(req.min_tokens or 0),
            stop=tuple(req.stop),
            seed=req.seed,
            ignore_eos=req.ext.ignore_eos,
            presence_penalty=float(req.presence_penalty or 0.0),
            frequency_penalty=float(req.frequency_penalty or 0.0),
            repetition_penalty=float(
                req.repetition_penalty if req.repetition_penalty is not None else 1.0
            ),
        )

    def _build(self, req, prompt_text: str, token_ids: list[int]) -> tuple[PreprocessedRequest, dict]:
        if not token_ids:
            raise ProtocolError("prompt tokenized to zero tokens")
        if len(token_ids) >= self.max_model_len:
            # a client error with the OpenAI code, mapped to a structured
            # 400 on the HTTP path (never a 500 or a mid-stream abort)
            raise ContextLengthError(
                f"prompt length {len(token_ids)} exceeds model context {self.max_model_len}"
            )
        annotations = {}
        if ANNOTATION_FORMATTED_PROMPT in req.ext.annotations:
            annotations[ANNOTATION_FORMATTED_PROMPT] = prompt_text
        if ANNOTATION_TOKEN_IDS in req.ext.annotations:
            annotations[ANNOTATION_TOKEN_IDS] = token_ids
        pre = PreprocessedRequest(
            request_id=uuid.uuid4().hex,
            token_ids=token_ids,
            sampling=self._sampling(req, len(token_ids)),
            eos_token_ids=tuple(self.tokenizer.eos_token_ids),
            stop_strings=tuple(req.stop),
            annotations=tuple(req.ext.annotations),
            model=req.model or self.model_name,
            logprobs=self._logprobs(req),
            skip_special_tokens=req.ext.skip_special_tokens,
        )
        return pre, annotations

    @staticmethod
    def _logprobs(req) -> Optional[int]:
        """OpenAI request fields -> engine logprobs count. Completions:
        ``logprobs`` is the alternatives count (0-5). Chat: ``logprobs`` is a
        bool gate and ``top_logprobs`` the count (0-20)."""
        lp = req.logprobs
        if lp is None or lp is False:
            if getattr(req, "top_logprobs", None) is not None:
                raise ProtocolError("top_logprobs requires logprobs to be true")
            return None
        if lp is True:
            return int(getattr(req, "top_logprobs", None) or 0)
        if isinstance(lp, int):
            if not 0 <= lp <= 20:
                raise ProtocolError("logprobs must be in [0, 20]")
            return lp
        raise ProtocolError("logprobs must be a boolean or integer")

    # ---------------- API ----------------

    def preprocess_chat(self, req: ChatCompletionRequest) -> tuple[PreprocessedRequest, dict]:
        # tools render into the chat template unless tool_choice forbids them
        # (reference: preprocessor/tools/request.rs ToolChoice::None)
        tools = req.tools if req.tools and req.tool_choice != "none" else None
        messages = [m.to_dict() for m in req.messages]
        images = []
        if any(isinstance(m.get("content"), list) for m in messages):
            has_images = any(
                isinstance(p, dict) and p.get("type") == "image_url"
                for m in messages
                if isinstance(m.get("content"), list)
                for p in m["content"]
            )
            if has_images and self.mm is None:
                raise ProtocolError(
                    f"model {self.model_name} does not accept image content parts"
                )
            from dynamo_tpu.llm import multimodal

            # flattens text-only part lists too (OpenAI SDKs send those for
            # plain text); any decode failure (bad base64, non-image payload,
            # degenerate shapes) is the client's fault -> protocol error
            try:
                messages, images = multimodal.extract_content_parts(
                    messages, media_root=self.media_root
                )
            except ProtocolError:
                raise
            except Exception as e:
                raise ProtocolError(f"invalid image content: {e}")
        if tools is None:
            # keep the no-tools call signature-compatible with bare tokenizers
            prompt = self.tokenizer.apply_chat_template(messages, add_generation_prompt=True)
        else:
            prompt = self.tokenizer.apply_chat_template(
                messages, add_generation_prompt=True, tools=tools
            )
        if images:
            try:
                token_ids, image_inputs = multimodal.tokenize_with_images(
                    prompt,
                    images,
                    self.tokenizer.encode,
                    patch_size=self.mm["patch_size"],
                    merge_size=self.mm["merge_size"],
                    vocab_size=self.mm["vocab_size"],
                    vision_start_id=self.mm.get("vision_start_id"),
                    vision_end_id=self.mm.get("vision_end_id"),
                )
            except Exception as e:
                raise ProtocolError(f"invalid image content: {e}")
            pre, annotations = self._build(req, prompt, token_ids)
            pre.images = image_inputs
            return pre, annotations
        token_ids = self.tokenizer.encode(prompt)
        return self._build(req, prompt, token_ids)

    def preprocess_completion(self, req: CompletionRequest) -> tuple[PreprocessedRequest, dict]:
        if isinstance(req.prompt, str):
            token_ids = self.tokenizer.encode(req.prompt)
            prompt_text = req.prompt
        elif isinstance(req.prompt, list) and all(isinstance(t, int) for t in req.prompt):
            token_ids = list(req.prompt)
            prompt_text = ""
        else:
            raise ProtocolError("prompt must be a string or a list of token ids")
        return self._build(req, prompt_text, token_ids)
