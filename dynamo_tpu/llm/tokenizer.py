"""Tokenizer wrappers + incremental streaming detokenization.

Mirrors the reference's tokenizer layer (reference: lib/llm/src/tokenizers.rs,
tokenizers/hf.rs, and the DecodeStream used by the backend, backend.rs:111).

Implementations:
  - ``HfTokenizer``: HuggingFace (transformers AutoTokenizer), incl. jinja chat
    templates from tokenizer_config.json
  - ``ByteTokenizer``: hermetic test tokenizer (utf-8 bytes + bos/eos), so the
    full serving path runs with no model files (the reference ships vendored
    tokenizer fixtures for the same reason, lib/llm/tests/data/sample-models/)
"""

from __future__ import annotations

import threading
from pathlib import Path
from typing import Optional, Protocol, Sequence

_preproc_pool = None
_preproc_lock = threading.Lock()


def preprocessing_executor():
    """Small dedicated pool for CPU-bound request preprocessing (chat-template
    render + BPE encode).

    Why not the default executor: HfTokenizer keeps one underlying tokenizer
    per THREAD (the PyO3 binding is not concurrency-safe — see HfTokenizer),
    so preprocessing on the default asyncio executor loads one duplicate
    ``AutoTokenizer.from_pretrained`` copy per executor thread it ever lands
    on (dozens of threads => dozens of multi-MB tokenizer copies and cold
    ~100ms loads mid-traffic). A 4-worker pool bounds that to 4 loads while
    still covering request-burst parallelism (encode releases the GIL).
    """
    global _preproc_pool
    if _preproc_pool is None:
        with _preproc_lock:
            if _preproc_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                _preproc_pool = ThreadPoolExecutor(
                    max_workers=4, thread_name_prefix="dyntpu-preproc"
                )
    return _preproc_pool


class Tokenizer(Protocol):
    vocab_size: int
    eos_token_ids: tuple[int, ...]

    def encode(self, text: str) -> list[int]: ...

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str: ...

    def apply_chat_template(
        self,
        messages: list[dict],
        add_generation_prompt: bool = True,
        tools: Optional[list] = None,
    ) -> str: ...


class ByteTokenizer:
    """utf-8 byte-level tokenizer: ids 0..255 bytes, 256 bos, 257 eos."""

    BOS = 256
    EOS = 257

    def __init__(self):
        self.vocab_size = 258
        self.eos_token_ids = (self.EOS,)

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        data = bytes(i for i in ids if i < 256)
        return data.decode("utf-8", errors="replace")

    def apply_chat_template(
        self,
        messages: list[dict],
        add_generation_prompt: bool = True,
        tools: Optional[list] = None,
    ) -> str:
        parts = [f"<{m['role']}>{m.get('content') or ''}</{m['role']}>" for m in messages]
        if tools:
            import json as _json

            parts.insert(0, f"<tools>{_json.dumps(tools, separators=(',', ':'))}</tools>")
        if add_generation_prompt:
            parts.append("<assistant>")
        return "\n".join(parts)


class HfTokenizer:
    """HF fast tokenizers are NOT safe for concurrent encode/template calls
    (the PyO3 binding raises "Already borrowed" when two threads touch one
    instance — huggingface/tokenizers#537), and the HTTP service runs
    preprocessing on a thread pool. Each thread therefore lazily loads its
    OWN underlying tokenizer (thread-local); vocab/eos metadata comes from
    the construction-time instance and is immutable."""

    def __init__(self, path: str):
        import threading

        self._path = path
        self._local = threading.local()
        tok = self._tok
        self.vocab_size = len(tok)
        eos = tok.eos_token_id
        ids = []
        if eos is not None:
            ids.append(eos)
        # some models define additional end ids in generation config (e.g.
        # llama-3 <|eot_id|>); include any token literally named like an end tag
        self.eos_token_ids = tuple(ids)

    @property
    def _tok(self):
        tok = getattr(self._local, "tok", None)
        if tok is None:
            from transformers import AutoTokenizer

            tok = AutoTokenizer.from_pretrained(self._path)
            self._local.tok = tok
        return tok

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text, add_special_tokens=False)

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        return self._tok.decode(ids, skip_special_tokens=skip_special_tokens)

    def apply_chat_template(
        self,
        messages: list[dict],
        add_generation_prompt: bool = True,
        tools: Optional[list] = None,
    ) -> str:
        # only forward tools when present: older transformers lack the kwarg
        kwargs = {"tools": tools} if tools is not None else {}
        return self._tok.apply_chat_template(
            messages,
            tokenize=False,
            add_generation_prompt=add_generation_prompt,
            **kwargs,
        )


def get_tokenizer(spec: str) -> Tokenizer:
    """'byte' -> ByteTokenizer; anything else -> HF from local path."""
    if spec == "byte":
        return ByteTokenizer()
    if Path(spec).exists():
        return HfTokenizer(spec)
    raise ValueError(f"unknown tokenizer spec {spec!r} (no egress: must be local)")


class DecodeStream:
    """Incremental detokenizer that never emits partial UTF-8/merge artifacts.

    Standard sliding-window scheme: decode(ids[prefix:]) vs decode(ids[prefix:read])
    and emit the suffix once it stabilizes (no trailing replacement char).
    """

    def __init__(self, tokenizer: Tokenizer, prompt_ids: Sequence[int] = (),
                 skip_special_tokens: bool = True):
        self.tokenizer = tokenizer
        self.ids: list[int] = list(prompt_ids)
        self.prefix_offset = len(self.ids)
        self.read_offset = len(self.ids)
        self.skip_special_tokens = skip_special_tokens

    def step(self, token_id: int) -> Optional[str]:
        self.ids.append(token_id)
        return self._emit_stable()

    def step_many(self, token_ids) -> Optional[str]:
        """Append a window of tokens and emit the stabilized text delta in ONE
        pair of decode calls (the per-token loop costs two tokenizer crossings
        per token; windows arrive decode_steps at a time from the engine).

        If the window's tail is mid-codepoint the whole batched delta would be
        withheld, so fall back to per-token stepping for that window — it
        emits everything that stabilizes and holds only the dangling bytes,
        exactly like the per-token path."""
        token_ids = list(token_ids)
        if not token_ids:
            return None
        if len(token_ids) == 1:
            return self.step(token_ids[0])
        mark = len(self.ids)
        self.ids.extend(token_ids)
        new_text = self.tokenizer.decode(
            self.ids[self.prefix_offset :],
            skip_special_tokens=self.skip_special_tokens,
        )
        if not new_text.endswith("�"):
            prefix_text = self.tokenizer.decode(
                self.ids[self.prefix_offset : self.read_offset],
                skip_special_tokens=self.skip_special_tokens,
            )
            if len(new_text) > len(prefix_text):
                delta = new_text[len(prefix_text) :]
                self.prefix_offset = self.read_offset
                self.read_offset = len(self.ids)
                return delta
            return None
        del self.ids[mark:]
        parts = [d for d in (self.step(t) for t in token_ids) if d]
        return "".join(parts) or None

    def _emit_stable(self) -> Optional[str]:
        prefix_text = self.tokenizer.decode(
            self.ids[self.prefix_offset : self.read_offset],
            skip_special_tokens=self.skip_special_tokens,
        )
        new_text = self.tokenizer.decode(
            self.ids[self.prefix_offset :],
            skip_special_tokens=self.skip_special_tokens,
        )
        if new_text.endswith("�"):
            return None  # mid-codepoint; wait for more tokens
        if len(new_text) > len(prefix_text):
            delta = new_text[len(prefix_text) :]
            self.prefix_offset = self.read_offset
            self.read_offset = len(self.ids)
            return delta
        return None
