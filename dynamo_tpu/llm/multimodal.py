"""Multimodal input handling: images in OpenAI chat content parts.

The reference serves vision models by delegating to its engines (vLLM et al.);
here the whole path is native. This module owns everything between an OpenAI
``image_url`` content part and the vision tower's patch arrays:

  - decoding images (base64 data URIs, local file paths under an allowlisted
    root, or ``data:application/x-npy`` raw-array URIs for hermetic tests)
  - smart-resize to patch-grid multiples with a pixel budget
  - patchify in merge-group order (the layout VisionModel.encode expects)
  - expansion of each image into its run of **virtual token ids** in the
    language sequence

Virtual token ids: every image-slot position gets a token id derived from the
image's content hash (``xxh3(image_hash || position)``, reduced into the
vocab). The embedding rows of these ids are overridden by the vision
embeddings during prefill, so their values never reach the forward math — but
they make the existing KV block hashing, prefix-cache reuse, and KV-aware
routing treat identical images as identical prefixes and different images as
different ones, with zero multimodal special-casing anywhere in that machinery.
"""

from __future__ import annotations

import base64
import io
from dataclasses import dataclass

import numpy as np
import xxhash

from dynamo_tpu.llm.tokens import XXH3_SEED

# CLIP-style normalization
IMAGE_MEAN = np.array([0.48145466, 0.4578275, 0.40821073], np.float32)
IMAGE_STD = np.array([0.26862954, 0.26130258, 0.27577711], np.float32)


@dataclass
class ImageInput:
    """One image, patchified for the vision tower, placed in the prompt.

    offset: index in token_ids where this image's virtual-token run starts.
    patches: [N, C*ps*ps] float32, merge-group order. rows/cols: [N] int32.
    num_tokens: N / merge^2 — virtual tokens this image occupies.
    """

    offset: int
    patches: np.ndarray
    rows: np.ndarray
    cols: np.ndarray
    grid: tuple[int, int]
    num_tokens: int
    content_hash: int

    def to_wire(self) -> dict:
        return {
            "offset": self.offset,
            "patches": base64.b64encode(
                self.patches.astype(np.float32).tobytes()
            ).decode(),
            "patch_dim": int(self.patches.shape[1]),
            "rows": self.rows.tolist(),
            "cols": self.cols.tolist(),
            "grid": list(self.grid),
            "num_tokens": self.num_tokens,
            "content_hash": self.content_hash,
        }

    @classmethod
    def from_wire(cls, d: dict) -> "ImageInput":
        pd = int(d["patch_dim"])
        buf = np.frombuffer(base64.b64decode(d["patches"]), np.float32)
        return cls(
            offset=int(d["offset"]),
            patches=buf.reshape(-1, pd).copy(),
            rows=np.asarray(d["rows"], np.int32),
            cols=np.asarray(d["cols"], np.int32),
            grid=(int(d["grid"][0]), int(d["grid"][1])),
            num_tokens=int(d["num_tokens"]),
            content_hash=int(d["content_hash"]),
        )


def image_content_hash(pixels: np.ndarray) -> int:
    return xxhash.xxh3_64_intdigest(
        np.ascontiguousarray(pixels, np.float32).tobytes(), seed=XXH3_SEED
    )


def virtual_token_ids(content_hash: int, num_tokens: int, vocab_size: int) -> list[int]:
    """Deterministic per-(image, position) ids inside the vocab. See module
    docstring: these exist for block hashing; embeddings are overridden."""
    return [
        xxhash.xxh3_64_intdigest(
            content_hash.to_bytes(8, "little") + j.to_bytes(4, "little"),
            seed=XXH3_SEED,
        )
        % max(1, vocab_size)
        for j in range(num_tokens)
    ]


def smart_resize(
    h: int, w: int, factor: int, min_pixels: int = 56 * 56, max_pixels: int = 14 * 14 * 4 * 1280
) -> tuple[int, int]:
    """Resize target: dimensions divisible by ``factor`` (patch * merge), area
    within [min_pixels, max_pixels], aspect ratio preserved."""
    if h <= 0 or w <= 0:
        raise ValueError(f"degenerate image size {h}x{w}")
    if max(h, w) / min(h, w) > 200:
        raise ValueError(f"absurd aspect ratio {h}x{w}")
    rh = max(factor, round(h / factor) * factor)
    rw = max(factor, round(w / factor) * factor)
    if rh * rw > max_pixels:
        beta = (h * w / max_pixels) ** 0.5
        rh = max(factor, int(h / beta / factor) * factor)
        rw = max(factor, int(w / beta / factor) * factor)
    elif rh * rw < min_pixels:
        beta = (min_pixels / (h * w)) ** 0.5
        rh = int(np.ceil(h * beta / factor)) * factor
        rw = int(np.ceil(w * beta / factor)) * factor
    return rh, rw


def load_image(url: str, root: str | None = None) -> np.ndarray:
    """Decode an image source into float32 [H, W, 3] in [0, 1].

    Supports ``data:image/*;base64,``, ``data:application/x-npy;base64,``
    (raw float array — the hermetic test path), and plain file paths (only when
    ``root`` is configured; zero-egress, so no http fetches).
    """
    if url.startswith("data:"):
        head, _, payload = url.partition(",")
        raw = base64.b64decode(payload)
        if "application/x-npy" in head:
            arr = np.load(io.BytesIO(raw), allow_pickle=False)
            return np.asarray(arr, np.float32)
        from PIL import Image

        img = Image.open(io.BytesIO(raw)).convert("RGB")
        return np.asarray(img, np.float32) / 255.0
    if url.startswith("http://") or url.startswith("https://"):
        raise ValueError("remote image URLs are not supported (zero-egress)")
    if root is None:
        raise ValueError("file image paths require a configured media root")
    import os

    path = os.path.realpath(os.path.join(root, url.lstrip("/")))
    if not path.startswith(os.path.realpath(root) + os.sep):
        raise ValueError("image path escapes the media root")
    from PIL import Image

    img = Image.open(path).convert("RGB")
    return np.asarray(img, np.float32) / 255.0


def patchify(
    pixels: np.ndarray, patch_size: int, merge_size: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray, tuple[int, int]]:
    """float32 [H, W, 3] -> (patches [N, 3*ps*ps], rows [N], cols [N], grid).

    Output order is merge-group major: for each (merged row, merged col), its
    merge^2 member patches are contiguous — VisionModel.encode's merger relies
    on this (reshape-based 2x2 concat).
    """
    factor = patch_size * merge_size
    h, w = pixels.shape[:2]
    rh, rw = smart_resize(h, w, factor)
    if (rh, rw) != (h, w):
        pixels = _resize_bilinear(pixels, rh, rw)
    pixels = (pixels - IMAGE_MEAN) / IMAGE_STD
    gh, gw = rh // patch_size, rw // patch_size
    # [gh, gw, ps, ps, C] patch grid
    grid = pixels.reshape(gh, patch_size, gw, patch_size, 3).transpose(0, 2, 1, 3, 4)
    m = merge_size
    # merge-group order: (GH, GW, m, m) leading axes
    grouped = grid.reshape(gh // m, m, gw // m, m, patch_size, patch_size, 3)
    grouped = grouped.transpose(0, 2, 1, 3, 4, 5, 6)
    patches = grouped.reshape(gh * gw, -1).astype(np.float32)
    rr, cc = np.meshgrid(np.arange(gh), np.arange(gw), indexing="ij")
    rr = rr.reshape(gh // m, m, gw // m, m).transpose(0, 2, 1, 3).reshape(-1)
    cc = cc.reshape(gh // m, m, gw // m, m).transpose(0, 2, 1, 3).reshape(-1)
    return patches, rr.astype(np.int32), cc.astype(np.int32), (gh, gw)


def _resize_bilinear(img: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    """Minimal bilinear resize (numpy; runs once per image on host)."""
    h, w = img.shape[:2]
    ys = (np.arange(out_h) + 0.5) * h / out_h - 0.5
    xs = (np.arange(out_w) + 0.5) * w / out_w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0)[:, None, None]
    wx = np.clip(xs - x0, 0.0, 1.0)[None, :, None]
    top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1] * wx
    bot = img[y1][:, x0] * (1 - wx) + img[y1][:, x1] * wx
    return (top * (1 - wy) + bot * wy).astype(np.float32)


# ---------------- chat-content parsing ----------------

_SENTINEL = "\x00dynimg:{i}\x00"


def extract_content_parts(messages: list[dict], media_root: str | None = None):
    """Flatten OpenAI content-part messages for template rendering.

    Returns (messages_with_sentinels, images) where each image content part is
    replaced by a unique sentinel string inside the message text; after
    rendering + around-sentinel tokenization the sentinels become virtual-token
    runs. images = list of float32 pixel arrays in content order.
    """
    out_messages = []
    images: list[np.ndarray] = []
    for m in messages:
        content = m.get("content")
        if isinstance(content, str) and "\x00" in content:
            # string contents must not be able to forge the image-placement
            # sentinels either (same sanitization as text parts below)
            m = dict(m, content=content.replace("\x00", ""))
            out_messages.append(m)
            continue
        if not isinstance(content, list):
            out_messages.append(m)
            continue
        pieces = []
        for part in content:
            ptype = part.get("type")
            if ptype == "text":
                # NUL never survives: user text must not be able to forge the
                # image-placement sentinels spliced in below
                pieces.append(part.get("text", "").replace("\x00", ""))
            elif ptype == "image_url":
                url = part.get("image_url")
                if isinstance(url, dict):
                    url = url.get("url", "")
                pixels = load_image(url, root=media_root)
                pieces.append(_SENTINEL.format(i=len(images)))
                images.append(pixels)
            else:
                raise ValueError(f"unsupported content part type: {ptype}")
        m2 = dict(m)
        m2["content"] = "".join(pieces)
        out_messages.append(m2)
    return out_messages, images


def tokenize_with_images(
    rendered: str,
    images: list[np.ndarray],
    encode,
    patch_size: int,
    merge_size: int,
    vocab_size: int,
    vision_start_id: int | None = None,
    vision_end_id: int | None = None,
) -> tuple[list[int], list[ImageInput]]:
    """Split the rendered prompt on image sentinels, encode text segments, and
    splice each image's virtual-token run in between. Returns (token_ids,
    image_inputs with offsets).

    When the checkpoint defines vision delimiter tokens (Qwen2-VL's
    ``<|vision_start|>`` / ``<|vision_end|>``, config.json
    ``vision_start_token_id`` / ``vision_end_token_id``), each virtual-token
    run is wrapped with them: those are real trained tokens whose embeddings
    DO reach the forward math, so real checkpoints see the prompt structure
    they were trained on. The run itself stays hash-derived virtual ids
    (embeddings overridden by vision output; the ids exist for KV block
    hashing and prefix-cache identity)."""
    token_ids: list[int] = []
    mm: list[ImageInput] = []
    cursor = 0
    for i, pixels in enumerate(images):
        sentinel = _SENTINEL.format(i=i)
        idx = rendered.find(sentinel, cursor)
        if idx < 0:
            raise ValueError(f"image {i} sentinel missing after template render")
        if idx > cursor:
            token_ids.extend(encode(rendered[cursor:idx]))
        if vision_start_id is not None:
            token_ids.append(int(vision_start_id))
        patches, rows, cols, grid = patchify(pixels, patch_size, merge_size)
        n_tokens = patches.shape[0] // (merge_size * merge_size)
        chash = image_content_hash(pixels)
        mm.append(
            ImageInput(
                offset=len(token_ids),
                patches=patches,
                rows=rows,
                cols=cols,
                grid=grid,
                num_tokens=n_tokens,
                content_hash=chash,
            )
        )
        token_ids.extend(virtual_token_ids(chash, n_tokens, vocab_size))
        if vision_end_id is not None:
            token_ids.append(int(vision_end_id))
        cursor = idx + len(sentinel)
    if cursor < len(rendered):
        token_ids.extend(encode(rendered[cursor:]))
    return token_ids, mm


def mrope_positions(
    num_tokens: int, images: list[ImageInput], merge_size: int
) -> tuple[np.ndarray, int]:
    """M-RoPE position components for a prompt (Qwen2-VL semantics).

    Text tokens advance a shared scalar p: components (p, p, p). An image's
    tokens (row-major over its merged gh' x gw' grid) get (base, base + r,
    base + c) where base is the position after the preceding text; the next
    text position is base + max(gh', gw'). Returns (positions3 [T, 3] int32,
    rope_delta) where rope_delta + seq_pos gives every component's decode-time
    rope position (generated text advances all components equally).
    """
    pos3 = np.zeros((num_tokens, 3), np.int32)
    by_offset = sorted(images, key=lambda im: im.offset)
    p = 0
    cursor = 0
    for im in by_offset:
        for i in range(cursor, im.offset):  # text run before the image
            pos3[i] = p
            p += 1
        ghm, gwm = im.grid[0] // merge_size, im.grid[1] // merge_size
        if im.num_tokens != ghm * gwm:
            raise ValueError(
                f"image at offset {im.offset}: {im.num_tokens} tokens != "
                f"merged grid {ghm}x{gwm}"
            )
        base = p
        for j in range(im.num_tokens):
            r, c = divmod(j, gwm)
            pos3[im.offset + j] = (base, base + r, base + c)
        p = base + max(ghm, gwm)
        cursor = im.offset + im.num_tokens
    for i in range(cursor, num_tokens):
        pos3[i] = p
        p += 1
    # decode continues at rope position p, p+1, ... while the sequential KV
    # position continues at num_tokens: delta aligns the two timelines
    return pos3, p - num_tokens
