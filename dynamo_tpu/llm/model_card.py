"""Model Deployment Card (MDC): the metadata bundle a worker publishes so
frontends/routers can serve its model.

Mirrors the reference MDC (reference: lib/llm/src/model_card/model.rs:94,
create.rs): model info (config.json), tokenizer kind, prompt formatter,
context length, kv block size, service slug.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Optional


def slugify(name: str) -> str:
    return re.sub(r"[^a-zA-Z0-9_.-]+", "-", name).strip("-").lower()


@dataclass
class ModelDeploymentCard:
    display_name: str
    service_name: str
    model_path: str = ""
    tokenizer: str = "byte"  # tokenizer spec for get_tokenizer()
    context_length: int = 2048
    kv_block_size: int = 16
    model_type: str = "chat"  # chat | completion | both
    architecture: str = "llama"
    revision: int = 0
    # multimodal: {"patch_size", "merge_size", "vocab_size"} when the model
    # has a vision tower (None for text-only); the preprocessor needs these to
    # patchify images and expand their virtual-token runs
    mm: Optional[dict] = None

    @classmethod
    def from_local_path(cls, path: str, name: Optional[str] = None) -> "ModelDeploymentCard":
        p = Path(path)
        display = name or p.name
        card = cls(display_name=display, service_name=slugify(display), model_path=str(p))
        cfg_file = p / "config.json"
        if cfg_file.exists():
            cfg = json.loads(cfg_file.read_text())
            card.context_length = int(
                cfg.get("max_position_embeddings", card.context_length)
            )
            archs = cfg.get("architectures") or []
            if archs:
                card.architecture = archs[0]
            vis = cfg.get("vision_config")
            if vis is not None or cfg.get("model_type") == "qwen2_vl":
                vis = vis or {}
                card.mm = {
                    "patch_size": int(vis.get("patch_size", 14)),
                    "merge_size": int(vis.get("spatial_merge_size", 2)),
                    "vocab_size": int(cfg.get("vocab_size", 1 << 30)),
                }
                # Trained vision delimiters (Qwen2-VL: <|vision_start|> /
                # <|vision_end|>): when present, the preprocessor wraps each
                # image's virtual-token run with them so real checkpoints see
                # the prompt structure they were trained on.
                for key, cfg_key in (
                    ("vision_start_id", "vision_start_token_id"),
                    ("vision_end_id", "vision_end_token_id"),
                ):
                    if cfg.get(cfg_key) is not None:
                        card.mm[key] = int(cfg[cfg_key])
        if (p / "tokenizer.json").exists() or (p / "tokenizer_config.json").exists():
            card.tokenizer = str(p)
        return card

    @classmethod
    def for_tiny(cls, name: str = "tiny") -> "ModelDeploymentCard":
        card = cls(
            display_name=name,
            service_name=slugify(name),
            model_path=name,
            tokenizer="byte",
            context_length=64,
            kv_block_size=4,
        )
        if name.startswith("tiny-vl"):
            # VisionConfig.tiny + LlamaConfig.tiny geometry
            card.context_length = 256
            card.mm = {"patch_size": 4, "merge_size": 2, "vocab_size": 256}
        return card

    def to_wire(self) -> dict:
        return self.__dict__.copy()

    @classmethod
    def from_wire(cls, d: dict) -> "ModelDeploymentCard":
        return cls(**{k: v for k, v in d.items() if k in cls.__dataclass_fields__})
