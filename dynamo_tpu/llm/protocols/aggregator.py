"""Stream -> unary aggregation: folds chat/completion chunks into a full
response object for ``stream=false`` clients.

Mirrors the reference aggregators (reference: lib/llm/src/protocols/openai/
chat_completions/aggregator.rs:1-462): the service always streams internally
and aggregates at the edge.
"""

from __future__ import annotations

from typing import AsyncIterator, Optional


def _base_from_chunk(chunk: dict, object_name: str) -> dict:
    return {
        "id": chunk.get("id"),
        "object": object_name,
        "created": chunk.get("created"),
        "model": chunk.get("model"),
        "choices": [],
    }


async def aggregate_chat_stream(chunks: AsyncIterator[dict]) -> dict:
    """Fold chat.completion.chunk dicts into one chat.completion response.

    Tool-call deltas merge by ``index``: OpenAI streams fragment one call
    across many chunks (id/name arrive once, function.arguments in pieces)."""
    out: Optional[dict] = None
    content: list[str] = []
    calls_by_index: dict[int, dict] = {}
    role = "assistant"
    finish_reason = None
    usage = None
    lp_content: list[dict] = []
    async for chunk in chunks:
        if "__event__" in chunk:
            continue  # annotation/timing events don't aggregate
        if out is None:
            out = _base_from_chunk(chunk, "chat.completion")
        for choice in chunk.get("choices", []):
            if choice.get("logprobs"):
                lp_content.extend(choice["logprobs"].get("content") or [])
            delta = choice.get("delta") or {}
            if delta.get("role"):
                role = delta["role"]
            if delta.get("content"):
                content.append(delta["content"])
            for frag in delta.get("tool_calls") or []:
                idx = frag.get("index", 0)
                call = calls_by_index.setdefault(
                    idx, {"id": None, "type": "function", "function": {"name": None, "arguments": ""}}
                )
                if frag.get("id"):
                    call["id"] = frag["id"]
                if frag.get("type"):
                    call["type"] = frag["type"]
                fn = frag.get("function") or {}
                if fn.get("name"):
                    call["function"]["name"] = fn["name"]
                if fn.get("arguments"):
                    call["function"]["arguments"] += fn["arguments"]
            if choice.get("finish_reason"):
                finish_reason = choice["finish_reason"]
        if chunk.get("usage"):
            usage = chunk["usage"]
    if out is None:
        raise ValueError("empty stream")
    message: dict = {"role": role, "content": "".join(content)}
    if calls_by_index:
        message["tool_calls"] = [calls_by_index[i] for i in sorted(calls_by_index)]
        if not message["content"]:
            message["content"] = None
    choice = {"index": 0, "message": message, "finish_reason": finish_reason}
    if lp_content:
        choice["logprobs"] = {"content": lp_content}
    out["choices"] = [choice]
    if usage:
        out["usage"] = usage
    return out


async def aggregate_completion_stream(chunks: AsyncIterator[dict]) -> dict:
    out: Optional[dict] = None
    text: list[str] = []
    finish_reason = None
    usage = None
    lp = {"tokens": [], "token_logprobs": [], "top_logprobs": [], "text_offset": []}
    async for chunk in chunks:
        if "__event__" in chunk:
            continue  # annotation/timing events don't aggregate
        if out is None:
            out = _base_from_chunk(chunk, "text_completion")
        for choice in chunk.get("choices", []):
            if choice.get("text"):
                text.append(choice["text"])
            if choice.get("logprobs"):
                for k in lp:
                    lp[k].extend(choice["logprobs"].get(k) or [])
            if choice.get("finish_reason"):
                finish_reason = choice["finish_reason"]
        if chunk.get("usage"):
            usage = chunk["usage"]
    if out is None:
        raise ValueError("empty stream")
    out["choices"] = [
        {"index": 0, "text": "".join(text), "finish_reason": finish_reason,
         "logprobs": lp if lp["tokens"] else None}
    ]
    if usage:
        out["usage"] = usage
    return out
