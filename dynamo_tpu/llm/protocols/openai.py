"""OpenAI-compatible protocol types: chat completions + completions.

Mirrors the reference's protocol surface (reference: lib/llm/src/protocols/openai/
chat_completions.rs, completions.rs, and the `nvext` extension) as plain Python
dataclasses with dict (de)serialization. The extension field is ``ext``
(accepted under both ``ext`` and ``nvext`` for wire compat): ignore_eos,
greed-sampling knobs, annotations.
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Optional


class ProtocolError(ValueError):
    """400-level request validation error. ``code`` (when set) rides the
    OpenAI error envelope as ``error.code`` so clients can match on it."""

    code: Optional[str] = None


class ContextLengthError(ProtocolError):
    """Prompt exceeds the model's context window — the OpenAI
    ``context_length_exceeded`` client error (a structured 400, never a
    500/stream abort: the check runs before any stream starts)."""

    code = "context_length_exceeded"


@dataclass
class ChatMessage:
    role: str
    content: str | list | None = None
    name: Optional[str] = None
    # multi-turn tool use: assistant turns carry tool_calls, tool-result
    # turns (role "tool") carry the tool_call_id they answer
    tool_calls: Optional[list] = None
    tool_call_id: Optional[str] = None

    @classmethod
    def from_dict(cls, d: dict) -> "ChatMessage":
        if not isinstance(d, dict) or "role" not in d:
            raise ProtocolError("message must be an object with a 'role'")
        return cls(
            role=d["role"],
            content=d.get("content"),
            name=d.get("name"),
            tool_calls=d.get("tool_calls"),
            tool_call_id=d.get("tool_call_id"),
        )

    def to_dict(self) -> dict:
        out = {"role": self.role, "content": self.content}
        if self.name:
            out["name"] = self.name
        if self.tool_calls:
            out["tool_calls"] = self.tool_calls
        if self.tool_call_id:
            out["tool_call_id"] = self.tool_call_id
        return out


@dataclass
class Ext:
    """Extension options (analogue of the reference's nvext)."""

    ignore_eos: bool = False
    top_k: int = 0
    annotations: list[str] = field(default_factory=list)
    greedy: bool = False
    # output option (reference: common.rs OutputOptions.skip_special_tokens)
    skip_special_tokens: bool = True

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "Ext":
        if not d:
            return cls()
        return cls(
            ignore_eos=bool(d.get("ignore_eos", False)),
            top_k=int(d.get("top_k", 0)),
            annotations=list(d.get("annotations", [])),
            greedy=bool(d.get("greedy", False)),
            skip_special_tokens=bool(d.get("skip_special_tokens", True)),
        )


def _common_fields(d: dict) -> dict:
    def positive(name, val, maxv=None):
        if val is not None:
            if not isinstance(val, (int, float)) or val < 0:
                raise ProtocolError(f"{name} must be a non-negative number")
            if maxv is not None and val > maxv:
                raise ProtocolError(f"{name} must be <= {maxv}")
        return val

    stop = d.get("stop")
    if stop is None:
        stop = []
    elif isinstance(stop, str):
        stop = [stop]
    elif isinstance(stop, list):
        if not all(isinstance(s, str) for s in stop):
            raise ProtocolError("stop must be a string or list of strings")
    else:
        raise ProtocolError("stop must be a string or list of strings")

    def bounded(name, val, lo, hi):
        if val is not None:
            if not isinstance(val, (int, float)) or not lo <= val <= hi:
                raise ProtocolError(f"{name} must be a number in [{lo}, {hi}]")
        return val

    return dict(
        model=d.get("model"),
        stream=bool(d.get("stream", False)),
        max_tokens=d.get("max_completion_tokens", d.get("max_tokens")),
        temperature=positive("temperature", d.get("temperature"), 2.0),
        top_p=positive("top_p", d.get("top_p"), 1.0),
        seed=d.get("seed"),
        stop=stop,
        n=int(d.get("n", 1)),
        logprobs=d.get("logprobs"),
        user=d.get("user"),
        # OpenAI penalties + common sampling extensions (vLLM-compatible
        # top-level names; the reference's SamplingOptions carries the same
        # set — common.rs presence/frequency/repetition/min_p/seed)
        presence_penalty=bounded("presence_penalty", d.get("presence_penalty"), -2.0, 2.0),
        frequency_penalty=bounded("frequency_penalty", d.get("frequency_penalty"), -2.0, 2.0),
        repetition_penalty=bounded("repetition_penalty", d.get("repetition_penalty"), 0.01, 10.0),
        min_p=bounded("min_p", d.get("min_p"), 0.0, 1.0),
        min_tokens=positive("min_tokens", d.get("min_tokens")),
        ext=Ext.from_dict(d.get("ext") or d.get("nvext")),
    )


@dataclass
class ChatCompletionRequest:
    messages: list[ChatMessage]
    model: Optional[str] = None
    stream: bool = False
    max_tokens: Optional[int] = None
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    seed: Optional[int] = None
    stop: list[str] = field(default_factory=list)
    n: int = 1
    logprobs: Any = None
    top_logprobs: Optional[int] = None
    user: Optional[str] = None
    presence_penalty: Optional[float] = None
    frequency_penalty: Optional[float] = None
    repetition_penalty: Optional[float] = None
    min_p: Optional[float] = None
    min_tokens: Optional[int] = None
    ext: Ext = field(default_factory=Ext)
    tools: Optional[list] = None
    tool_choice: Any = None  # None|"none"|"auto"|"required"|{"type":"function",...}

    @classmethod
    def from_dict(cls, d: dict) -> "ChatCompletionRequest":
        msgs = d.get("messages")
        if not isinstance(msgs, list) or not msgs:
            raise ProtocolError("messages must be a non-empty array")
        common = _common_fields(d)
        if common["n"] != 1:
            raise ProtocolError("n > 1 is not supported")
        top_lp = d.get("top_logprobs")
        if top_lp is not None and (not isinstance(top_lp, int) or not 0 <= top_lp <= 20):
            raise ProtocolError("top_logprobs must be an integer in [0, 20]")
        return cls(
            messages=[ChatMessage.from_dict(m) for m in msgs],
            tools=d.get("tools"),
            tool_choice=d.get("tool_choice"),
            top_logprobs=top_lp,
            **common,
        )


@dataclass
class CompletionRequest:
    prompt: str | list
    model: Optional[str] = None
    stream: bool = False
    max_tokens: Optional[int] = None
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    seed: Optional[int] = None
    stop: list[str] = field(default_factory=list)
    n: int = 1
    logprobs: Any = None
    user: Optional[str] = None
    presence_penalty: Optional[float] = None
    frequency_penalty: Optional[float] = None
    repetition_penalty: Optional[float] = None
    min_p: Optional[float] = None
    min_tokens: Optional[int] = None
    ext: Ext = field(default_factory=Ext)
    echo: bool = False

    @classmethod
    def from_dict(cls, d: dict) -> "CompletionRequest":
        prompt = d.get("prompt")
        if prompt is None:
            raise ProtocolError("prompt is required")
        common = _common_fields(d)
        if common["n"] != 1:
            raise ProtocolError("n > 1 is not supported")
        return cls(prompt=prompt, echo=bool(d.get("echo", False)), **common)


# ---------------------------------------------------------------- responses


def _now() -> int:
    return int(time.time())


def new_id(prefix: str) -> str:
    return f"{prefix}-{uuid.uuid4().hex[:24]}"


@dataclass
class Usage:
    prompt_tokens: int = 0
    completion_tokens: int = 0

    def to_dict(self) -> dict:
        return {
            "prompt_tokens": self.prompt_tokens,
            "completion_tokens": self.completion_tokens,
            "total_tokens": self.prompt_tokens + self.completion_tokens,
        }


def _chat_logprob(entry: dict) -> dict:
    """Backend logprobs entry -> chat-API content entry."""
    out = {
        "token": entry["token"],
        "logprob": entry["logprob"],
        "bytes": entry.get("bytes"),
        "top_logprobs": [
            {"token": t["token"], "logprob": t["logprob"], "bytes": t.get("bytes")}
            for t in entry.get("top", ())
        ],
    }
    return out


class ChatDeltaGenerator:
    """Builds chat.completion.chunk dicts for a streaming response
    (reference: lib/llm/src/protocols/openai/chat_completions/delta.rs)."""

    def __init__(self, model: str, request_id: Optional[str] = None):
        self.id = request_id or new_id("chatcmpl")
        self.model = model
        self.created = _now()
        self._sent_role = False

    def _chunk(self, delta: dict, finish_reason: Optional[str] = None) -> dict:
        return {
            "id": self.id,
            "object": "chat.completion.chunk",
            "created": self.created,
            "model": self.model,
            "choices": [
                {"index": 0, "delta": delta, "finish_reason": finish_reason}
            ],
        }

    def role_chunk(self) -> dict:
        self._sent_role = True
        return self._chunk({"role": "assistant", "content": ""})

    def text_chunk(self, text: str, logprobs: Optional[list] = None) -> dict:
        delta: dict = {"content": text}
        if not self._sent_role:
            delta["role"] = "assistant"
            self._sent_role = True
        out = self._chunk(delta)
        if logprobs:
            out["choices"][0]["logprobs"] = {
                "content": [_chat_logprob(e) for e in logprobs]
            }
        return out

    def tool_calls_chunk(self, tool_calls: list[dict]) -> dict:
        delta: dict = {
            "tool_calls": [dict(c, index=i) for i, c in enumerate(tool_calls)]
        }
        if not self._sent_role:
            delta["role"] = "assistant"
            self._sent_role = True
        return self._chunk(delta)

    def finish_chunk(self, finish_reason: str, usage: Optional[Usage] = None) -> dict:
        out = self._chunk({}, finish_reason=finish_reason)
        if usage is not None:
            out["usage"] = usage.to_dict()
        return out


class CompletionDeltaGenerator:
    def __init__(self, model: str, request_id: Optional[str] = None):
        self.id = request_id or new_id("cmpl")
        self.model = model
        self.created = _now()
        self._text_offset = 0  # running offset for logprobs text_offset

    def text_chunk(
        self, text: str, finish_reason: Optional[str] = None,
        logprobs: Optional[list] = None,
    ) -> dict:
        lp_obj = None
        if logprobs:
            lp_obj = {
                "tokens": [e["token"] for e in logprobs],
                "token_logprobs": [e["logprob"] for e in logprobs],
                "top_logprobs": [
                    {t["token"]: t["logprob"] for t in e["top"]} if "top" in e else None
                    for e in logprobs
                ],
                "text_offset": [self._text_offset for _ in logprobs],
            }
        self._text_offset += len(text)
        return {
            "id": self.id,
            "object": "text_completion",
            "created": self.created,
            "model": self.model,
            "choices": [
                {"index": 0, "text": text, "finish_reason": finish_reason,
                 "logprobs": lp_obj}
            ],
        }

    def finish_chunk(self, finish_reason: str, usage: Optional[Usage] = None) -> dict:
        out = self.text_chunk("", finish_reason=finish_reason)
        if usage is not None:
            out["usage"] = usage.to_dict()
        return out
