"""Engine-facing internal protocols.

Mirrors the reference's common protocol types (reference: lib/llm/src/protocols/
common/preprocessor.rs:25 PreprocessedRequest, common/llm_backend.rs:27,61
BackendOutput/LLMEngineOutput, common.rs StopConditions/SamplingOptions).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from dynamo_tpu.engine.sampling import SamplingParams


@dataclass
class PreprocessedRequest:
    """Tokenized request flowing preprocessor -> router -> engine."""

    request_id: str
    token_ids: list[int]
    sampling: SamplingParams = field(default_factory=SamplingParams)
    eos_token_ids: tuple[int, ...] = ()
    stop_strings: tuple[str, ...] = ()
    annotations: tuple[str, ...] = ()
    model: Optional[str] = None
    # multimodal: ImageInput list (llm/multimodal.py); the image-slot positions
    # in token_ids hold content-hash virtual ids
    images: list = field(default_factory=list)
    # OpenAI logprobs: None = off, n >= 0 = chosen-token logprob + n top
    # alternatives per sampled token
    logprobs: Optional[int] = None
    # output option: detokenize with special tokens hidden (default) or kept
    skip_special_tokens: bool = True
    # fleet-wide prefix cache: the KV router's best remote prefix holder for
    # this prompt (pull-server address + matched blocks), attached by the
    # processor when a peer's cached prefix beats the routed worker's
    kv_holder_addr: str = ""
    kv_holder_blocks: int = 0
    # multi-LoRA: adapter name resolved from the OpenAI ``model`` field
    # (``base:adapter``); "" = base model. Salts routing hashes and the
    # engine's KV block identity; the worker pins the adapter's device slot.
    lora_name: str = ""
    # goodput accounting tags (utils/goodput.py): tenant from the frontend's
    # ``x-tenant`` header, scenario from the replay harness's ``x-scenario``
    # header — ride to the engine so its per-request outcomes and
    # tenant-labeled SLO series attribute correctly ("" = untagged)
    tenant: str = ""
    scenario: str = ""
    # multi-tenant QoS (utils/qos.py): priority class stamped by the
    # frontend (x-priority header or per-tenant/adapter policy) — rides to
    # the engine the same way tenant tags do; "" = standard
    priority: str = ""

    def to_wire(self) -> dict:
        out = {
            "request_id": self.request_id,
            "token_ids": self.token_ids,
            "sampling": {
                "temperature": self.sampling.temperature,
                "top_k": self.sampling.top_k,
                "top_p": self.sampling.top_p,
                "min_p": self.sampling.min_p,
                "max_tokens": self.sampling.max_tokens,
                "min_tokens": self.sampling.min_tokens,
                "ignore_eos": self.sampling.ignore_eos,
                "seed": self.sampling.seed,
                "presence_penalty": self.sampling.presence_penalty,
                "frequency_penalty": self.sampling.frequency_penalty,
                "repetition_penalty": self.sampling.repetition_penalty,
            },
            "eos_token_ids": list(self.eos_token_ids),
            "stop_strings": list(self.stop_strings),
            "annotations": list(self.annotations),
            "model": self.model,
            "logprobs": self.logprobs,
            "skip_special_tokens": self.skip_special_tokens,
        }
        if self.kv_holder_addr:
            out["kv_holder_addr"] = self.kv_holder_addr
            out["kv_holder_blocks"] = self.kv_holder_blocks
        if self.lora_name:
            out["lora_name"] = self.lora_name
        if self.tenant:
            out["tenant"] = self.tenant
        if self.scenario:
            out["scenario"] = self.scenario
        if self.priority:
            out["priority"] = self.priority
        if self.images:
            out["images"] = [im.to_wire() for im in self.images]
        return out

    @classmethod
    def from_wire(cls, d: dict) -> "PreprocessedRequest":
        s = d.get("sampling", {})
        images = []
        if d.get("images"):
            from dynamo_tpu.llm.multimodal import ImageInput

            images = [ImageInput.from_wire(x) for x in d["images"]]
        return cls(
            images=images,
            logprobs=d.get("logprobs"),
            skip_special_tokens=d.get("skip_special_tokens", True),
            kv_holder_addr=d.get("kv_holder_addr", ""),
            kv_holder_blocks=int(d.get("kv_holder_blocks", 0) or 0),
            lora_name=str(d.get("lora_name", "") or ""),
            tenant=str(d.get("tenant", "") or ""),
            scenario=str(d.get("scenario", "") or ""),
            priority=str(d.get("priority", "") or ""),
            request_id=d["request_id"],
            token_ids=list(d["token_ids"]),
            sampling=SamplingParams(
                temperature=s.get("temperature", 0.0),
                top_k=s.get("top_k", 0),
                top_p=s.get("top_p", 1.0),
                min_p=s.get("min_p", 0.0),
                max_tokens=s.get("max_tokens", 512),
                min_tokens=s.get("min_tokens", 0),
                ignore_eos=s.get("ignore_eos", False),
                seed=s.get("seed"),
                presence_penalty=s.get("presence_penalty", 0.0),
                frequency_penalty=s.get("frequency_penalty", 0.0),
                repetition_penalty=s.get("repetition_penalty", 1.0),
            ),
            eos_token_ids=tuple(d.get("eos_token_ids", ())),
            stop_strings=tuple(d.get("stop_strings", ())),
            annotations=tuple(d.get("annotations", ())),
            model=d.get("model"),
        )


@dataclass
class BackendOutput:
    """Detokenized stream item: text delta + token ids + finish state."""

    request_id: str
    text: str = ""
    token_ids: list[int] = field(default_factory=list)
    finish_reason: Optional[str] = None  # stop | length | error | cancelled
    cumulative_tokens: int = 0
    cached_tokens: int = 0
    # per-token logprobs entries for this delta (when the request asked):
    # {"token": str, "logprob": float, "bytes": [int], "top": [{"token",
    # "logprob", "bytes"}]}
    logprobs: Optional[list] = None

    @property
    def finished(self) -> bool:
        return self.finish_reason is not None
