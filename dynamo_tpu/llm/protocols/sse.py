"""Server-Sent Events codec for OpenAI streaming responses.

Mirrors the reference's SSE codec + Annotated envelope
(reference: lib/llm/src/protocols/codec.rs:1-754, lib/runtime/src/protocols/annotated.rs):
``data:`` lines carry JSON payloads, ``event:`` lines carry annotation events,
``:`` lines are comments, and the stream terminates with ``data: [DONE]``.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Iterator, Optional

DONE = "[DONE]"


@dataclass
class SseMessage:
    data: Optional[str] = None
    event: Optional[str] = None
    id: Optional[str] = None
    comments: list[str] = field(default_factory=list)

    @property
    def is_done(self) -> bool:
        return self.data is not None and self.data.strip() == DONE

    def json(self) -> Any:
        return json.loads(self.data) if self.data else None


def encode_data(payload: Any) -> bytes:
    """One data frame (payload JSON-encoded unless already a string)."""
    text = payload if isinstance(payload, str) else json.dumps(payload, separators=(",", ":"))
    return f"data: {text}\n\n".encode()


def encode_event(event: str, payload: Any = None) -> bytes:
    out = f"event: {event}\n"
    if payload is not None:
        out += f"data: {json.dumps(payload, separators=(',', ':'))}\n"
    return (out + "\n").encode()


def encode_comment(comment: str) -> bytes:
    return f": {comment}\n\n".encode()


def encode_done() -> bytes:
    return f"data: {DONE}\n\n".encode()


class SseDecoder:
    """Incremental decoder: feed bytes, yields SseMessages at blank lines."""

    def __init__(self) -> None:
        self._buf = b""
        self._current = SseMessage()

    def feed(self, chunk: bytes) -> Iterator[SseMessage]:
        self._buf += chunk
        while b"\n" in self._buf:
            line, self._buf = self._buf.split(b"\n", 1)
            text = line.decode("utf-8", errors="replace").rstrip("\r")
            if text == "":
                if (
                    self._current.data is not None
                    or self._current.event is not None
                    or self._current.comments
                ):
                    msg, self._current = self._current, SseMessage()
                    yield msg
                continue
            if text.startswith(":"):
                self._current.comments.append(text[1:].lstrip())
            elif text.startswith("data:"):
                value = text[5:].lstrip()
                if self._current.data is None:
                    self._current.data = value
                else:  # multi-line data concatenates with newline per SSE spec
                    self._current.data += "\n" + value
            elif text.startswith("event:"):
                self._current.event = text[6:].strip()
            elif text.startswith("id:"):
                self._current.id = text[3:].strip()


async def decode_stream(byte_iter: AsyncIterator[bytes]) -> AsyncIterator[SseMessage]:
    decoder = SseDecoder()
    async for chunk in byte_iter:
        for msg in decoder.feed(chunk):
            yield msg
