"""dynamo-tpu: a TPU-native distributed LLM inference-serving framework.

Capabilities (mirroring NVIDIA Dynamo, see /root/repo/SURVEY.md):
  - OpenAI-compatible HTTP frontend with streaming SSE
  - Distributed runtime: Namespace/Component/Endpoint, lease-based discovery,
    two-plane RPC (request push + call-home streamed responses)
  - KV-cache-aware routing: global radix-tree index fed by worker events
  - Disaggregated prefill/decode with a work queue and direct KV-block transfer
  - Multi-tier KV cache with host-DRAM offload
  - A native JAX serving engine: paged KV cache, continuous batching,
    Pallas attention kernels, pjit/shard_map tensor parallelism over a Mesh

The compute path is JAX/XLA/Pallas; the runtime around it is asyncio +
native-code fast paths.
"""

__version__ = "0.1.0"
