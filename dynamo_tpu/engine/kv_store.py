"""Disk KV tier: the third rung of the cache ladder (HBM -> host DRAM -> disk).

`HostKvPool` (engine/offload.py) stops at host DRAM: its LRU victims are
gone, and a multi-turn conversation that parks cold for an hour pays a full
prefill recompute on resume. This module adds a byte-budgeted disk tier below
the host pool so eviction DEMOTES instead of dropping:

  - **identity**: blocks are keyed by the same chained sequence hash the
    prefix cache, KV events, and the fleet router speak — any tier answers
    the same question, so `lookup_prefix` and router overlap estimates stay
    honest across all three rungs.
  - **compression**: blocks land on disk int8-quantized (per-row symmetric
    scales, the quant/kv.py wire layout), so a disk byte holds ~2x the bf16
    context. Already-int8 wire blocks (`kv_cache_dtype="int8"`) are stored
    losslessly — a disk round trip is bit-exact and greedy decoding stays
    token-identical across a park/resume cycle.
  - **integrity**: each block file carries a JSON header (shapes, dtype,
    scale-plane geometry) and an xxh3-64 payload checksum — the same
    family the disagg dataplane uses. A corrupt or truncated file is a MISS,
    never a wrong answer: restore stops at the first bad block and the
    engine falls back to recompute for the tail.
  - **asynchrony**: the engine thread only touches the in-memory index
    (membership, LRU, byte budget — synchronous truth); all file I/O runs on
    one daemon worker over a FIFO queue, so a write enqueued by a spill
    always lands before a restore or unlink of the same block. Restores
    return a future shaped like a prefix-fetch result, so the scheduler's
    existing FETCHING_KV deferred-admission path scatters disk blocks into
    HBM without a new code path and a cold resume never blocks the loop.

Eviction truthfulness: `spill()` returns the hashes that left the DISK tier
(budget evictions) — with a disk tier attached, those are the only blocks
that left their *last* tier, so only they may emit `removed` KV events.
"""

from __future__ import annotations

import json
import os
import queue
import struct
import tempfile
import threading
import time
from collections import OrderedDict
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Optional

import numpy as np
import xxhash

from dynamo_tpu.quant.kv import is_quantized_wire
from dynamo_tpu.utils import events

#: environment override for where block files live (else a fresh tempdir)
DISK_DIR_ENV = "DYNTPU_KV_DISK_DIR"

_MAGIC = b"DKV1"
_INT8_MAX = 127.0

#: scale-plane rank of the wire layout [L, 2, n, ps, ...]: one f32 scale per
#: (layer, k/v, page, row) — the same placement quant/kv.py ships on the wire
_SCALE_AXES = 4


def resolve_disk_capacity_blocks(budget_bytes: int, block_bytes: int) -> int:
    """How many disk blocks a byte budget holds at the int8 on-disk block
    cost (the disk sibling of ``resolve_host_capacity_blocks`` — used by
    tests and capacity displays; the store itself enforces the budget on
    actual file bytes, headers included)."""
    if budget_bytes <= 0 or block_bytes <= 0:
        return 0
    return budget_bytes // block_bytes


def disk_block_bytes(page_size: int, num_kv_heads: int, head_dim: int,
                     num_layers: int) -> int:
    """Payload bytes one block costs ON DISK: always the int8 wire cost
    (values + f32 per-row scales), independent of the serving cache dtype —
    this is why a disk byte holds ~2x the bf16 context."""
    from dynamo_tpu.quant.kv import kv_page_bytes

    return kv_page_bytes(page_size, num_kv_heads, head_dim, num_layers, "int8")


def _quantize_block(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Full-precision wire block [L, 2, n, ps, ...] -> (int8 values, f32
    per-row scales [L, 2, n, ps]). Numpy twin of quant.kv.quantize_kv_rows:
    symmetric absmax over each row's head values, floored so all-zero
    padding rows divide cleanly to zeros."""
    x32 = np.asarray(x, np.float32)
    lead = x32.shape[:_SCALE_AXES]
    absmax = np.max(np.abs(x32.reshape(lead + (-1,))), axis=-1)
    scale = np.maximum(absmax, 1e-12) / _INT8_MAX
    s_b = scale.reshape(lead + (1,) * (x32.ndim - _SCALE_AXES))
    q = np.clip(np.rint(x32 / s_b), -_INT8_MAX, _INT8_MAX).astype(np.int8)
    return q, scale.astype(np.float32)


def _dequantize_block(q: np.ndarray, s: np.ndarray, dtype) -> np.ndarray:
    s_b = np.asarray(s, np.float32).reshape(s.shape + (1,) * (q.ndim - s.ndim))
    return (q.astype(np.float32) * s_b).astype(dtype)


def _dtype_from_name(name: str):
    """np.dtype lookup that also resolves the ml_dtypes names (bfloat16) a
    bf16 serving cache round-trips through the header."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _encode_block(seq_hash: int, data) -> bytes:
    """Serialize one wire block (ndarray or int8 wire dict) to the on-disk
    record: magic | u32 header_len | JSON header | q bytes | s bytes."""
    if is_quantized_wire(data):
        q = np.ascontiguousarray(data["q"], dtype=np.int8)
        s = np.ascontiguousarray(data["s"], dtype=np.float32)
        src_dtype, quantized_src = "int8", True
    else:
        arr = np.asarray(data)
        q, s = _quantize_block(arr)
        src_dtype, quantized_src = arr.dtype.name, False
    payload = q.tobytes() + s.tobytes()
    header = {
        "v": 1,
        "hash": int(seq_hash),
        "dtype": src_dtype,
        "quantized_src": quantized_src,
        "q_shape": list(q.shape),
        "s_shape": list(s.shape),
        "xxh3": xxhash.xxh3_64_intdigest(payload),
    }
    hdr = json.dumps(header, separators=(",", ":")).encode()
    return _MAGIC + struct.pack("<I", len(hdr)) + hdr + payload


def _decode_block(raw: bytes, seq_hash: int):
    """Inverse of ``_encode_block``; raises ValueError on any corruption
    (bad magic, truncation, checksum or identity mismatch)."""
    if len(raw) < len(_MAGIC) + 4 or raw[: len(_MAGIC)] != _MAGIC:
        raise ValueError("bad magic")
    (hdr_len,) = struct.unpack_from("<I", raw, len(_MAGIC))
    off = len(_MAGIC) + 4
    if len(raw) < off + hdr_len:
        raise ValueError("truncated header")
    header = json.loads(raw[off : off + hdr_len])
    payload = raw[off + hdr_len :]
    q_shape = tuple(header["q_shape"])
    s_shape = tuple(header["s_shape"])
    want = int(np.prod(q_shape)) + 4 * int(np.prod(s_shape))
    if len(payload) != want:
        raise ValueError("truncated payload")
    if xxhash.xxh3_64_intdigest(payload) != header["xxh3"]:
        raise ValueError("checksum mismatch")
    if int(header["hash"]) != int(seq_hash):
        raise ValueError("block identity mismatch")
    q_bytes = int(np.prod(q_shape))
    q = np.frombuffer(payload[:q_bytes], np.int8).reshape(q_shape)
    s = np.frombuffer(payload[q_bytes:], np.float32).reshape(s_shape)
    if header["quantized_src"]:
        return {"q": np.array(q), "s": np.array(s)}
    return _dequantize_block(q, s, _dtype_from_name(header["dtype"]))


def _block_disk_nbytes(data) -> int:
    """Exact int8 payload bytes ``data`` will cost on disk, computed WITHOUT
    quantizing — the engine-thread side of the byte budget."""
    if is_quantized_wire(data):
        return int(data["q"].nbytes) + int(data["s"].nbytes)
    arr = np.asarray(data)
    n = int(np.prod(arr.shape))
    rows = int(np.prod(arr.shape[:_SCALE_AXES]))
    return n + 4 * rows


@dataclass
class DiskPart:
    """One contiguous run of restored blocks, shaped like a prefix-fetch
    part so ``scheduler._scatter_fetched`` consumes it unchanged."""

    block_from: int
    block_to: int  # exclusive
    data: object  # wire-concat of the run (ndarray or int8 wire dict)
    cat_axis: int


@dataclass
class DiskFetchResult:
    """Worker-thread result of a restore, mirroring the prefix-fetch client
    result contract the scheduler's poll loop already speaks."""

    status: str  # "hit" | "miss"
    blocks: int = 0
    bytes: int = 0
    parts: list = field(default_factory=list)
    #: hashes whose files failed verification — left their last tier; the
    #: engine thread discards them and emits the one truthful ``removed``
    failed: list = field(default_factory=list)


@dataclass
class _Entry:
    nbytes: int
    path: str


class DiskKvStore:
    """Byte-budgeted disk tier below the host pool.

    The in-memory LRU index is the synchronous truth and is only touched
    from the engine thread; one daemon worker drains a FIFO op queue for
    every file read/write/unlink, so ordering hazards (restore racing its
    own spill's write; unlink racing a write) resolve by queue position.
    """

    def __init__(
        self,
        directory: Optional[str] = None,
        budget_bytes: int = 0,
        page_axis: int = 2,
        block_bytes: int = 0,
    ):
        env_dir = os.environ.get(DISK_DIR_ENV, "")
        self._owns_dir = not (directory or env_dir)
        self.directory = (
            directory or env_dir or tempfile.mkdtemp(prefix="dyntpu-kv-disk-")
        )
        os.makedirs(self.directory, exist_ok=True)
        self.budget_bytes = int(budget_bytes)
        self.page_axis = page_axis
        #: nominal int8 bytes per block (display/capacity arithmetic; the
        #: budget itself bites on actual per-block payload bytes)
        self.block_bytes = int(block_bytes)
        self._index: OrderedDict[int, _Entry] = OrderedDict()
        self.bytes_resident = 0
        #: optional utils/metering.MeterLedger — disk byte-residency edges
        #: (spill = acquire under the owner the host pool carries down;
        #: budget eviction / discard = release)
        self.meter = None
        # counters (worker thread increments restore-side under _lock)
        self.spills = 0
        self.restores = 0
        self.drops = 0
        self.io_errors = 0
        self.restore_s = 0.0
        self._lock = threading.Lock()
        self._ops: queue.Queue = queue.Queue()
        self._worker = threading.Thread(
            target=self._drain, name="dyntpu-kv-disk", daemon=True
        )
        self._worker.start()

    # ---------------- engine-thread index surface ----------------

    def __contains__(self, seq_hash: int) -> bool:
        return seq_hash in self._index

    def __len__(self) -> int:
        return len(self._index)

    def _path(self, seq_hash: int) -> str:
        return os.path.join(self.directory, f"{seq_hash & (2**64 - 1):016x}.kvb")

    def spill(self, seq_hash: int, data, owner=None) -> list[int]:
        """Engine thread: demote one host-pool victim to disk. Serialization
        and the write happen on the worker; the index and byte budget update
        here, synchronously. ``owner`` is the metering owner the host pool
        carries down the ladder (the block stores int8-compressed, so the
        disk tier charges the compressed bytes). Returns hashes EVICTED from
        disk to stay under budget — the blocks that just left their last
        tier."""
        if self.budget_bytes <= 0:
            return [seq_hash]
        if seq_hash in self._index:
            self._index.move_to_end(seq_hash)
            return []
        nbytes = _block_disk_nbytes(data)
        if nbytes > self.budget_bytes:
            return [seq_hash]  # a block the budget can never hold
        path = self._path(seq_hash)
        self._index[seq_hash] = _Entry(nbytes=nbytes, path=path)
        self.bytes_resident += nbytes
        if self.meter is not None:
            self.meter.kv_acquire("disk", seq_hash, nbytes, owner)
        self.spills += 1
        self._ops.put(("write", path, seq_hash, data))
        evicted: list[int] = []
        while self.bytes_resident > self.budget_bytes and self._index:
            victim, entry = self._index.popitem(last=False)
            self.bytes_resident -= entry.nbytes
            if self.meter is not None:
                self.meter.kv_release("disk", victim)
            self.drops += 1
            self._ops.put(("unlink", entry.path))
            evicted.append(victim)
        if evicted:
            events.emit(
                "offload.disk_drop", request_id="", blocks=len(evicted)
            )
        return evicted

    def discard(self, seq_hash: int) -> bool:
        """Engine thread: drop one block from the index (promotion back up
        the ladder, or a failed restore). Unlink rides the queue."""
        entry = self._index.pop(seq_hash, None)
        if entry is None:
            return False
        self.bytes_resident -= entry.nbytes
        if self.meter is not None:
            self.meter.kv_release("disk", seq_hash)
        self._ops.put(("unlink", entry.path))
        return True

    def leading_run(self, hashes: list[int]) -> list[int]:
        """The contiguous leading run of ``hashes`` resident on disk — the
        only shape a restore can scatter (KV pages chain)."""
        run: list[int] = []
        for h in hashes:
            if h not in self._index:
                break
            run.append(h)
        return run

    def restore_async(self, hashes: list[int]) -> "Future[DiskFetchResult]":
        """Engine thread: start an async restore of the leading resident run
        of ``hashes``. Returns a future resolving to a prefix-fetch-shaped
        result; never blocks (misses resolve immediately)."""
        run = self.leading_run(hashes)
        fut: Future = Future()
        if not run:
            fut.set_result(DiskFetchResult(status="miss"))
            return fut
        for h in run:
            self._index.move_to_end(h)
        paths = [self._index[h].path for h in run]
        self._ops.put(("read", list(run), paths, fut))
        return fut

    def restore(self, hashes: list[int], timeout: float = 30.0) -> DiskFetchResult:
        """Synchronous restore (tests, tooling)."""
        return self.restore_async(hashes).result(timeout)

    def flush(self, timeout: float = 30.0) -> None:
        """Block until every op enqueued so far has landed on disk."""
        done = threading.Event()
        self._ops.put(("barrier", done))
        done.wait(timeout)

    def close(self) -> None:
        self.flush()
        self._ops.put(("stop",))
        self._worker.join(timeout=5.0)
        if self._owns_dir:
            for entry in list(self._index.values()):
                try:
                    os.unlink(entry.path)
                except OSError:
                    pass
            try:
                os.rmdir(self.directory)
            except OSError:
                pass

    # ---------------- worker thread ----------------

    def _drain(self) -> None:
        while True:
            op = self._ops.get()
            kind = op[0]
            if kind == "stop":
                return
            if kind == "barrier":
                op[1].set()
                continue
            try:
                if kind == "write":
                    _, path, seq_hash, data = op
                    tmp = path + ".tmp"
                    with open(tmp, "wb") as f:
                        f.write(_encode_block(seq_hash, data))
                    os.replace(tmp, path)
                elif kind == "unlink":
                    try:
                        os.unlink(op[1])
                    except FileNotFoundError:
                        pass
                elif kind == "read":
                    self._do_read(*op[1:])
            except Exception:
                with self._lock:
                    self.io_errors += 1
                if kind == "read":
                    # a failed read op must still resolve its future
                    _, _, fut = op[1:]
                    if not fut.done():
                        fut.set_result(DiskFetchResult(status="miss"))

    def _do_read(self, run: list[int], paths: list[str], fut: Future) -> None:
        if fut.cancelled():
            return  # the sequence was preempted while we were queued
        t0 = time.monotonic()
        blocks: list = []
        failed: list[int] = []
        nbytes = 0
        for h, path in zip(run, paths):
            try:
                with open(path, "rb") as f:
                    raw = f.read()
                data = _decode_block(raw, h)
            except Exception:
                # corrupt/truncated/missing: stop at the first bad block —
                # the tail falls back to recompute, never a wrong answer
                failed.append(h)
                with self._lock:
                    self.io_errors += 1
                break
            blocks.append(data)
            nbytes += _block_disk_nbytes(data)
        dt = time.monotonic() - t0
        with self._lock:
            self.restores += len(blocks)
            self.restore_s += dt
        if not blocks:
            result = DiskFetchResult(status="miss", failed=failed)
        else:
            from dynamo_tpu.quant.kv import wire_concat

            part = DiskPart(
                block_from=0,
                block_to=len(blocks),
                data=wire_concat(blocks, self.page_axis),
                cat_axis=self.page_axis,
            )
            result = DiskFetchResult(
                status="hit", blocks=len(blocks), bytes=nbytes,
                parts=[part], failed=failed,
            )
        if not fut.cancelled():
            try:
                fut.set_result(result)
            except Exception:  # pragma: no cover - cancel raced set_result
                pass
