"""Continuous-batching scheduler: admission, chunked prefill with prefix-cache
reuse, batched decode, preemption.

Policy (round 1, deliberately simple):
  - admit waiting requests whenever a decode slot and enough pages exist
    (watermark guard keeps headroom for decode growth)
  - prefill runs chunk-by-chunk through bucket-padded jit calls; the cached
    prefix (from the page allocator) is skipped, mirroring the reference's
    prefix-hit accounting used for routing/disagg decisions
  - on page exhaustion mid-decode, the most-recently-admitted sequence is
    preempted back to the waiting queue (prompt = original + generated so far)
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.page_table import PageAllocator
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.utils import get_logger

log = get_logger("engine.sched")


@dataclass
class EngineRequest:
    """Tokens-in/tokens-out request (the ExecutionContext contract,
    reference: lib/llm/src/backend.rs:60-63)."""

    request_id: str
    token_ids: list[int]
    sampling: SamplingParams = field(default_factory=SamplingParams)
    eos_token_ids: tuple[int, ...] = ()


@dataclass
class StepOutput:
    request_id: str
    token: Optional[int] = None
    finished: bool = False
    finish_reason: Optional[str] = None  # stop | length | error | preempted
    cached_tokens: int = 0  # prefix-cache hit length (first output only)


@dataclass
class RunningSeq:
    req: EngineRequest
    slot: int
    prompt_len: int
    cached_len: int
    generated: list[int] = field(default_factory=list)
    page_table: np.ndarray = None  # [max_pages_per_seq]
    admitted_order: int = 0

    @property
    def pos(self) -> int:
        """Position of the next token to be decoded."""
        return self.prompt_len + len(self.generated)


class Scheduler:
    def __init__(self, config: EngineConfig, runner, allocator: PageAllocator):
        self.config = config
        self.runner = runner
        self.allocator = allocator
        self.waiting: deque[EngineRequest] = deque()
        self.adopted_waiting: deque[RunningSeq] = deque()  # prefilled remotely, need a slot
        self.slots: list[Optional[RunningSeq]] = [None] * config.max_seqs
        self._admit_counter = 0
        self.finished_count = 0

    # ---------------- queue ----------------

    def add_request(self, req: EngineRequest) -> None:
        self.waiting.append(req)

    def has_work(self) -> bool:
        return (
            bool(self.waiting)
            or bool(self.adopted_waiting)
            or any(s is not None for s in self.slots)
        )

    @property
    def num_running(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    def cancel(self, request_id: str) -> bool:
        for i, s in enumerate(self.slots):
            if s is not None and s.req.request_id == request_id:
                self.allocator.free_sequence(s.req.request_id)
                self.slots[i] = None
                return True
        for s in list(self.adopted_waiting):
            if s.req.request_id == request_id:
                self.allocator.free_sequence(request_id)
                self.adopted_waiting.remove(s)
                return True
        for req in list(self.waiting):
            if req.request_id == request_id:
                self.waiting.remove(req)
                return True
        return False

    # ---------------- main loop step ----------------

    def step(self) -> list[StepOutput]:
        outputs: list[StepOutput] = []
        outputs.extend(self._admit())
        outputs.extend(self._decode())
        return outputs

    # ---------------- admission + prefill ----------------

    def _free_slot(self) -> Optional[int]:
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return None

    def _admit(self) -> list[StepOutput]:
        outputs = []
        watermark_pages = int(self.config.watermark * self.config.num_pages)
        # adopted sequences first: their pages are already allocated and their
        # first token already emitted — they only need a decode slot
        while self.adopted_waiting:
            slot = self._free_slot()
            if slot is None:
                break
            seq = self.adopted_waiting.popleft()
            seq.slot = slot
            self.slots[slot] = seq
        while self.waiting:
            slot = self._free_slot()
            if slot is None:
                break
            req = self.waiting[0]
            if len(req.token_ids) > self.config.max_model_len:
                self.waiting.popleft()
                outputs.append(
                    StepOutput(req.request_id, finished=True, finish_reason="error")
                )
                continue
            pages_needed = -(-len(req.token_ids) // self.config.page_size)
            if self.allocator.free_pages < pages_needed + watermark_pages:
                break
            self.waiting.popleft()
            try:
                outputs.extend(self._start_sequence(req, slot))
            except MemoryError:
                self.waiting.appendleft(req)
                break
        return outputs

    def _start_sequence(self, req: EngineRequest, slot: int) -> list[StepOutput]:
        cached_len, state = self.allocator.allocate_sequence(req.request_id, req.token_ids)
        prompt_len = len(req.token_ids)
        page_table = np.zeros(self.config.max_pages_per_seq, np.int32)
        page_table[: len(state.pages)] = state.pages

        seq = RunningSeq(
            req=req,
            slot=slot,
            prompt_len=prompt_len,
            cached_len=cached_len,
            page_table=page_table,
            admitted_order=self._admit_counter,
        )
        self._admit_counter += 1

        first_token = self.run_prefill_chunks(req, page_table, cached_len, prompt_len)
        self.allocator.commit_prefilled(req.request_id, prompt_len)
        self.slots[slot] = seq
        return self._emit_token(seq, first_token, cached=cached_len)

    def run_prefill_chunks(
        self, req: EngineRequest, page_table: np.ndarray, cached_len: int, prompt_len: int
    ) -> int:
        """Chunked bucket-padded prefill, skipping the cached prefix; samples
        and returns the first output token. Shared by local admission and the
        disagg prefill worker."""
        s = req.sampling
        first_token: Optional[int] = None
        start = cached_len
        max_chunk = self.config.max_prefill_chunk
        while start < prompt_len:
            end = min(start + max_chunk, prompt_len)
            is_last = end == prompt_len
            tok = self.runner.prefill_chunk(
                np.asarray(req.token_ids[start:end], np.int32),
                start_pos=start,
                page_table=page_table,
                sample=is_last,
                temperature=s.temperature,
                top_k=s.top_k,
                top_p=s.top_p,
            )
            if is_last:
                first_token = tok
            start = end
        return first_token

    def adopt_prefilled(
        self, req: EngineRequest, first_token: int, cached_len: int = 0
    ) -> list[StepOutput]:
        """Adopt a sequence whose prompt KV was produced remotely (disagg path).

        Pages must already be allocated in the allocator under req.request_id
        and the KV injected; this emits the first token and queues the sequence
        for a decode slot.
        """
        state = self.allocator._seqs[req.request_id]
        page_table = np.zeros(self.config.max_pages_per_seq, np.int32)
        page_table[: len(state.pages)] = state.pages
        seq = RunningSeq(
            req=req,
            slot=-1,
            prompt_len=len(req.token_ids),
            cached_len=cached_len,
            page_table=page_table,
            admitted_order=self._admit_counter,
        )
        self._admit_counter += 1
        slot = self._free_slot()
        if slot is not None:
            seq.slot = slot
            self.slots[slot] = seq
        else:
            self.adopted_waiting.append(seq)
        return self._emit_token(seq, first_token, cached=cached_len)

    # ---------------- decode ----------------

    def _decode(self) -> list[StepOutput]:
        outputs: list[StepOutput] = []
        K = max(1, self.config.decode_steps)

        # Each active sequence feeds its last generated token, whose KV lands at
        # position seq.pos - 1; over a window of W fused steps writes reach
        # seq.pos + W - 2, so capacity for seq.pos + W - 1 tokens must exist up
        # front — page tables are static inside the fused call. W is clipped to
        # the request's remaining max_tokens budget (no pages reserved or
        # device steps spent on tokens that can never be emitted), and under
        # page pressure with no preemption victim the window shrinks to
        # whatever fits (limits[] freezes the sequence on device) instead of
        # failing the request.
        for seq in sorted(
            [s for s in self.slots if s is not None], key=lambda s: s.admitted_order
        ):
            if self.slots[seq.slot] is not seq:
                continue  # already preempted as a victim this step
            need = self._window_need(seq, K)
            while self.slots[seq.slot] is seq and not self.allocator.ensure_capacity(
                seq.req.request_id, need
            ):
                victim = self._pick_victim(exclude=seq)
                if victim is None:
                    if need > seq.pos and self.allocator.ensure_capacity(
                        seq.req.request_id, seq.pos
                    ):
                        break  # shorter window; device freezes at capacity
                    outputs.extend(self._finish(seq, "error"))
                    break
                outputs.extend(self._preempt(victim))
            if self.slots[seq.slot] is seq:
                state = self.allocator._seqs[seq.req.request_id]
                seq.page_table[: len(state.pages)] = state.pages

        active_seqs = [s for s in self.slots if s is not None]
        if not active_seqs:
            return outputs

        B = self.config.max_seqs
        tokens = np.zeros(B, np.int32)
        positions = np.zeros(B, np.int32)
        page_tables = np.zeros((B, self.config.max_pages_per_seq), np.int32)
        active = np.zeros(B, bool)
        limits = np.zeros(B, np.int32)  # max fed-token position per slot
        temps = np.zeros(B, np.float32)
        top_ks = np.zeros(B, np.int32)
        top_ps = np.ones(B, np.float32)

        for seq in active_seqs:
            i = seq.slot
            # Feed the last sampled token: its KV is written at seq.pos - 1,
            # attention covers <= pos-1, and the step samples the next token.
            tokens[i] = seq.generated[-1]
            positions[i] = seq.pos - 1
            page_tables[i] = seq.page_table
            active[i] = True
            # freeze at whichever bound is tightest: fused window, model
            # length, remaining token budget, or actually-allocated capacity
            cap_tokens = self.allocator._seqs[seq.req.request_id].num_pages * self.config.page_size
            limits[i] = min(self._window_need(seq, K), cap_tokens) - 1
            temps[i] = seq.req.sampling.temperature
            top_ks[i] = seq.req.sampling.top_k
            top_ps[i] = seq.req.sampling.top_p

        new_tokens = self.runner.decode_steps(
            tokens, positions, page_tables, active, limits, temps, top_ks, top_ps, K
        )  # [K, B]

        # Emit per fused step, but never past the slot's device freeze point
        # (limits): steps j run on device only while positions[i] + j <=
        # limits[i] — tokens past that are sampled from frozen state with no
        # KV written behind them and must not reach the client or the
        # allocator's block hashes. A sequence that finishes mid-window
        # ignores the remaining steps (wasted-work bound = K-1).
        for seq in active_seqs:
            i = seq.slot
            real_steps = int(limits[i] - positions[i] + 1)
            for j in range(min(real_steps, new_tokens.shape[0])):
                out = self._emit_token(seq, int(new_tokens[j, i]))
                outputs.extend(out)
                if out and out[-1].finished:
                    break
        return outputs

    def _window_need(self, seq: RunningSeq, K: int) -> int:
        """Token capacity a fused K-step window needs for `seq`: write positions
        run seq.pos - 1 .. seq.pos + W - 2 where W = min(K, remaining budget)."""
        remaining = max(1, seq.req.sampling.max_tokens - len(seq.generated))
        window = min(K, remaining)
        return min(seq.pos + window - 1, self.config.max_model_len)

    # ---------------- helpers ----------------

    def _emit_token(self, seq: RunningSeq, token: Optional[int], cached: int = 0) -> list[StepOutput]:
        if token is None:
            return []
        req = seq.req
        seq.generated.append(token)
        self.allocator.append_token(req.request_id, token)
        finish: Optional[str] = None
        if (not req.sampling.ignore_eos) and req.eos_token_ids and token in req.eos_token_ids:
            finish = "stop"
        elif len(seq.generated) >= req.sampling.max_tokens:
            finish = "length"
        elif seq.pos >= self.config.max_model_len:
            finish = "length"
        out = StepOutput(req.request_id, token=token, cached_tokens=cached)
        if finish is not None:
            out.finished = True
            out.finish_reason = finish
            self._release(seq)
        return [out]

    def _finish(self, seq: RunningSeq, reason: str) -> list[StepOutput]:
        self._release(seq)
        return [StepOutput(seq.req.request_id, finished=True, finish_reason=reason)]

    def _release(self, seq: RunningSeq) -> None:
        self.allocator.free_sequence(seq.req.request_id)
        if seq.slot >= 0 and self.slots[seq.slot] is seq:
            self.slots[seq.slot] = None
        elif seq in self.adopted_waiting:
            self.adopted_waiting.remove(seq)
        self.finished_count += 1

    def _pick_victim(self, exclude: RunningSeq) -> Optional[RunningSeq]:
        candidates = [s for s in self.slots if s is not None and s is not exclude]
        if not candidates:
            return None
        return max(candidates, key=lambda s: s.admitted_order)

    def _preempt(self, seq: RunningSeq) -> list[StepOutput]:
        """Return a sequence to the waiting queue; its work restarts later
        (prefix cache usually recovers most of it)."""
        log.info("preempting %s (page pressure)", seq.req.request_id)
        self.allocator.free_sequence(seq.req.request_id)
        self.slots[seq.slot] = None
        new_req = EngineRequest(
            request_id=seq.req.request_id,
            token_ids=list(seq.req.token_ids) + seq.generated,
            sampling=seq.req.sampling,
            eos_token_ids=seq.req.eos_token_ids,
        )
        # already-generated tokens count against max_tokens when it resumes
        new_req.sampling = SamplingParams(
            temperature=seq.req.sampling.temperature,
            top_k=seq.req.sampling.top_k,
            top_p=seq.req.sampling.top_p,
            max_tokens=max(1, seq.req.sampling.max_tokens - len(seq.generated)),
            stop=seq.req.sampling.stop,
            ignore_eos=seq.req.sampling.ignore_eos,
        )
        self.waiting.appendleft(new_req)
        return []
