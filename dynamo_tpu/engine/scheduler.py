"""Continuous-batching scheduler: admission, chunked prefill with prefix-cache
reuse, pipelined batched decode, preemption.

Policy (deliberately simple admission; aggressive latency hiding):
  - admit waiting requests whenever a decode slot and enough pages exist
    (watermark guard keeps headroom for decode growth)
  - prefill runs chunk-by-chunk through bucket-padded jit calls; the cached
    prefix (from the page allocator) is skipped, mirroring the reference's
    prefix-hit accounting used for routing/disagg decisions
  - decode runs as fused K-step windows dispatched **ahead** of result
    materialization (config.pipeline_depth windows in flight): the sampled
    token feedback lives on device (ModelRunner.tokens_dev), so the host never
    syncs between windows. Results are reconciled in dispatch order; EOS is
    therefore discovered up to (pipeline_depth * K) steps late, and the device
    wastes at most that much work per finished sequence — the price of hiding
    per-call dispatch/transfer latency, which dominates on tunneled platforms.
  - on page exhaustion mid-decode the pipeline is drained, then the
    most-recently-admitted sequence is preempted back to the waiting queue
    (prompt = original + generated so far)

Scheduled-vs-materialized positions: `seq.sched_len` counts tokens that exist
in the *scheduled* timeline (prefill's first token + every window step), while
`seq.generated` holds materialized tokens only. Device-side positions are
deterministic given the dispatched control arrays, so the host tracks them
exactly without reading anything back.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.page_table import PageAllocator
from dynamo_tpu.engine.sampling import MAX_EOS_IDS, SamplingParams, fold_seed
from dynamo_tpu.spec import make_proposer
from dynamo_tpu.utils import events, get_logger, tracing
from dynamo_tpu.utils.goodput import MAX_ITL_SAMPLES, RequestOutcome
from dynamo_tpu.utils.prometheus import Histogram
from dynamo_tpu.utils.qos import priority_rank, priority_weight
from dynamo_tpu.utils.step_anatomy import StepAnatomy, roofline_for_runner

log = get_logger("engine.sched")


@dataclass
class EngineRequest:
    """Tokens-in/tokens-out request (the ExecutionContext contract,
    reference: lib/llm/src/backend.rs:60-63)."""

    request_id: str
    token_ids: list[int]
    sampling: SamplingParams = field(default_factory=SamplingParams)
    eos_token_ids: tuple[int, ...] = ()
    # multimodal: images (llm/multimodal.py ImageInput, offsets into token_ids
    # where each image's virtual-token run sits) + their encoded embeddings
    # ([num_tokens, D] float32 each), filled by the engine at admission
    images: list = field(default_factory=list)
    mm_embeds: Optional[list] = None
    # OpenAI logprobs: None = off, 0 = chosen token only, n>0 = n top
    # alternatives per token (capped at sampling.LOGPROBS_K on device)
    logprobs: Optional[int] = None
    # M-RoPE (filled at admission for mrope models with images): [T, 3]
    # positions for the prompt + the scalar decode-time offset
    mrope_pos: Optional[object] = None
    mrope_delta: int = 0
    # preemption resume: token_ids[penalty_output_from:] were previously
    # GENERATED (their occurrence counts restore at re-admission so
    # presence/frequency penalties stay continuous)
    penalty_output_from: Optional[int] = None
    # observability: monotonic submission time (queue-wait/TTFT attribution)
    # and the edge-stamped trace id engine spans stitch to — both optional,
    # filled by AsyncJaxEngine at submission
    enqueue_ts: float = 0.0
    trace_id: Optional[str] = None
    # fleet-wide prefix cache: the KV router's best remote holder for this
    # prompt — that worker's pull-server address and its matched prefix
    # length in blocks. When the holder's advantage over the local prefix
    # cache clears prefix_fetch_min_blocks, admission pulls the pages over
    # the dataplane (FETCHING_KV) instead of recomputing them.
    kv_holder_addr: str = ""
    kv_holder_blocks: int = 0
    # live migration (disagg/migrate.py): non-empty = this request is the
    # ADOPTING side of a handoff — token_ids are a migrated sequence's full
    # history, and admission pulls its committed KV from kv_holder_addr via
    # the seq_handoff fetch kind (naming the source sequence here) instead
    # of the shared-prefix kind. Any pull failure recomputes from history.
    kv_handoff_seq: str = ""
    # multi-LoRA: the adapter this request serves ("" = base model). The
    # scheduler pins a device pool slot at admission (waiting while the
    # adapter loads — never blocking other requests) and salts the
    # sequence's KV block identity with the adapter uid.
    lora_name: str = ""
    # goodput accounting tags (utils/goodput.py): the tenant this request
    # bills to and the replay scenario that generated it — both ride the
    # per-request RequestOutcome and the tenant-labeled SLO series ("" =
    # untagged organic traffic)
    tenant: str = ""
    scenario: str = ""
    # multi-tenant QoS (utils/qos.py): priority class — critical | standard
    # | batch ("" = standard). Orders admission, weights the prefill
    # fairness cap, and orders preemption victims (batch lanes go first).
    priority: str = ""
    # cost metering (utils/metering.py): True once this request's admitted-
    # token charge posted to the ledger — carried through preemption requeues
    # so re-admission never double-bills the tenant's admitted count
    cost_admitted: bool = False


@dataclass
class StepOutput:
    request_id: str
    token: Optional[int] = None
    finished: bool = False
    finish_reason: Optional[str] = None  # stop | length | error | preempted
    cached_tokens: int = 0  # prefix-cache hit length (first output only)
    logprob: Optional[float] = None  # chosen-token logprob (when requested)
    top_logprobs: Optional[list] = None  # [(token_id, logprob), ...]


@dataclass
class RunningSeq:
    req: EngineRequest
    slot: int
    prompt_len: int
    cached_len: int
    generated: list[int] = field(default_factory=list)  # materialized tokens
    page_table: np.ndarray = None  # [max_pages_per_seq]
    admitted_order: int = 0
    sched_len: int = 0  # tokens in the scheduled timeline (>= len(generated))
    finished: bool = False
    # packed-prefill progress: next chunk start, or None when all chunks are
    # dispatched (decode windows only pick up seqs with prefill_pos None)
    prefill_pos: Optional[int] = None
    # speculative decoding: True = this sequence advances via verify rounds
    # (spec-eligible request on a spec-enabled engine); False = classic
    # dispatch-ahead decode windows. Fixed at admission so a sequence never
    # switches mid-stream between the sync (materialized) and dispatch-ahead
    # (scheduled) position-tracking regimes.
    spec_mode: bool = False
    # n-gram speculation: the sequence's incremental suffix index
    # (spec/proposer.py NgramIndex), built lazily at its first round and
    # extended with ACCEPTED tokens only — proposing costs O(new tokens),
    # not a full history rescan per round
    ngram: Optional[object] = None
    # draft-model speculation: how many tokens of this sequence's history
    # the draft model's KV has fed (None = draft cache not built yet);
    # draft_dead = the draft pool couldn't hold this sequence — it keeps
    # verifying (correct, 1 token/round) with no proposals
    draft_pos: Optional[int] = None
    draft_dead: bool = False
    # FETCHING_KV: an in-flight remote-prefix pull (_PrefixFetch). While set,
    # no prefill chunk dispatches for this sequence; resolution either
    # advances prefill_pos past the pulled prefix or falls back to recompute.
    fetch: Optional["_PrefixFetch"] = None
    # MIGRATING_OUT (disagg/migrate.py): frozen for handoff — no window,
    # spec round, or prefill dispatch touches it; pages stay resident so the
    # destination's seq_handoff pull can export them. Cleared if the handoff
    # fails (decode resumes locally); released without a finish when the
    # destination's continuation stream takes over.
    migrating: bool = False
    # multi-LoRA: the device pool slot this sequence's adapter is pinned in
    # (0 = base / no adapter). >0 implies one LoraStore ref held until the
    # sequence releases or is preempted — a pinned slot is never hot-swapped
    # under an in-flight sequence.
    lora_slot: int = 0
    # goodput outcome accounting (utils/goodput.py): admission queue wait,
    # first/last materialized-token walls, and the per-token inter-arrival
    # gaps after the first token. The gaps are client-shaped — a decode
    # window's tokens materialize together, so the series is bursty and its
    # per-request p99 is the honest stall signal the SLO verdict uses.
    queue_wait_s: Optional[float] = None
    first_token_wall: float = 0.0
    last_token_wall: float = 0.0
    itl_gaps: list = field(default_factory=list)

    @property
    def pos(self) -> int:
        """Materialized position of the next token to be decoded."""
        return self.prompt_len + len(self.generated)

    @property
    def next_fed_pos(self) -> int:
        """Position where the next scheduled window's first KV write lands."""
        return self.prompt_len + self.sched_len - 1


@dataclass
class _PrefixFetch:
    """Handle for one sequence's FETCHING_KV wait."""

    fut: object  # concurrent.futures.Future[PrefixFetchResult]
    base_block: int  # first requested block's index in the sequence
    t0: float
    # belt over the client's own wait_for: if the fetcher's loop dies and the
    # future never resolves, the scheduler still unwedges admission here
    belt_deadline: float
    # seq_handoff pull of a migrated sequence's pages (ADOPTING side):
    # resolution feeds the migration counters instead of the prefix ones
    handoff: bool = False
    # disk-tier restore (engine/kv_store.py): same FETCHING_KV parking, but
    # resolution promotes blocks disk->device and feeds the disk counters
    disk: bool = False


@dataclass
class _InFlight:
    kind: str  # "first" | "window"
    dev: object  # device array (async copy already started)
    # first: (seq, cached_len); window: [(seq, slot_idx, steps), ...]
    seqs: list = field(default_factory=list)
    cached_len: int = 0
    lp: object = None  # (chosen, top_ids, top_lps) device arrays, if requested
    # step-anatomy record of the dispatch that produced this entry: the
    # reconcile's device-wait/emission time attributes back to it
    rec: object = None


def _mm_chunk_overrides(req: EngineRequest, start: int, end: int):
    """Dense [n, D] embedding overrides + mask for the chunk [start, end):
    rows from every image whose virtual-token run intersects the chunk."""
    if not req.images or req.mm_embeds is None:
        return None, None
    n = end - start
    embeds = None
    mask = np.zeros(n, bool)
    for im, emb in zip(req.images, req.mm_embeds):
        lo = max(start, im.offset)
        hi = min(end, im.offset + im.num_tokens)
        if lo >= hi:
            continue
        if embeds is None:
            embeds = np.zeros((n, emb.shape[1]), np.float32)
        embeds[lo - start : hi - start] = emb[lo - im.offset : hi - im.offset]
        mask[lo - start : hi - start] = True
    if embeds is None:
        return None, None  # pure-text chunk: reuse the text prefill executable
    return embeds, mask


def _is_ready(arr) -> bool:
    try:
        return bool(arr.is_ready())
    except Exception:
        return False


@dataclass
class StageStats:
    """Cumulative per-stage engine-time attribution (seconds + counts).

    Always on — the cost is a handful of monotonic() reads per window against
    ms-scale stages — so bench artifacts and worker stats can break a round's
    wall time into queue wait / prefill / decode dispatch / device sync
    without enabling tracing. Spans (DYNTPU_TRACE) add the per-request
    timeline on top of these aggregates.
    """

    queue_wait_s: float = 0.0
    queue_wait_n: int = 0
    prefill_s: float = 0.0  # dispatch time of prefill calls (packed + chained)
    prefill_calls: int = 0
    prefill_rows: int = 0
    decode_dispatch_s: float = 0.0
    decode_windows: int = 0
    decode_steps: int = 0
    reconcile_wait_s: float = 0.0  # host blocked on device results
    reconcile_waits: int = 0
    # blocking reconciles forced by the prefill pipeline gate: with
    # prefill_pipeline_depth=1 every packed call stalls here before the next
    # dispatches; dispatch-ahead exists to shrink this count
    prefill_stalls: int = 0
    ttft_s: float = 0.0  # submission -> first materialized token
    ttft_n: int = 0
    # speculative decoding (spec rounds are synchronous verify passes, so
    # dispatch + device sync land in one number): draft tokens proposed,
    # drafts accepted by verification, and tokens actually emitted (accepted
    # + the per-round correction/bonus token)
    spec_rounds: int = 0
    spec_dispatch_s: float = 0.0
    spec_proposed: int = 0
    spec_accepted: int = 0
    spec_emitted: int = 0
    # draft-model speculation: the batched on-device drafting dispatches and
    # the per-sequence draft-cache prefills (both separate from the verify
    # pass's spec_dispatch_s so the round's cost splits draft vs verify)
    spec_draft_calls: int = 0
    spec_draft_s: float = 0.0
    spec_draft_prefills: int = 0
    spec_draft_prefill_s: float = 0.0

    def snapshot(self) -> dict:
        snap = {
            "queue_wait_s": round(self.queue_wait_s, 4),
            "queue_wait_n": self.queue_wait_n,
            "prefill_s": round(self.prefill_s, 4),
            "prefill_calls": self.prefill_calls,
            "prefill_rows": self.prefill_rows,
            "decode_dispatch_s": round(self.decode_dispatch_s, 4),
            "decode_windows": self.decode_windows,
            "decode_steps": self.decode_steps,
            "reconcile_wait_s": round(self.reconcile_wait_s, 4),
            "reconcile_waits": self.reconcile_waits,
            "prefill_stalls": self.prefill_stalls,
            "ttft_s": round(self.ttft_s, 4),
            "ttft_n": self.ttft_n,
        }
        if self.spec_rounds:
            snap.update(
                spec_rounds=self.spec_rounds,
                spec_dispatch_s=round(self.spec_dispatch_s, 4),
                spec_proposed=self.spec_proposed,
                spec_accepted=self.spec_accepted,
                spec_emitted=self.spec_emitted,
                spec_acceptance_rate=round(
                    self.spec_accepted / max(1, self.spec_proposed), 4
                ),
            )
        if self.spec_draft_calls or self.spec_draft_prefills:
            snap.update(
                spec_draft_calls=self.spec_draft_calls,
                spec_draft_s=round(self.spec_draft_s, 4),
                spec_draft_prefills=self.spec_draft_prefills,
                spec_draft_prefill_s=round(self.spec_draft_prefill_s, 4),
            )
        return snap


# bucket ladders for the engine-stage histograms: queue wait and TTFT reach
# into tens of seconds under overload; dispatch/sync stages are ms-scale
_WAIT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
_STAGE_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                  0.25, 0.5, 1.0, 2.5, 5.0)


def _stage_histograms() -> dict[str, Histogram]:
    return {
        "queue_wait": Histogram(
            "dynamo_engine_queue_wait_seconds",
            "time from engine submission to scheduler admission",
            _WAIT_BUCKETS,
        ),
        "ttft": Histogram(
            "dynamo_engine_ttft_seconds",
            "time from engine submission to first materialized token",
            _WAIT_BUCKETS,
        ),
        "prefill": Histogram(
            "dynamo_engine_prefill_seconds",
            "per-request prefill dispatch time across all chunks",
            _STAGE_BUCKETS,
        ),
        "decode_window": Histogram(
            "dynamo_engine_decode_window_dispatch_seconds",
            "host dispatch time of one fused multi-step decode window",
            _STAGE_BUCKETS,
        ),
        "reconcile": Histogram(
            "dynamo_engine_reconcile_wait_seconds",
            "host time blocked waiting on in-flight device results",
            _STAGE_BUCKETS,
        ),
        # fleet prefix cache: admission -> pulled-prefix-scattered (or
        # fallback) per remote fetch; the FETCHING_KV dwell time
        "prefix_fetch": Histogram(
            "dynamo_prefix_fetch_seconds",
            "remote prefix pull wall time, fetch start to scatter/fallback",
            _WAIT_BUCKETS,
        ),
        # per-round acceptance: how many draft tokens each participating
        # request had accepted in one speculative verify round (0 = only the
        # correction token advanced; k = the whole proposal held)
        "spec_accept": Histogram(
            "dynamo_spec_accepted_per_round",
            "draft tokens accepted per request per speculative verify round",
            (0.0, 1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0),
        ),
    }


class Scheduler:
    def __init__(self, config: EngineConfig, runner, allocator: PageAllocator):
        self.config = config
        self.runner = runner
        self.allocator = allocator
        self.waiting: deque[EngineRequest] = deque()
        self.adopted_waiting: deque[RunningSeq] = deque()  # prefilled remotely, need a slot
        self.slots: list[Optional[RunningSeq]] = [None] * config.max_seqs
        self.in_flight: deque[_InFlight] = deque()
        self._admit_counter = 0
        self.finished_count = 0
        # structural interference counters (read by tests/metrics): what a
        # decode pool pays for colocated prefill work. Pool specialization
        # (disagg) shows up as these dropping on the decode side while
        # remote_prefills rises on its DisaggDecodeEngine wrapper.
        self.preempt_count = 0  # sequences bounced back to waiting (page pressure)
        self.pressure_drain_count = 0  # pipeline drains forced by ensure_capacity misses
        self.local_prefill_rows = 0  # prompt tokens prefilled on THIS engine's chip
        # per-stage latency attribution: cumulative aggregates (always on) +
        # Prometheus histograms (rendered by the worker's /metrics)
        self.stage = StageStats()
        self.stage_hist = _stage_histograms()
        # step-anatomy plane (utils/step_anatomy.py): per-dispatch host/device
        # phase attribution in a bounded ring + the live roofline estimator
        # priced from this runner's actual param bytes and KV page cost
        self.anatomy = StepAnatomy(
            roofline=roofline_for_runner(runner, config) if runner is not None
            else None,
        )
        store = getattr(runner, "lora_store", None) if runner is not None else None
        if store is not None:
            # slot loads (device scatters) record as lora_slot_load dispatches
            store.anatomy = self.anatomy
        # cost-attribution ledger (utils/metering.py MeterLedger), attached by
        # the engine when config.metering: dispatch records carry bill rows
        # (anatomy.meter splits their phases), queued-seconds and
        # admitted/consumed token charges post here directly
        self.meter = None
        # run_prefill_chunks' most recent record: the dispatch-ahead callers
        # attach it to their _InFlight entry so the reconcile's device-wait
        # attributes back to the producing prefill chain
        self._last_prefill_rec = None
        # optional SLO sink (utils/slo.SloTracker): queue-wait and TTFT
        # observations feed rolling-window percentiles when attached
        self.slo = None
        # optional per-request outcome sink (utils/goodput.GoodputTracker
        # .observe, attached by the engine): every naturally-finished
        # sequence emits ONE RequestOutcome — the goodput plane's input
        self.outcome_sink = None
        # speculative decoding: parsed config + the draft proposer (history
        # in, <= k token ids out). None when --speculative is unset.
        self.spec = config.spec
        self.proposer = make_proposer(self.spec) if self.spec is not None else None
        # fleet-wide prefix cache: the pull client (disagg/prefix_fetch.py
        # PrefixFetchClient) the worker attaches; None = fetch disabled and
        # kv_holder hints on requests are ignored
        self.prefix_fetcher = None
        self.prefix_fetch_hits = 0  # fetches that landed >= 1 remote block
        self.prefix_fetch_fallbacks = 0  # timeout/gone/error -> recompute
        self.prefix_fetch_blocks = 0  # blocks pulled and scattered
        self.prefix_fetch_bytes = 0  # payload bytes pulled (wire KV dtype)
        self.prefix_fetch_tokens = 0  # prompt tokens whose recompute was skipped
        # disk KV tier (engine/kv_store.py): scheduler-side resume counters
        # (the store itself counts spills/restores/drops/io at the file layer)
        self.disk_restore_hits = 0  # restores that landed >= 1 disk block
        self.disk_restore_fallbacks = 0  # miss/corrupt head -> recompute
        self.disk_restore_blocks = 0  # blocks promoted disk -> device
        self.disk_restore_tokens = 0  # prompt tokens whose recompute was skipped
        # live migration (disagg/migrate.py): both roles' counters live here
        # so resource_snapshot / dynamo_migration_* render from one place
        self.migration_out = 0  # sequences handed to a peer (stream re-pinned)
        self.migration_out_failed = 0  # handoffs that resumed locally instead
        self.migration_in = 0  # migrated sequences admitted (ADOPTING)
        self.migration_in_pulled = 0  # adoptions whose seq_handoff pull landed
        self.migration_in_recomputed = 0  # adoptions that rebuilt KV from history
        self.migration_tokens_salvaged = 0  # history tokens whose recompute a pull skipped
        # long-context telemetry (dynamo_engine_context_* families): the
        # page-table width ladder, depth-aware chunk planner, and the
        # watermark-driven cold-block drain to the host tier
        self.table_promotions = 0  # sequences promoted to a wider table rung
        self.table_dispatches: dict[int, int] = {}  # table width -> dispatches
        self.chunk_dispatches: dict[int, int] = {}  # chunk bucket -> chunks
        self.offload_pressure_blocks = 0  # cold blocks drained to host by watermark
        # multi-tenant QoS (utils/qos.py): per-class preemption victims and
        # critical-triggered sheds (a waiting critical request evicting a
        # lower-class lane); migrate_shed is the hosting worker's hook —
        # (request_id) -> bool — that hands the victim to a peer via live
        # migration instead of preempt+recompute when a servable peer exists
        self.qos_preempted: dict[str, int] = {}
        self.qos_sheds = 0
        self.qos_shed_migrations = 0
        self.migrate_shed = None
        # last time a shed went via migration: the handoff is async (the
        # victim only freezes once migrate_out reaches the engine thread),
        # so without a cooldown every scheduler step until then would
        # migrate ANOTHER lane for the same waiting critical request
        self._last_shed_migration = 0.0

    # ---------------- queue ----------------

    def add_request(self, req: EngineRequest) -> None:
        self.waiting.append(req)

    def has_work(self) -> bool:
        return (
            bool(self.waiting)
            or bool(self.adopted_waiting)
            or bool(self.in_flight)
            or any(s is not None for s in self.slots)
        )

    def oldest_waiting_age(self, now: Optional[float] = None) -> float:
        """Age of the oldest queued request (the watchdog's stuck-queue
        signal). 0 when the queue is empty or unstamped."""
        for req in self.waiting:
            if req.enqueue_ts:
                return max(0.0, (now or time.monotonic()) - req.enqueue_ts)
        return 0.0

    def progress_marker(self) -> int:
        """Monotonic count of completed engine work; a frozen marker while
        has_work() holds means the loop is wedged (watchdog no-progress)."""
        st = self.stage
        return (
            st.prefill_calls + st.decode_windows + st.spec_rounds
            + st.reconcile_waits + self.finished_count
        )

    @property
    def num_running(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    def cancel(self, request_id: str) -> bool:
        for i, s in enumerate(self.slots):
            if s is not None and s.req.request_id == request_id:
                self._release(s, count_finished=False)
                return True
        for s in list(self.adopted_waiting):
            if s.req.request_id == request_id:
                s.finished = True
                self.allocator.free_sequence(request_id)
                self.adopted_waiting.remove(s)
                return True
        for req in list(self.waiting):
            if req.request_id == request_id:
                self.waiting.remove(req)
                return True
        return False

    # ---------------- main loop step ----------------

    def step(self) -> list[StepOutput]:
        outputs: list[StepOutput] = []
        self._drain_cold_to_host()
        outputs.extend(self._reconcile(block=False))
        outputs.extend(self._admit())
        dispatched = self._poll_fetches(outputs)
        dispatched += self._dispatch_prefill_batches(outputs)
        if self.spec is not None:
            dispatched += self._dispatch_spec_round(outputs)
        dispatched += self._dispatch_windows(outputs)
        pipeline_full = self._windows_in_flight() >= max(1, self.config.pipeline_depth)
        if pipeline_full or (self.in_flight and not dispatched and not outputs):
            outputs.extend(self._reconcile(block=True))
        elif not outputs and not dispatched and not self.in_flight and (
            self._fetching() or self._migrating()
        ):
            # FETCHING_KV / MIGRATING_OUT is the only live work: both resolve
            # on another thread's event loop, so don't hot-spin the engine
            # loop while waiting
            time.sleep(0.001)
        return outputs

    def _windows_in_flight(self) -> int:
        return sum(1 for e in self.in_flight if e.kind == "window")

    def _prefills_in_flight(self) -> int:
        return sum(
            1 for e in self.in_flight if e.kind in ("first", "first_batch")
        )

    def _drain_cold_to_host(self) -> None:
        """Pressure-driven host offload: once page-pool occupancy crosses
        ``offload_watermark``, move the coldest refcount-0 cached blocks —
        the deep KV of long sequences nothing is actively decoding — to the
        host tier in batches (one device gather each), returning their pages
        to the free list. Allocation bursts and decode growth then find
        fresh pages instead of paying per-block reclaim round trips, or
        preempting whole sequences, at the moment of exhaustion."""
        alloc, cfg = self.allocator, self.config
        if alloc.offload is None or cfg.offload_watermark >= 1.0:
            return
        total = max(1, cfg.num_pages - 1)
        drained = 0
        t0 = time.monotonic()
        while alloc.used_pages / total > cfg.offload_watermark and alloc._reusable:
            moved = alloc.drain_to_host(cfg.offload_drain_batch)
            if not moved:
                break
            self.offload_pressure_blocks += moved
            drained += moved
        if drained:
            dt = time.monotonic() - t0
            self.anatomy.record(
                "offload_drain", dispatch_s=dt, tokens=drained, ts=t0,
            )
            tracing.record_span(
                "engine.offload.drain", t0, duration=dt,
                attrs={"blocks": drained},
            )
            events.emit(
                "offload.drain", request_id="", blocks=drained,
                occupancy=round(alloc.used_pages / total, 4),
            )

    # ---------------- page-table ladder ----------------

    def _new_table(self, pages: list[int]) -> np.ndarray:
        """Page table at the sequence's CURRENT ladder width (pow2 bucket of
        its page count) — not the dense max_pages_per_seq width, so a short
        request in a 128K-capable engine dispatches a narrow table."""
        table = np.zeros(self.config.table_bucket_for(max(1, len(pages))), np.int32)
        table[: len(pages)] = pages
        return table

    def _refresh_table(self, seq: RunningSeq) -> None:
        """Re-sync a sequence's table from the allocator, promoting it to
        the next ladder rung when its pages outgrew the current width."""
        state = self.allocator._seqs[seq.req.request_id]
        n = len(state.pages)
        if n > len(seq.page_table):
            seq.page_table = np.zeros(self.config.table_bucket_for(n), np.int32)
            self.table_promotions += 1
        seq.page_table[:n] = state.pages

    def _count_table_dispatch(self, width: int) -> None:
        self.table_dispatches[width] = self.table_dispatches.get(width, 0) + 1

    # ---------------- admission + prefill ----------------

    def _free_slot(self) -> Optional[int]:
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return None

    def _admit(self) -> list[StepOutput]:
        outputs = []
        watermark_pages = int(self.config.watermark * self.config.num_pages)
        # adopted sequences first: their pages are already allocated and their
        # first token already emitted — they only need a decode slot
        while self.adopted_waiting:
            slot = self._free_slot()
            if slot is None:
                break
            seq = self.adopted_waiting.popleft()
            seq.slot = slot
            self.slots[slot] = seq
            # seed the device token-feedback buffer with its last token
            self.runner.write_token_slots(
                np.array([slot], np.int32), np.array([seq.generated[-1]], np.int32)
            )
            self.runner.set_slot_lora(slot, seq.lora_slot)
        # admission fairness for the PER-REQUEST prefill path (packed path
        # disabled: pp/sp meshes, multimodal, prefill_lanes=1): starting a
        # sequence there dispatches its whole prefill chain immediately, so
        # cap new starts per step like _dispatch_prefill_batches caps packed
        # calls — a burst must not serialize all its weight passes ahead of
        # running decode windows
        cap = self.config.prefill_batches_per_step
        decode_running = any(
            s is not None and not s.finished and s.prefill_pos is None
            for s in self.slots
        )
        packed_mode = self.runner.packed_prefill_mode
        started = 0
        # multi-LoRA: requests whose adapter is still loading (or whose slots
        # are all pinned) step aside WITHOUT blocking the queue behind them —
        # they re-enter at the queue front next step, so FIFO holds among
        # ready requests and an async adapter load never stalls the engine
        deferred: list[EngineRequest] = []
        try:
            while self.waiting:
                slot = self._free_slot()
                if slot is None:
                    # a waiting critical request may evict a lower-class lane
                    # (preferring live migration when a peer can adopt it)
                    if not self._shed_for_critical(outputs):
                        break
                    slot = self._free_slot()
                    if slot is None:
                        break  # shed went via async migration; slot frees later
                idx = self._next_waiting_index()
                req = self.waiting[idx]
                # reject oversized prompts BEFORE the fairness-cap break: the
                # rejection is pure host work (no chip time), so an oversized
                # prompt at the queue head must fail now, not stall behind the
                # per-step prefill cap (and stall everything queued behind it)
                if len(req.token_ids) > self.config.max_model_len:
                    del self.waiting[idx]
                    events.emit(
                        "sched.admission_rejected",
                        request_id=req.request_id, trace_id=req.trace_id,
                        tenant=req.tenant, priority=req.priority or "",
                        reason="oversized_prompt", prompt_tokens=len(req.token_ids),
                    )
                    self._record_request_error(req)
                    outputs.append(
                        StepOutput(req.request_id, finished=True, finish_reason="error")
                    )
                    continue
                if (
                    cap
                    and decode_running
                    and started >= cap
                    and not (packed_mode and not req.images)
                ):
                    break
                pages_needed = -(-len(req.token_ids) // self.config.page_size)
                if self.allocator.free_pages < pages_needed + watermark_pages:
                    break
                lora_slot = 0
                if req.lora_name:
                    store = getattr(self.runner, "lora_store", None)
                    try:
                        if store is None:
                            raise KeyError("engine has no LoRA adapters configured")
                        lora_slot = store.acquire(req.lora_name)
                    except Exception as e:
                        # unknown adapter / broken source: this request can
                        # never serve — fail it, don't wedge the queue
                        log.warning(
                            "rejecting %s: %s", req.request_id, e
                        )
                        del self.waiting[idx]
                        events.emit(
                            "sched.admission_rejected",
                            request_id=req.request_id, trace_id=req.trace_id,
                            tenant=req.tenant, priority=req.priority or "",
                            reason="lora_unavailable", adapter=req.lora_name,
                        )
                        self._record_request_error(req)
                        outputs.append(StepOutput(
                            req.request_id, finished=True, finish_reason="error"
                        ))
                        continue
                    if lora_slot is None:
                        del self.waiting[idx]
                        deferred.append(req)
                        events.emit(
                            "sched.admission_deferred",
                            request_id=req.request_id, trace_id=req.trace_id,
                            tenant=req.tenant, priority=req.priority or "",
                            reason="lora_loading", adapter=req.lora_name,
                        )
                        continue
                del self.waiting[idx]
                try:
                    self._start_sequence(req, slot, lora_slot=lora_slot)
                    # priority weights compose with the fairness cap: one
                    # start consumes 1/weight cap units, so a critical burst
                    # starts more prefill chains per step than batch work at
                    # the same configured cap (all-standard traffic consumes
                    # exactly 1 each — the pre-QoS behavior)
                    started += (
                        1.0 / priority_weight(req.priority)
                        if self.config.qos else 1.0
                    )
                except MemoryError:
                    self._release_lora_name(req.lora_name, lora_slot)
                    self.waiting.appendleft(req)
                    break
                except Exception:
                    # admission died mid-flight (e.g. a trace error on the first
                    # prefill): fail THIS request — it is in no queue or slot
                    # anymore, so nothing else would ever answer its caller
                    log.exception("admission failed for %s", req.request_id)
                    events.emit(
                        "sched.admission_rejected",
                        request_id=req.request_id, trace_id=req.trace_id,
                        tenant=req.tenant, priority=req.priority or "",
                        reason="admission_error",
                    )
                    self._release_lora_name(req.lora_name, lora_slot)
                    if req.request_id in self.allocator._seqs:
                        self.allocator.free_sequence(req.request_id)
                    if self.slots[slot] is not None and self.slots[slot].req is req:
                        self.slots[slot] = None
                    self._record_request_error(req)
                    outputs.append(
                        StepOutput(req.request_id, finished=True, finish_reason="error")
                    )
        finally:
            self.waiting.extendleft(reversed(deferred))
        return outputs

    # ---------------- multi-tenant QoS (utils/qos.py) ----------------

    def _next_waiting_index(self) -> int:
        """Admission order under QoS: the first waiting request of the
        highest priority class present (FIFO within a class — all-standard
        traffic admits in exactly the pre-QoS order). QoS disabled = plain
        FIFO."""
        if not self.config.qos or len(self.waiting) < 2:
            return 0
        best_i, best_rank = 0, priority_rank(self.waiting[0].priority)
        for i, req in enumerate(self.waiting):
            if i == 0:
                continue
            r = priority_rank(req.priority)
            if r < best_rank:
                best_i, best_rank = i, r
                if r == 0:
                    break
        return best_i

    def _shed_for_critical(self, outputs: list[StepOutput]) -> bool:
        """A critical request stuck waiting (no free slot) past the
        qos_preempt_wait gate evicts the lowest-class, most-recent running
        lane. The victim goes via live migration when the hosting worker
        wired a peer hook (``migrate_shed`` — the request survives on
        another worker and the slot frees when the relay takes over),
        otherwise preempt+requeue (never worse than page-pressure
        preemption). Returns True only when a slot was freed NOW."""
        if not self.config.qos or not self.waiting:
            return False
        req = self.waiting[self._next_waiting_index()]
        if priority_rank(req.priority) != 0:
            return False
        if req.enqueue_ts and (
            time.monotonic() - req.enqueue_ts
            < self.config.qos_preempt_wait_ms / 1e3
        ):
            return False  # transient full house: don't thrash lanes
        victims = [
            s for s in self.slots
            if s is not None and not s.finished and not s.migrating
            and priority_rank(s.req.priority) > 0
        ]
        if not victims:
            return False  # never shed critical for critical
        victim = max(
            victims,
            key=lambda s: (priority_rank(s.req.priority), s.admitted_order),
        )
        now = time.monotonic()
        if self.migrate_shed is not None and (
            now - self._last_shed_migration
            < max(0.05, self.config.qos_preempt_wait_ms / 1e3)
        ):
            return False  # a shed handoff is already in flight; let it land
        self.qos_sheds += 1
        events.emit(
            "qos.shed",
            request_id=victim.req.request_id, trace_id=victim.req.trace_id,
            tenant=victim.req.tenant, priority=victim.req.priority or "",
            site="engine", waiting_critical=req.request_id,
            via="migration" if self.migrate_shed is not None else "preempt",
        )
        if self.migrate_shed is not None:
            try:
                if self.migrate_shed(victim.req.request_id):
                    self._last_shed_migration = now
                    self.qos_shed_migrations += 1
                    log.info(
                        "QoS shed: migrating %s (%s) for waiting critical %s",
                        victim.req.request_id,
                        victim.req.priority or "standard", req.request_id,
                    )
                    return False  # slot frees when the handoff completes
            except Exception:
                log.exception("migrate_shed hook failed; preempting instead")
        # preempt contract: drain so victim.generated is authoritative
        if self.in_flight:
            outputs.extend(self._reconcile(block=True, drain=True))
        if victim.finished or self.slots[victim.slot] is not victim:
            return self._free_slot() is not None  # drain finished it anyway
        log.info(
            "QoS shed: preempting %s (%s) for waiting critical %s",
            victim.req.request_id, victim.req.priority or "standard",
            req.request_id,
        )
        self._preempt(victim)
        return True

    # ---------------- multi-LoRA helpers ----------------

    def _lora_salt(self, req: EngineRequest) -> int:
        """Adapter uid folded into this request's KV block identity (0 =
        base): adapter-specific prefixes never cross-hit — locally, in the
        router radix, or over the fleet pull path."""
        if not req.lora_name:
            return 0
        from dynamo_tpu.lora.adapter import lora_uid

        return lora_uid(req.lora_name)

    def _release_lora_name(self, name: str, lora_slot) -> None:
        if name and lora_slot:
            store = getattr(self.runner, "lora_store", None)
            if store is not None:
                store.release(name)

    def _release_lora(self, seq: RunningSeq) -> None:
        self._release_lora_name(seq.req.lora_name, seq.lora_slot)
        seq.lora_slot = 0

    def _bill(self, req: EngineRequest, weight: float) -> tuple:
        """One cost-attribution bill row for a dispatch record: the meter
        splits the record's phase seconds across its rows proportional to
        ``weight`` (utils/metering.py MeterLedger.on_phase)."""
        return (
            req.request_id, req.tenant, req.lora_name,
            req.priority or "", weight,
        )

    def _charge_admission(self, req: EngineRequest, wait) -> None:
        """Post a newly-admitted request's ledger charges: queued-seconds,
        plus the SAME admitted-token cost the QoS bucket debited at the front
        door (prompt + output budget) — once per request, preemption
        re-admissions excluded (cost_admitted survives the requeue)."""
        if self.meter is None:
            return
        if wait:
            self.meter.queued(req.tenant, wait)
        if not req.cost_admitted:
            req.cost_admitted = True
            self.meter.charge_tokens(
                req.tenant, "admitted",
                len(req.token_ids) + max(0, req.sampling.max_tokens),
            )

    def _start_sequence(self, req: EngineRequest, slot: int, lora_slot: int = 0) -> None:
        wait = None
        if req.enqueue_ts:
            now = time.monotonic()
            wait = max(0.0, now - req.enqueue_ts)
            self.stage.queue_wait_s += wait
            self.stage.queue_wait_n += 1
            self.stage_hist["queue_wait"].observe(wait)
            if self.slo is not None:
                self.slo.observe(
                    "queue_wait", wait, tenant=req.tenant,
                    priority=req.priority or "",
                )
            tracing.record_span(
                "engine.queue_wait", now - wait, end=now,
                request_id=req.request_id, trace_id=req.trace_id,
            )
        events.emit(
            "sched.admitted",
            request_id=req.request_id, trace_id=req.trace_id,
            tenant=req.tenant, priority=req.priority or "",
            slot=slot, queue_wait_ms=round(wait * 1e3, 3) if wait else 0.0,
        )
        self._charge_admission(req, wait)
        cached_len, state = self.allocator.allocate_sequence(
            req.request_id, req.token_ids, salt=self._lora_salt(req),
            owner=(req.tenant, req.request_id),
        )
        prompt_len = len(req.token_ids)
        page_table = self._new_table(state.pages)

        seq = RunningSeq(
            req=req,
            slot=slot,
            prompt_len=prompt_len,
            cached_len=cached_len,
            page_table=page_table,
            admitted_order=self._admit_counter,
            sched_len=1,  # the prefill's sampled token enters the timeline now
            spec_mode=self._spec_eligible(req),
            lora_slot=lora_slot,
            queue_wait_s=wait,
        )
        self._admit_counter += 1
        # decode windows read each slot's adapter id from the device-resident
        # slot_state vector; write it once here (no per-window H2D)
        self.runner.set_slot_lora(slot, lora_slot)

        if req.kv_handoff_seq:
            self.migration_in += 1
        fetch = self._maybe_start_fetch(req, cached_len, prompt_len)
        if fetch is None:
            # no remote holder (or it lost): a cold-parked session's blocks
            # may still sit on the local disk tier — same FETCHING_KV wait
            fetch = self._maybe_start_disk_restore(req, cached_len, prompt_len)
        if self.runner.packed_prefill_mode and not req.images:
            # packed path: per-request prep now, chunk dispatch deferred to
            # _dispatch_prefill_batches so chunks of DIFFERENT sequences can
            # share one weight pass
            self._prep_prefill(req, slot, prompt_len)
            seq.prefill_pos = cached_len
            seq.fetch = fetch
            self.slots[slot] = seq
            return
        if fetch is not None:
            # FETCHING_KV on the per-request path: hold the chunk dispatch
            # until the pull resolves (hit -> prefill only the tail past the
            # pulled prefix; miss -> prefill from cached_len as if no holder)
            seq.prefill_pos = cached_len
            seq.fetch = fetch
            self.slots[slot] = seq
            return

        # dispatch-ahead: chunks run without any host sync; the final chunk
        # samples, seeds tokens_dev[slot] on device, and async-copies the token
        result = self._dispatch_prefill_chunks(
            req, page_table, cached_len, prompt_len, slot=slot, lora_slot=lora_slot
        )
        tok_dev, lp = result if isinstance(result, tuple) else (result, None)
        self.allocator.commit_prefilled(req.request_id, prompt_len)
        self.slots[slot] = seq
        self.in_flight.append(
            _InFlight(kind="first", dev=tok_dev, seqs=[seq], cached_len=cached_len,
                      lp=lp, rec=self._last_prefill_rec)
        )

    # ---------------- fleet-wide prefix fetch (FETCHING_KV) ----------------

    def _maybe_start_fetch(
        self, req: EngineRequest, cached_len: int, prompt_len: int
    ) -> Optional[_PrefixFetch]:
        """Kick a remote-prefix pull when the router attached a holder whose
        matched prefix beats our local cache by >= prefix_fetch_min_blocks.
        Returns the FETCHING_KV handle, or None (prefill proceeds normally).

        A migration adoption (req.kv_handoff_seq) rides the same machinery
        with the ``seq_handoff`` fetch kind, its own deadline belt
        (migration_timeout_s), and a 1-block advantage bar — any committed
        block the source still holds beats recomputing it."""
        handoff = bool(req.kv_handoff_seq)
        if (
            self.prefix_fetcher is None
            or not req.kv_holder_addr
            or req.kv_holder_blocks <= 0
        ):
            if handoff and (prompt_len - 1) // self.config.page_size > cached_len // self.config.page_size:
                # no pull possible: the adoption rebuilds KV from history
                self.migration_in_recomputed += 1
            return None
        if not handoff and not self.config.prefix_fetch:
            return None
        ps = self.config.page_size
        base = cached_len // ps
        # never consume the entire prompt from cache: the final token must
        # prefill so the model produces next-token logits (same rule the
        # local prefix cache applies in allocate_sequence)
        want_to = min(req.kv_holder_blocks, (prompt_len - 1) // ps)
        min_blocks = 1 if handoff else max(1, self.config.prefix_fetch_min_blocks)
        if want_to - base < min_blocks:
            return None
        state = self.allocator._seqs[req.request_id]
        hashes = [b.sequence_hash for b in state.token_seq.blocks[base:want_to]]
        if not hashes:
            return None
        timeout = (
            self.config.migration_timeout_s if handoff
            else self.config.prefix_fetch_timeout_s
        )
        try:
            fut = self.prefix_fetcher.fetch(
                req.kv_holder_addr, hashes, timeout_s=timeout,
                kind="seq_handoff" if handoff else "prefix_fetch",
                seq_id=req.kv_handoff_seq,
            )
        except Exception:
            log.exception("prefix fetch start failed for %s", req.request_id)
            if handoff:
                self.migration_in_recomputed += 1
            return None
        now = time.monotonic()
        log.debug(
            "%s for %s: blocks [%d, %d) from %s",
            "seq handoff pull" if handoff else "prefix fetch",
            req.request_id, base, want_to, req.kv_holder_addr,
        )
        return _PrefixFetch(
            fut=fut, base_block=base, t0=now, belt_deadline=now + timeout + 2.0,
            handoff=handoff,
        )

    def _maybe_start_disk_restore(
        self, req: EngineRequest, cached_len: int, prompt_len: int
    ) -> Optional[_PrefixFetch]:
        """Kick an async disk->HBM restore when the disk tier holds the
        chain past our device+host cached prefix (a cold session resuming).
        Rides the same FETCHING_KV parking as the fleet prefix pull — the
        engine loop never blocks on file I/O; the worker thread reads,
        verifies, and dequantizes, and ``_poll_fetches`` scatters the result
        exactly like a remote part."""
        disk = getattr(self.allocator.offload, "disk", None)
        if disk is None or len(disk) == 0:
            return None
        ps = self.config.page_size
        base = cached_len // ps
        # same never-consume-the-whole-prompt rule as every other tier
        want_to = (prompt_len - 1) // ps
        if want_to <= base:
            return None
        state = self.allocator._seqs[req.request_id]
        hashes = [b.sequence_hash for b in state.token_seq.blocks[base:want_to]]
        if not hashes or hashes[0] not in disk:
            return None
        fut = disk.restore_async(hashes)
        now = time.monotonic()
        log.debug(
            "disk restore for %s: blocks [%d, %d)", req.request_id, base, want_to
        )
        return _PrefixFetch(
            fut=fut, base_block=base, t0=now,
            belt_deadline=now + self.config.prefix_fetch_timeout_s + 2.0,
            disk=True,
        )

    def _fetching(self) -> bool:
        return any(
            s is not None and not s.finished and s.fetch is not None
            for s in self.slots
        )

    def _migrating(self) -> bool:
        return any(
            s is not None and not s.finished and s.migrating
            for s in self.slots
        )

    def _poll_fetches(self, outputs: list[StepOutput]) -> int:
        """Resolve FETCHING_KV sequences: scatter pulled pages and advance
        prefill_pos past them on a hit, fall back to recompute on anything
        else. Returns the number of sequences released (dispatch count for
        the step loop)."""
        resolved = 0
        for seq in list(self.slots):
            if seq is None or seq.finished or seq.fetch is None:
                continue
            f = seq.fetch
            res = None
            timed_out = False
            if f.fut.done():
                try:
                    res = f.fut.result()
                except Exception:
                    log.exception(
                        "prefix fetch future failed for %s", seq.req.request_id
                    )
            elif time.monotonic() >= f.belt_deadline:
                # the client's own timeout should have fired long ago — its
                # loop is gone; a dead fetcher must never wedge admission
                f.fut.cancel()
                timed_out = True
                log.warning(
                    "prefix fetch for %s missed the belt deadline; recomputing",
                    seq.req.request_id,
                )
            else:
                continue
            seq.fetch = None
            resolved += 1
            dt = time.monotonic() - f.t0
            if not f.disk:
                self.stage_hist["prefix_fetch"].observe(dt)
            applied = 0
            if res is not None and getattr(res, "status", "") == "hit" and res.blocks:
                applied = self._scatter_fetched(seq, f, res)
            if f.disk:
                self._resolve_disk_restore(seq, f, res, applied, dt, timed_out)
                self._resume_after_fetch(seq, outputs)
                continue
            if applied:
                ps = self.config.page_size
                new_cached = (f.base_block + applied) * ps
                self.prefix_fetch_hits += 1
                self.prefix_fetch_blocks += applied
                self.prefix_fetch_bytes += res.bytes
                self.prefix_fetch_tokens += max(0, new_cached - seq.prefill_pos)
                if f.handoff:
                    self.migration_in_pulled += 1
                    self.migration_tokens_salvaged += max(
                        0, new_cached - seq.prefill_pos
                    )
                seq.prefill_pos = max(seq.prefill_pos, new_cached)
                seq.cached_len = max(seq.cached_len, new_cached)
                tracing.record_span(
                    "engine.prefix_fetch", f.t0, duration=dt,
                    request_id=seq.req.request_id, trace_id=seq.req.trace_id,
                    attrs={"blocks": applied, "bytes": res.bytes,
                           "holder": seq.req.kv_holder_addr,
                           "handoff": f.handoff},
                )
                events.emit(
                    "prefix_fetch.hit",
                    request_id=seq.req.request_id, trace_id=seq.req.trace_id,
                    tenant=seq.req.tenant, priority=seq.req.priority or "",
                    blocks=applied, bytes=res.bytes, handoff=f.handoff,
                    holder=seq.req.kv_holder_addr,
                )
            else:
                self.prefix_fetch_fallbacks += 1
                if f.handoff:
                    self.migration_in_recomputed += 1
                status = getattr(res, "status", "dead") if res is not None else "dead"
                log.info(
                    "%s for %s fell back to recompute (%s)",
                    "seq handoff pull" if f.handoff else "prefix fetch",
                    seq.req.request_id, status,
                )
                events.emit(
                    "prefix_fetch.timeout"
                    if timed_out or status == "timeout"
                    else "prefix_fetch.fallback",
                    request_id=seq.req.request_id, trace_id=seq.req.trace_id,
                    tenant=seq.req.tenant, priority=seq.req.priority or "",
                    status=status, handoff=f.handoff, waited_ms=round(dt * 1e3, 3),
                )
            self._resume_after_fetch(seq, outputs)
        return resolved

    def _resolve_disk_restore(
        self, seq: RunningSeq, f: _PrefixFetch, res, applied: int, dt: float,
        timed_out: bool,
    ) -> None:
        """Book a resolved disk restore: promote scattered blocks
        disk->device (their advertised identity stays valid — no removed
        event), drop corrupt blocks truthfully, advance prefill past the
        restored prefix, and journal the outcome."""
        failed = list(getattr(res, "failed", ()) or ()) if res is not None else []
        if applied:
            ps = self.config.page_size
            new_cached = (f.base_block + applied) * ps
            self.disk_restore_hits += 1
            self.disk_restore_blocks += applied
            self.disk_restore_tokens += max(0, new_cached - seq.prefill_pos)
            self.allocator.promote_restored(
                seq.req.request_id, f.base_block, applied
            )
            seq.prefill_pos = max(seq.prefill_pos, new_cached)
            seq.cached_len = max(seq.cached_len, new_cached)
            tracing.record_span(
                "engine.disk_restore", f.t0, duration=dt,
                request_id=seq.req.request_id, trace_id=seq.req.trace_id,
                attrs={"blocks": applied, "bytes": res.bytes},
            )
        else:
            self.disk_restore_fallbacks += 1
            log.info(
                "disk restore for %s fell back to recompute (%s)",
                seq.req.request_id,
                "belt_timeout" if timed_out
                else getattr(res, "status", "dead") if res is not None
                else "dead",
            )
        if failed:
            # corrupt/truncated files left their last tier: one truthful
            # removed per block; the tail past them recomputes
            self.allocator.drop_disk_blocks(failed)
        events.emit(
            "offload.disk_restore",
            request_id=seq.req.request_id, trace_id=seq.req.trace_id,
            tenant=seq.req.tenant, priority=seq.req.priority or "",
            blocks=applied, corrupt=len(failed),
            waited_ms=round(dt * 1e3, 3),
            outcome="hit" if applied else "fallback",
        )

    def _scatter_fetched(self, seq: RunningSeq, f: _PrefixFetch, res) -> int:
        """Inject pulled parts into the sequence's pre-allocated pages.
        Returns the contiguous block count applied (0 on any failure — the
        recompute simply overwrites whatever partially landed)."""
        state = self.allocator._seqs.get(seq.req.request_id)
        if state is None:
            return 0
        t0 = time.monotonic()
        try:
            applied = 0
            for part in res.parts:
                if part.block_from != applied:
                    break  # hole: only the contiguous leading run is cached
                ids = np.asarray(
                    state.pages[f.base_block + part.block_from:
                                f.base_block + part.block_to],
                    np.int32,
                )
                if len(ids) != part.block_to - part.block_from:
                    break
                self.runner.inject_pages_bucketed(ids, part.data, axis=part.cat_axis)
                applied = part.block_to
            if applied:
                self.anatomy.record(
                    "prefix_fetch_scatter", dispatch_s=time.monotonic() - t0,
                    tokens=applied, ts=t0, bill=[self._bill(seq.req, 1.0)],
                )
            return applied
        except Exception:
            log.exception(
                "scatter of fetched prefix failed for %s; recomputing",
                seq.req.request_id,
            )
            return 0

    def _resume_after_fetch(self, seq: RunningSeq, outputs: list[StepOutput]) -> None:
        """Release a sequence from FETCHING_KV into its prefill path."""
        if seq.finished or self.slots[seq.slot] is not seq:
            return
        req = seq.req
        if self.runner.packed_prefill_mode and not req.images:
            return  # prefill_pos is live again; the packed dispatcher takes over
        try:
            result = self._dispatch_prefill_chunks(
                req, seq.page_table, seq.prefill_pos, seq.prompt_len, slot=seq.slot,
                lora_slot=seq.lora_slot,
            )
        except Exception:
            log.exception("prefill after prefix fetch failed for %s", req.request_id)
            outputs.extend(self._finish(seq, "error"))
            return
        tok_dev, lp = result if isinstance(result, tuple) else (result, None)
        self.allocator.commit_prefilled(req.request_id, seq.prompt_len)
        seq.prefill_pos = None
        self.in_flight.append(_InFlight(
            kind="first", dev=tok_dev, seqs=[seq], cached_len=seq.cached_len,
            lp=lp, rec=self._last_prefill_rec,
        ))

    def _dispatch_prefill_batches(self, outputs: list[StepOutput]) -> int:
        """Pack pending prefill chunks of distinct sequences into shared
        prefill calls (one weight pass per call — the reference's engines
        batch prefills the same way; SURVEY.md §2.4 vLLM scheduler). Each
        sequence contributes at most one chunk per call (chunk i+1 reads the
        pages chunk i wrote, so same-sequence chunks ride consecutive calls).
        Single pending chunks take the per-request path — a packed call pads
        compute to its full lane count, which a lone request shouldn't pay.

        Fairness: dispatches at most ``config.prefill_batches_per_step``
        calls per invocation when decode work is running, so a burst of new
        prompts cannot serialize all its weight passes ahead of the decode
        windows that running streams' ITL depends on (step() alternates back
        here after the windows dispatch).

        Dispatch-ahead (``config.prefill_pipeline_depth``): every packed
        call leaves an in-flight entry, and up to depth calls ride
        unreconciled so call N+1's host prep + dispatch overlap call N's
        device time — the same pipelining decode windows get from
        ``pipeline_depth``. depth=1 block-reconciles each call before the
        next dispatches (the old mixed-regime behavior; every such forced
        wait counts in ``stage.prefill_stalls``)."""
        count = 0
        cap = self.config.prefill_batches_per_step
        depth = max(1, self.config.prefill_pipeline_depth)
        decode_running = any(
            s is not None and not s.finished and s.prefill_pos is None
            for s in self.slots
        )
        while True:
            if cap and decode_running and count >= cap:
                return count
            # prefill pipeline gate: never hold more than depth prefill
            # dispatches unreconciled. depth>=2 first drains entries whose
            # results already landed (no stall); depth=1 skips the readiness
            # poll — its contract is a strict reconcile between calls.
            while self._prefills_in_flight() >= depth:
                if depth > 1:
                    outputs.extend(self._reconcile(block=False))
                    if self._prefills_in_flight() < depth:
                        break
                self.stage.prefill_stalls += 1
                outputs.extend(self._reconcile(block=True))
            t_prep = time.monotonic()
            pending = sorted(
                (s for s in self.slots
                 if s is not None and not s.finished and s.prefill_pos is not None
                 and s.fetch is None),  # FETCHING_KV: hold until the pull resolves
                key=lambda s: s.admitted_order,
            )
            if not pending:
                return count
            # greedy bucket-aware packing in admission order: grow the lane
            # set while every taken lane still fits the (possibly enlarged)
            # bucket's row budget — one long head chunk goes alone, short
            # chunks pack together. Each lane's chunk length is depth-aware:
            # chunk_len_for shrinks it as that sequence's prefill advances
            # into a long prompt, keeping per-chunk latency roughly flat —
            # and backlog-aware: a deep pending queue promotes the bucket so
            # the burst takes fewer, larger dispatches.
            backlog_rows = sum(s.prompt_len - s.prefill_pos for s in pending)
            chunks = []
            bucket = 0
            for s in pending:
                limit = self.config.chunk_len_for(
                    s.prefill_pos, backlog_rows=backlog_rows
                )
                end = min(s.prefill_pos + limit, s.prompt_len)
                cand = self.config.bucket_for(max(bucket, end - s.prefill_pos))
                if chunks and len(chunks) + 1 > self.config.lanes_for(cand):
                    break
                chunks.append((s, s.prefill_pos, end))
                bucket = cand
            lanes_max = self.config.lanes_for(bucket)
            # lone chunks ride the packed trace at N=1 too: measured 33%
            # faster than the per-request trace for identical work (r5
            # on-chip, 512-row call: 11.3 vs 16.8 ms). N rounds up to a
            # power of two so partial packs compile at most log2(lanes_max)
            # executables per bucket, padding <= 2x on the rare odd sizes.
            lanes = []
            finals = []  # (seq, lane_idx)
            want_lp = False
            for j, (seq, start, end) in enumerate(chunks):
                is_final = end == seq.prompt_len
                lanes.append((
                    np.asarray(seq.req.token_ids[start:end], np.int32),
                    start,
                    seq.page_table,
                    seq.slot,
                    seq.req.sampling,
                    () if seq.req.sampling.ignore_eos else seq.req.eos_token_ids,
                    is_final,
                    seq.lora_slot,
                ))
                if is_final:
                    finals.append((seq, j))
                    want_lp = want_lp or seq.req.logprobs is not None
            rows = sum(end - start for _, start, end in chunks)
            self.local_prefill_rows += rows
            for _, start, end in chunks:
                cb = self.config.bucket_for(end - start)
                self.chunk_dispatches[cb] = self.chunk_dispatches.get(cb, 0) + 1
            self._count_table_dispatch(self.config.table_bucket_for(
                max(len(s.page_table) for s, _, _ in chunks)
            ))
            N = min(lanes_max, 1 << (len(chunks) - 1).bit_length())
            t0 = time.monotonic()
            rec = self.anatomy.begin(
                "prefill_packed", ts=t_prep,
                # cost split: each sequence pays for its own rows in the pack
                bill=[self._bill(s.req, end - start) for s, start, end in chunks],
            )
            self.anatomy.add_phase(rec, "host_prep", t0 - t_prep)
            try:
                result = self.runner.prefill_chunk_batch(
                    lanes, N=N, want_logprobs=want_lp
                )
            except Exception:
                log.exception(
                    "packed prefill failed for %s",
                    [seq.req.request_id for seq, _, _ in chunks],
                )
                for seq, _, _ in chunks:
                    outputs.extend(self._finish(seq, "error"))
                continue
            dt = time.monotonic() - t0
            self.stage.prefill_s += dt
            self.stage.prefill_calls += 1
            self.stage.prefill_rows += rows
            self.stage_hist["prefill"].observe(dt)
            self.anatomy.add_phase(rec, "dispatch", dt)
            self.anatomy.note_steps(rec, tokens=rows, participants=len(chunks))
            self.anatomy.note_prefill_floor(rec, rows)
            if tracing.enabled():
                tracing.record_span(
                    "engine.prefill", t0, duration=dt,
                    request_id=chunks[0][0].req.request_id,
                    trace_id=chunks[0][0].req.trace_id,
                    attrs={
                        "rows": rows, "lanes": N, "packed": True,
                        "requests": [s.req.request_id for s, _, _ in chunks],
                    },
                )
            for j, (seq, start, end) in enumerate(chunks):
                if end == seq.prompt_len:
                    self.allocator.commit_prefilled(seq.req.request_id, seq.prompt_len)
                    seq.prefill_pos = None
                else:
                    seq.prefill_pos = end
            toks_dev, lp = result if want_lp else (result, None)
            # EVERY pack (not just final-bearing ones) rides the in-flight
            # queue: the pipeline gate above counts it, and its reconcile
            # attributes the pack's device_wait to the dispatch that caused
            # it — a non-final pack just has no tokens to emit (empty seqs)
            self.in_flight.append(_InFlight(
                kind="first_batch", dev=toks_dev, lp=lp,
                seqs=[(seq, j, seq.cached_len) for seq, j in finals],
                rec=rec,
            ))
            count += 1

    def _prep_prefill(
        self, req: EngineRequest, slot: int, prompt_len: int, cached_len: int = 0
    ) -> None:
        """Per-request device-state prep that must precede any of its prefill
        chunks: vision encode (skipped when every image run sits inside the
        cached prefix — a repeat request never re-runs the vision tower),
        penalty-slot seeding (restoring prior-output counts after a
        preemption; image virtual-token runs excluded — their ids are
        hash-derived arbitrary vocab ids), M-RoPE positions."""
        needs_vision = req.images and any(
            im.offset + im.num_tokens > cached_len for im in req.images
        )
        if needs_vision and req.mm_embeds is None:
            req.mm_embeds = self.runner.encode_images(req.images)
        if (
            req.sampling.min_tokens >= 1
            and not req.sampling.ignore_eos
            and len(req.eos_token_ids) > MAX_EOS_IDS
        ):
            log.warning(
                "min_tokens: %d EOS ids exceed the device limit %d for %s; "
                "the excess are not suppressed on device",
                len(req.eos_token_ids), MAX_EOS_IDS, req.request_id,
            )
        if req.sampling.needs_penalties and slot >= 0:
            pen_ids = np.asarray(req.token_ids, np.int32)
            pen_from = req.penalty_output_from
            if req.images:
                keep = np.ones(len(pen_ids), bool)
                for im in req.images:
                    keep[im.offset : im.offset + im.num_tokens] = False
                if pen_from is not None:
                    pen_from = int(keep[:pen_from].sum())
                pen_ids = pen_ids[keep]
            self.runner.seed_penalty_slot(slot, pen_ids, output_from=pen_from)
        mcfg = getattr(self.runner.model.config, "mrope_section", None)
        if req.images and mcfg is not None and req.mrope_pos is None:
            from dynamo_tpu.llm.multimodal import mrope_positions

            req.mrope_pos, req.mrope_delta = mrope_positions(
                prompt_len, req.images,
                self.runner.model.config.vision.spatial_merge_size,
            )

    def _dispatch_prefill_chunks(
        self, req: EngineRequest, page_table: np.ndarray, cached_len: int,
        prompt_len: int, slot: int, prep: bool = True, lora_slot: int = 0,
    ):
        """Dispatch-ahead chunked prefill: no host sync; the final chunk seeds
        tokens_dev[slot] and returns the token as a device scalar."""
        return self.run_prefill_chunks(
            req, page_table, cached_len, prompt_len, slot=slot, sync=False,
            want_logprobs=req.logprobs is not None, prep=prep, lora_slot=lora_slot,
        )

    def run_prefill_chunks(
        self,
        req: EngineRequest,
        page_table: np.ndarray,
        cached_len: int,
        prompt_len: int,
        slot: int = -1,
        sync: bool = True,
        want_logprobs: bool = False,
        prep: bool = True,
        on_chunk=None,
        lora_slot: int = 0,
    ):
        """Bucket-chunked prefill, skipping the cached prefix; samples the first
        output token on the final chunk. sync=True (disagg prefill-worker path)
        returns it as a host int; sync=False returns the device scalar.
        prep=False skips _prep_prefill (already run at packed-path admission).
        on_chunk(start, end) fires after each chunk's dispatch — the streamed
        disagg export hook: pages finalized by the chunk can be exported (and
        put on the wire) while the next chunk computes."""
        rows = max(0, prompt_len - cached_len)
        self.local_prefill_rows += rows
        if rows:
            self._count_table_dispatch(
                self.config.table_bucket_for(len(page_table))
            )
        s = req.sampling
        first_token = None
        start = cached_len
        t0 = time.monotonic()
        rec = self._last_prefill_rec = self.anatomy.begin(
            "prefill_chunk", ts=t0, bill=[self._bill(req, max(1, rows))],
        )
        if prep:
            self._prep_prefill(req, slot, prompt_len, cached_len=cached_len)
        self.anatomy.add_phase(rec, "host_prep", time.monotonic() - t0)
        while start < prompt_len:
            # depth-aware chunk sizing: shrink the chunk as the context
            # deepens so per-chunk latency stays roughly flat at depth
            end = min(start + self.config.chunk_len_for(start), prompt_len)
            is_last = end == prompt_len
            cb = self.config.bucket_for(end - start)
            self.chunk_dispatches[cb] = self.chunk_dispatches.get(cb, 0) + 1
            embeds, embeds_mask = _mm_chunk_overrides(req, start, end)
            rope_pos = req.mrope_pos[start:end] if req.mrope_pos is not None else None
            tok = self.runner.prefill_chunk(
                np.asarray(req.token_ids[start:end], np.int32),
                start_pos=start,
                page_table=page_table,
                sample=is_last,
                temperature=s.temperature,
                top_k=s.top_k,
                top_p=s.top_p,
                slot=slot if is_last else -1,
                sync=sync,
                embeds=embeds,
                embeds_mask=embeds_mask,
                rope_pos=rope_pos,
                want_logprobs=want_logprobs and not sync,
                sampling=s,
                eos_ids=() if s.ignore_eos else req.eos_token_ids,
                lora_slot=lora_slot,
            )
            if is_last:
                first_token = tok
            if on_chunk is not None:
                on_chunk(start, end)
            start = end
        dt = time.monotonic() - t0
        self.stage.prefill_s += dt
        self.stage.prefill_calls += 1
        self.stage.prefill_rows += rows
        self.stage_hist["prefill"].observe(dt)
        # everything past host_prep is dispatch time (sync=True chains block
        # per chunk, so device wait folds into the same phase here)
        self.anatomy.add_phase(rec, "dispatch", dt - rec.host_prep_s)
        self.anatomy.note_steps(rec, tokens=rows, participants=1)
        self.anatomy.note_prefill_floor(rec, rows)
        tracing.record_span(
            "engine.prefill", t0, duration=dt,
            request_id=req.request_id, trace_id=req.trace_id,
            attrs={"rows": rows, "cached": cached_len, "sync": sync},
        )
        return first_token

    def adopt_prefilled(
        self, req: EngineRequest, first_token: int, cached_len: int = 0
    ) -> list[StepOutput]:
        """Adopt a sequence whose prompt KV was produced remotely (disagg path).

        Pages must already be allocated in the allocator under req.request_id
        and the KV injected; this emits the first token and queues the sequence
        for a decode slot.
        """
        wait = None
        if req.enqueue_ts:
            # the adopted analogue of admission queue wait: submission (on the
            # decode worker) -> remote KV adopted into a decode slot
            now = time.monotonic()
            wait = max(0.0, now - req.enqueue_ts)
            self.stage.queue_wait_s += wait
            self.stage.queue_wait_n += 1
            self.stage_hist["queue_wait"].observe(wait)
            if self.slo is not None:
                self.slo.observe(
                    "queue_wait", wait, tenant=req.tenant,
                    priority=req.priority or "",
                )
            tracing.record_span(
                "engine.queue_wait", now - wait, end=now,
                request_id=req.request_id, trace_id=req.trace_id,
                attrs={"adopted": True},
            )
        events.emit(
            "sched.admitted",
            request_id=req.request_id, trace_id=req.trace_id,
            tenant=req.tenant, priority=req.priority or "",
            adopted=True, cached_tokens=cached_len,
        )
        self._charge_admission(req, wait)
        state = self.allocator._seqs[req.request_id]
        page_table = self._new_table(state.pages)
        lora_slot = 0
        if req.lora_name:
            # adopted sequences arrive with their KV already computed; the
            # adapter must be pinned before any decode window. Blocking here
            # is acceptable: adoption runs rarely and the host copy is
            # usually cached (disagg routes lora requests down the local
            # path, so this is a belt for direct adopters).
            store = getattr(self.runner, "lora_store", None)
            if store is None:
                raise RuntimeError(
                    f"adopted request {req.request_id} names adapter "
                    f"{req.lora_name!r} but the engine has no LoRA adapters"
                )
            lora_slot = store.acquire_blocking(req.lora_name)
            if lora_slot is None:
                raise RuntimeError(
                    f"no free LoRA slot for adopted request {req.request_id}"
                )
        seq = RunningSeq(
            req=req,
            slot=-1,
            prompt_len=len(req.token_ids),
            cached_len=cached_len,
            page_table=page_table,
            admitted_order=self._admit_counter,
            sched_len=1,
            spec_mode=self._spec_eligible(req),
            lora_slot=lora_slot,
            queue_wait_s=wait,
        )
        self._admit_counter += 1
        slot = self._free_slot()
        if slot is not None:
            seq.slot = slot
            self.slots[slot] = seq
            self.runner.write_token_slots(
                np.array([slot], np.int32), np.array([first_token], np.int32)
            )
            self.runner.set_slot_lora(slot, lora_slot)
        else:
            self.adopted_waiting.append(seq)
        return self._emit_token(seq, first_token, cached=cached_len)

    # ---------------- speculative decode (spec rounds) ----------------

    def _spec_eligible(self, req: EngineRequest) -> bool:
        """Spec-mode eligibility, fixed at admission: penalties and logprobs
        need the window path's per-slot device state, min_tokens needs its
        EOS masking, and image requests carry M-RoPE deltas the verify pass
        doesn't model — all of those ride classic decode windows instead
        (correct, just not speculated)."""
        if self.spec is None:
            return False
        s = req.sampling
        return (
            not req.images
            and req.logprobs is None
            and not s.needs_penalties
            and s.min_tokens <= 0
        )

    def _propose_ngram(self, seq: RunningSeq, max_d: int) -> list[int]:
        """Propose via the sequence's incremental suffix index: built once
        from the prompt at the first round, extended with ACCEPTED tokens
        only — each round costs O(tokens accepted since the last round), not
        a full prompt+output rescan."""
        idx = seq.ngram
        if idx is None:
            idx = seq.ngram = self.proposer.index(seq.req.token_ids)
        for t in seq.generated[len(idx) - seq.prompt_len :]:
            idx.append(t)
        return idx.propose(max_d)

    # ---------------- draft-model speculation ----------------

    def _free_draft(self, seq: RunningSeq) -> None:
        draft = getattr(self.runner, "draft", None) if self.runner else None
        if draft is not None and seq.draft_pos is not None:
            draft.free_sequence(seq.req.request_id)
        seq.draft_pos = None

    def _drop_draft(self, seq: RunningSeq, why: str) -> None:
        """Draft pool can't serve this sequence: it keeps verifying (1 token
        per round, still exact) with no proposals for the rest of its life."""
        log.warning("draft cache dropped for %s (%s)", seq.req.request_id, why)
        self._free_draft(seq)
        seq.draft_dead = True

    def _draft_sync(self, seq: RunningSeq, K: int) -> bool:
        """Bring the draft model's KV up to the sequence's history: the
        steady state just extends capacity for this round's k draft rows;
        a fresh (or fallen-behind) sequence chunk-prefills everything but
        the newest token — admission, preemption resume, host-offload
        restores, and remote-prefill adoption all land here, so the draft
        cache is rebuilt from the authoritative token history in every case.
        Returns True when the lane can draft this round."""
        if seq.draft_dead:
            return False
        draft = self.runner.draft
        rid = seq.req.request_id
        behind = None if seq.draft_pos is None else seq.pos - seq.draft_pos
        if behind is not None and not 1 <= behind <= K + 1:
            # catch-up wider than the dispatch's K+1 rows (can't happen in
            # steady state; belt for exotic resume paths): rebuild
            self._free_draft(seq)
            behind = None
        if behind is None:
            hist = list(seq.req.token_ids) + seq.generated
            t0 = time.monotonic()
            if not draft.prefill_sequence(rid, hist[:-1]):
                self._drop_draft(seq, "draft page pool exhausted at prefill")
                return False
            dt = time.monotonic() - t0
            self.stage.spec_draft_prefills += 1
            self.stage.spec_draft_prefill_s += dt
            tracing.record_span(
                "engine.spec.draft_prefill", t0, duration=dt,
                request_id=rid, trace_id=seq.req.trace_id,
                attrs={"tokens": len(hist) - 1},
            )
            seq.draft_pos = len(hist) - 1
            return True
        # fed positions this round reach seq.pos + K - 1
        if not draft.ensure_capacity(rid, seq.pos + K):
            self._drop_draft(seq, "draft page pool exhausted")
            return False
        return True

    def _dispatch_draft_phase(self, candidates: list, K: int):
        """Batched drafting for a draft-model round: one
        ``runner.dispatch_draft`` across every lane whose draft cache is
        live. Fills each candidate's draft list in place (candidates are
        [seq, p, drafts, max_d] records) and returns the [B, K, V] draft-
        probability device array for the verify pass (None when no lane
        drafted)."""
        live = []
        for cand in candidates:
            seq, p, _, max_d = cand
            if max_d > 0 and self._draft_sync(seq, K):
                live.append(cand)
        if not live:
            return None
        B = self.config.max_seqs
        draft = self.runner.draft
        W = self.config.table_bucket_for(max(
            len(draft.table_for(s.req.request_id)) for s, _, _, _ in live
        ))
        V = self.runner.model.config.vocab_size
        positions = np.zeros(B, np.int32)
        tables = np.zeros((B, W), np.int32)
        active = np.zeros(B, bool)
        fed = np.full((B, K + 1), V, np.int32)
        n_feed = np.ones(B, np.int32)
        temps = np.zeros(B, np.float32)
        top_ks = np.zeros(B, np.int32)
        top_ps = np.ones(B, np.float32)
        min_ps = np.zeros(B, np.float32)
        seeds = np.zeros(B, np.int32)
        for seq, p, _, max_d in live:
            i = seq.slot
            rid = seq.req.request_id
            table = draft.table_for(rid)
            positions[i] = seq.draft_pos
            tables[i, : len(table)] = table
            active[i] = True
            pending = seq.generated[seq.draft_pos - seq.prompt_len :]
            n_feed[i] = len(pending)
            fed[i, : len(pending)] = pending
            s = seq.req.sampling
            temps[i] = s.temperature
            top_ks[i] = s.top_k
            top_ps[i] = s.top_p
            min_ps[i] = s.min_p
            seeds[i] = fold_seed(s.seed)
        t0 = time.monotonic()
        toks_dev, qs_dev = self.runner.dispatch_draft(
            positions, tables, active, fed, n_feed, temps, top_ks, top_ps,
            min_ps=min_ps, seeds=seeds if np.any(seeds) else None,
        )
        t_disp = time.monotonic()
        toks = np.asarray(toks_dev)  # graftlint: sync-ok draft reconcile point priced by step_anatomy device_wait
        dt = time.monotonic() - t0
        self.stage.spec_draft_calls += 1
        self.stage.spec_draft_s += dt
        self.anatomy.record(
            "spec_draft", dispatch_s=t_disp - t0,
            device_wait_s=time.monotonic() - t_disp,
            steps=K, tokens=int(sum(c[3] for c in live)),
            participants=len(live), ts=t0,
            # cost split: each lane pays for the draft tokens it asked for
            bill=[self._bill(c[0].req, max(1, c[3])) for c in live],
        )
        if tracing.enabled():
            tracing.record_span(
                "engine.spec.draft", t0, duration=dt,
                request_id=live[0][0].req.request_id,
                trace_id=live[0][0].req.trace_id,
                attrs={"participants": len(live), "k": K},
            )
        for cand in live:
            seq, _, _, max_d = cand
            cand[2] = toks[seq.slot, :max_d].tolist()
        return qs_dev

    def _dispatch_spec_round(self, outputs: list[StepOutput]) -> int:
        """One speculative verify round over every spec-mode decode slot.

        Per slot: propose up to k draft tokens — from the sequence's own
        history (n-gram suffix index) or, in draft-model mode, from one
        batched on-device drafting dispatch shared by every lane — then feed
        [anchor, drafts...] at consecutive fed positions through ONE
        multi-query verify pass, and emit the accepted prefix plus the
        correction/bonus token (1..k+1 tokens). Rounds are synchronous — the
        next proposal needs this round's accepted tokens — so the host
        tracks materialized positions exactly; KV written for rejected
        drafts (in the target AND the draft cache) is overwritten by the
        next round at the advanced anchor. Returns 1 when a round ran (the
        step loop's dispatch count)."""
        K = self.spec.k
        draft_mode = self.spec.kind == "draft"
        candidates = []  # mutable [seq, p, drafts, max_d] records
        for seq in sorted(
            [s for s in self.slots if s is not None], key=lambda s: s.admitted_order
        ):
            if (
                seq.finished
                or not seq.spec_mode
                or seq.prefill_pos is not None
                or seq.migrating  # MIGRATING_OUT: frozen for handoff
                or not seq.generated  # first token still in flight
            ):
                continue
            budget = seq.req.sampling.max_tokens - len(seq.generated)
            p = seq.prompt_len + len(seq.generated) - 1  # anchor fed position
            if budget <= 0 or p >= self.config.max_model_len:
                continue
            max_d = max(0, min(K, budget - 1, self.config.max_model_len - 1 - p))
            if draft_mode:
                drafts = None  # filled by the batched draft dispatch below
            else:
                drafts = self._propose_ngram(seq, max_d) if max_d > 0 else []
                max_d = len(drafts)
            # page capacity for the fed rows (anchor..anchor+max_d);
            # same pressure ladder as the window path: drain the pipeline,
            # then preempt, then shrink the proposal to the allocated pages
            need = p + max_d + 1
            while self.slots[seq.slot] is seq and not self.allocator.ensure_capacity(
                seq.req.request_id, need
            ):
                if self.in_flight:
                    self.pressure_drain_count += 1
                    outputs.extend(self._reconcile(block=True, drain=True))
                    continue
                victim = self._pick_victim(exclude=seq)
                if victim is None:
                    cap = self.allocator._seqs[seq.req.request_id].num_pages * \
                        self.config.page_size
                    if cap > p:
                        shrunk = min(max_d, cap - 1 - p)
                        if shrunk < max_d:
                            # page pressure with no victim left: the round
                            # still runs, at a truncated proposal depth
                            events.emit(
                                "sched.spec_degraded",
                                request_id=seq.req.request_id,
                                trace_id=seq.req.trace_id,
                                tenant=seq.req.tenant,
                                priority=seq.req.priority or "",
                                proposed=max_d, degraded_to=shrunk,
                                reason="page_pressure",
                            )
                        max_d = shrunk
                        if drafts is not None:
                            drafts = drafts[:max_d]
                        break
                    outputs.extend(self._finish(seq, "error"))
                    break
                self._preempt(victim)
            if self.slots[seq.slot] is not seq or seq.finished:
                continue
            self._refresh_table(seq)
            candidates.append([seq, p, drafts, max_d])
        # a later candidate's page-pressure preemption can evict an earlier
        # one mid-pass; only still-live slots ride the verify call
        candidates = [
            c for c in candidates
            if not c[0].finished and self.slots[c[0].slot] is c[0]
        ]
        if not candidates:
            return 0

        draft_probs = None
        if draft_mode:
            draft_probs = self._dispatch_draft_phase(candidates, K)
            for c in candidates:
                if c[2] is None:  # lane did not draft (dead/empty budget)
                    c[2], c[3] = [], 0
                else:
                    c[3] = len(c[2])

        t_prep = time.monotonic()
        B = self.config.max_seqs
        # per-round table width: the widest participant's ladder rung (narrow
        # sequences zero-pad into the trash page)
        W = self.config.table_bucket_for(
            max(len(s.page_table) for s, _, _, _ in candidates)
        )
        self._count_table_dispatch(W)
        positions = np.zeros(B, np.int32)
        page_tables = np.zeros((B, W), np.int32)
        active = np.zeros(B, bool)
        fed = np.zeros((B, K + 1), np.int32)
        n_drafts = np.zeros(B, np.int32)
        temps = np.zeros(B, np.float32)
        top_ks = np.zeros(B, np.int32)
        top_ps = np.ones(B, np.float32)
        min_ps = np.zeros(B, np.float32)
        seeds = np.zeros(B, np.int32)
        lora_slots = np.zeros(B, np.int32)
        snapshot = []
        for seq, p, drafts, _ in candidates:
            i = seq.slot
            positions[i] = p
            page_tables[i, : len(seq.page_table)] = seq.page_table
            active[i] = True
            fed[i, 0] = seq.generated[-1]
            if drafts:
                fed[i, 1 : 1 + len(drafts)] = drafts
            n_drafts[i] = len(drafts)
            s = seq.req.sampling
            temps[i] = s.temperature
            top_ks[i] = s.top_k
            top_ps[i] = s.top_p
            min_ps[i] = s.min_p
            seeds[i] = fold_seed(s.seed)
            lora_slots[i] = seq.lora_slot
            snapshot.append((seq, i, len(drafts), p))

        t0 = time.monotonic()
        out_dev, n_emit_dev = self.runner.dispatch_verify(
            positions, page_tables, active, fed, n_drafts, temps, top_ks,
            top_ps, min_ps=min_ps, seeds=seeds if np.any(seeds) else None,
            draft_probs=draft_probs,
            lora_slots=lora_slots if np.any(lora_slots) else None,
        )
        t_disp = time.monotonic()
        tokens = np.asarray(out_dev)  # graftlint: sync-ok verify reconcile point priced by step_anatomy device_wait
        n_emit = np.asarray(n_emit_dev)  # graftlint: sync-ok verify reconcile: n_emit rides the same resolved dispatch
        dt = time.monotonic() - t0
        st = self.stage
        st.spec_rounds += 1
        st.spec_dispatch_s += dt
        # step anatomy: one verify round reads weights + every participant's
        # live pages once (a multi-query pass, not one read per row), so the
        # floor prices like a single decode step at the round's occupancy
        live_pages = sum(
            self.allocator._seqs[s.req.request_id].num_pages
            for s, _, _, _ in candidates
            if s.req.request_id in self.allocator._seqs
        )
        rec = self.anatomy.record(
            "spec_verify", host_prep_s=t0 - t_prep, dispatch_s=t_disp - t0,
            device_wait_s=time.monotonic() - t_disp, steps=1,
            participants=len(candidates),
            floor_bytes=self.anatomy.decode_floor_bytes(live_pages, 1), ts=t_prep,
            # cost split: each candidate pays for its verify rows (anchor +
            # drafts); the reconcile phase below rides the same bill
            bill=[self._bill(s.req, n + 1) for s, _, n, _ in snapshot],
        )
        t_rec = time.monotonic()
        round_proposed = round_accepted = round_emitted = 0
        for seq, i, proposed, p in snapshot:
            if seq.finished:
                continue  # EOS/cancel raced in via a drain above
            emitted = int(n_emit[i])
            accepted = max(0, emitted - 1)
            st.spec_proposed += proposed
            st.spec_accepted += accepted
            st.spec_emitted += emitted
            round_proposed += proposed
            round_accepted += accepted
            round_emitted += emitted
            self.stage_hist["spec_accept"].observe(accepted)
            if draft_mode and seq.draft_pos is not None:
                # accepted draft rows are already fed in the draft cache;
                # the correction/bonus token is next round's catch-up feed,
                # and rejected rows get overwritten at the advanced anchor
                seq.draft_pos = p + 1 + accepted
            for j in range(emitted):
                outputs.extend(self._emit_token(seq, int(tokens[i, j])))
                if seq.finished:
                    break  # stop/length mid-chunk: the tail tokens are dead
        self.anatomy.add_phase(rec, "reconcile", time.monotonic() - t_rec)
        self.anatomy.note_steps(rec, tokens=round_emitted)
        if tracing.enabled():
            tracing.record_span(
                "engine.spec.verify", t0, duration=dt,
                request_id=snapshot[0][0].req.request_id,
                trace_id=snapshot[0][0].req.trace_id,
                attrs={
                    "participants": len(snapshot), "k": K,
                    "proposed": round_proposed, "accepted": round_accepted,
                    "requests": [s.req.request_id for s, _, _, _ in snapshot],
                },
            )
        return 1

    # ---------------- pipelined decode ----------------

    def _dispatch_windows(self, outputs: list[StepOutput]) -> int:
        count = 0
        while self._windows_in_flight() < max(1, self.config.pipeline_depth):
            if not self._dispatch_one_window(outputs):
                break
            count += 1
        return count

    def _plan_steps(self, seq: RunningSeq, K: int) -> int:
        """Steps this window can run for `seq` before budget/length bounds."""
        if seq.prefill_pos is not None:
            return 0  # prefill chunks still pending; no sampled token yet
        if seq.migrating:
            return 0  # MIGRATING_OUT: frozen for handoff, pages stay resident
        if seq.spec_mode:
            return 0  # advances via speculative verify rounds, never windows
        budget = seq.req.sampling.max_tokens - seq.sched_len
        length = self.config.max_model_len - seq.next_fed_pos
        return max(0, min(K, budget, length))

    def _dispatch_one_window(self, outputs: list[StepOutput]) -> bool:
        K = max(1, self.config.decode_steps)

        # capacity pass: every participant needs pages for its planned writes
        # (fed positions next_fed_pos .. next_fed_pos + steps - 1); page tables
        # are static inside the window
        for seq in sorted(
            [s for s in self.slots if s is not None], key=lambda s: s.admitted_order
        ):
            steps = self._plan_steps(seq, K)
            if steps <= 0:
                continue
            need = seq.next_fed_pos + steps
            while self.slots[seq.slot] is seq and not self.allocator.ensure_capacity(
                seq.req.request_id, need
            ):
                # page pressure: drain the pipeline (may free pages via EOS),
                # then preempt the most recent victim
                if self.in_flight:
                    self.pressure_drain_count += 1
                    outputs.extend(self._reconcile(block=True, drain=True))
                    continue
                victim = self._pick_victim(exclude=seq)
                if victim is None:
                    cap = self.allocator._seqs[seq.req.request_id].num_pages * \
                        self.config.page_size
                    if cap > seq.next_fed_pos:
                        break  # shorter window; limits[] freezes at capacity
                    outputs.extend(self._finish(seq, "error"))
                    break
                self._preempt(victim)
            if self.slots[seq.slot] is seq:
                self._refresh_table(seq)

        # host-prep timing starts AFTER the capacity pass: a pressure drain
        # up there blocks in _reconcile, and that wait is already attributed
        # as device_wait on the drained entries' own records
        t_prep = time.monotonic()
        participants = []
        for seq in self.slots:
            if seq is None or seq.finished:
                continue
            steps = self._plan_steps(seq, K)
            if steps <= 0:
                continue
            cap = self.allocator._seqs[seq.req.request_id].num_pages * self.config.page_size
            steps = min(steps, cap - seq.next_fed_pos)
            if steps <= 0:
                continue
            participants.append((seq, steps))
        if not participants:
            return False

        B = self.config.max_seqs
        # per-window table width: the widest participant's ladder rung —
        # short-sequence batches keep their narrow H2D + gather, and only
        # windows containing a deep sequence dispatch the wide executable
        W = self.config.table_bucket_for(
            max(len(seq.page_table) for seq, _ in participants)
        )
        self._count_table_dispatch(W)
        positions = np.zeros(B, np.int32)
        page_tables = np.zeros((B, W), np.int32)
        active = np.zeros(B, bool)
        limits = np.zeros(B, np.int32)
        temps = np.zeros(B, np.float32)
        top_ks = np.zeros(B, np.int32)
        top_ps = np.ones(B, np.float32)
        rope_deltas = np.zeros(B, np.int32)
        min_ps = np.zeros(B, np.float32)
        penalties = np.tile(np.array([[0.0], [0.0], [1.0]], np.float32), (1, B))
        seeds = np.zeros(B, np.int32)
        eos_allowed_from = np.zeros(B, np.int32)
        eos_rows = np.full((B, MAX_EOS_IDS), self.runner.model.config.vocab_size, np.int32)
        any_eos_mask = False

        snapshot = []
        for seq, steps in participants:
            i = seq.slot
            positions[i] = seq.next_fed_pos
            page_tables[i, : len(seq.page_table)] = seq.page_table
            active[i] = True
            limits[i] = seq.next_fed_pos + steps - 1  # max fed position
            temps[i] = seq.req.sampling.temperature
            top_ks[i] = seq.req.sampling.top_k
            top_ps[i] = seq.req.sampling.top_p
            rope_deltas[i] = seq.req.mrope_delta
            min_ps[i] = seq.req.sampling.min_p
            penalties[0, i] = seq.req.sampling.presence_penalty
            penalties[1, i] = seq.req.sampling.frequency_penalty
            penalties[2, i] = seq.req.sampling.repetition_penalty
            seeds[i] = fold_seed(seq.req.sampling.seed)
            sam = seq.req.sampling
            if sam.min_tokens > 1 and seq.req.eos_token_ids and not sam.ignore_eos:
                # the decode step sampling generation #k feeds position
                # prompt_len + k - 2 (prefill sampled #1); EOS is suppressed
                # while sampling generation #k for k <= min_tokens (vLLM
                # semantics: min_tokens non-EOS tokens are guaranteed), so it
                # unblocks at fed position prompt_len + min_tokens - 1
                eos_allowed_from[i] = seq.prompt_len + sam.min_tokens - 1
                ids = np.asarray(seq.req.eos_token_ids[:MAX_EOS_IDS], np.int32)
                eos_rows[i, : len(ids)] = ids
                any_eos_mask = True
            snapshot.append((seq, i, steps))
            seq.sched_len += steps

        want_lp = any(seq.req.logprobs is not None for seq, _ in participants)
        want_pen = any(seq.req.sampling.needs_penalties for seq, _ in participants)
        # step anatomy: every scanned step reads the weights + each live
        # participant's KV pages — the bytes-moved floor at this occupancy
        live_pages = sum(
            self.allocator._seqs[seq.req.request_id].num_pages
            for seq, _ in participants
            if seq.req.request_id in self.allocator._seqs
        )
        rec = self.anatomy.begin(
            "decode_window", ts=t_prep,
            # cost split: each participant pays for its scheduled steps
            bill=[self._bill(s.req, max(1, n)) for s, _, n in snapshot],
        )
        t0 = time.monotonic()
        self.anatomy.add_phase(rec, "host_prep", t0 - t_prep)
        result = self.runner.dispatch_decode_window(
            positions, page_tables, active, limits, temps, top_ks, top_ps, K,
            want_logprobs=want_lp, rope_deltas=rope_deltas, min_ps=min_ps,
            penalties=penalties if want_pen else None,
            seeds=seeds if np.any(seeds) else None,
            eos_allowed_from=eos_allowed_from if any_eos_mask else None,
            eos_ids=eos_rows if any_eos_mask else None,
        )
        dt = time.monotonic() - t0
        steps_total = sum(steps for _, _, steps in snapshot)
        self.stage.decode_dispatch_s += dt
        self.stage.decode_windows += 1
        self.stage.decode_steps += K
        self.stage_hist["decode_window"].observe(dt)
        self.anatomy.add_phase(rec, "dispatch", dt)
        self.anatomy.note_steps(
            rec, steps=K, tokens=steps_total, participants=len(snapshot),
            floor_bytes=self.anatomy.decode_floor_bytes(live_pages, K),
        )
        if tracing.enabled():
            tracing.record_span(
                "engine.decode.window", t0, duration=dt,
                request_id=snapshot[0][0].req.request_id,
                trace_id=snapshot[0][0].req.trace_id,
                attrs={
                    "participants": len(snapshot), "k": K,
                    "steps_total": steps_total,
                    "requests": [s.req.request_id for s, _, _ in snapshot],
                },
            )
        toks_dev, lp = result if want_lp else (result, None)
        self.in_flight.append(_InFlight(
            kind="window", dev=toks_dev, seqs=snapshot, lp=lp, rec=rec,
        ))
        return True

    def _reconcile(self, block: bool, drain: bool = False) -> list[StepOutput]:
        """Materialize arrived results in dispatch order and emit tokens.

        block: wait for (at least) the oldest entry. drain: wait for all."""
        outputs: list[StepOutput] = []
        while self.in_flight:
            entry = self.in_flight[0]
            ready = _is_ready(entry.dev)
            if not (block or drain) and not ready:
                break
            self.in_flight.popleft()
            t0 = time.monotonic()
            data = np.asarray(entry.dev)  # graftlint: sync-ok THE priced reconcile point: step_anatomy device_wait source
            if not ready:
                # host actually blocked on the device: the sync wait the
                # dispatch-ahead pipeline exists to hide
                dt = time.monotonic() - t0
                self.stage.reconcile_wait_s += dt
                self.stage.reconcile_waits += 1
                self.stage_hist["reconcile"].observe(dt)
                self.anatomy.add_phase(entry.rec, "device_wait", dt)
                if tracing.enabled():
                    tracing.record_span(
                        "engine.decode.sync", t0, duration=dt,
                        attrs={"kind": entry.kind, "drain": drain},
                    )
            t_rec = time.monotonic()
            lp = None
            if entry.lp is not None:
                lp = tuple(np.asarray(a) for a in entry.lp)
            block = False
            if entry.kind == "first":
                seq = entry.seqs[0]
                if seq.finished:
                    continue
                outputs.extend(
                    self._emit_token(
                        seq, int(data), cached=entry.cached_len,
                        lp=(lp[0][()], lp[1], lp[2]) if lp is not None else None,
                    )
                )
            elif entry.kind == "first_batch":
                for seq, lane, cached in entry.seqs:
                    if seq.finished:
                        continue
                    step_lp = None
                    if lp is not None and seq.req.logprobs is not None:
                        step_lp = (lp[0][lane], lp[1][lane], lp[2][lane])
                    outputs.extend(
                        self._emit_token(seq, int(data[lane]), cached=cached, lp=step_lp)
                    )
            else:
                for seq, slot_idx, steps in entry.seqs:
                    if seq.finished:
                        continue  # EOS/cancel discovered earlier; zombie tokens
                    for j in range(min(steps, data.shape[0])):
                        step_lp = None
                        if lp is not None:
                            step_lp = (lp[0][j, slot_idx], lp[1][j, slot_idx], lp[2][j, slot_idx])
                        outputs.extend(
                            self._emit_token(seq, int(data[j, slot_idx]), lp=step_lp)
                        )
                        if seq.finished:
                            break
            # host-side materialization (token emission, stop scanning) of
            # this entry attributes back to the dispatch that produced it
            self.anatomy.add_phase(entry.rec, "reconcile", time.monotonic() - t_rec)
        return outputs

    # ---------------- helpers ----------------

    def _emit_token(
        self, seq: RunningSeq, token: Optional[int], cached: int = 0, lp=None
    ) -> list[StepOutput]:
        if token is None or seq.finished:
            return []
        req = seq.req
        seq.generated.append(token)
        now = time.monotonic()
        if len(seq.generated) == 1:
            seq.first_token_wall = now
            if req.enqueue_ts:
                ttft = max(0.0, now - req.enqueue_ts)
                self.stage.ttft_s += ttft
                self.stage.ttft_n += 1
                self.stage_hist["ttft"].observe(ttft)
                if self.slo is not None:
                    self.slo.observe(
                        "ttft", ttft, tenant=req.tenant,
                        priority=req.priority or "",
                    )
                tracing.record_span(
                    "engine.ttft", req.enqueue_ts, duration=ttft,
                    request_id=req.request_id, trace_id=req.trace_id,
                    attrs={"cached": cached} if cached else None,
                )
                events.emit(
                    "request.first_token",
                    request_id=req.request_id, trace_id=req.trace_id,
                    tenant=req.tenant, priority=req.priority or "",
                    ttft_ms=round(ttft * 1e3, 3), cached_tokens=cached,
                )
        else:
            # per-token inter-arrival gap at materialization time (a window's
            # tokens land together — the bursty series IS the client view);
            # capped so a 100K-token stream can't grow the record unbounded
            gap = max(0.0, now - seq.last_token_wall)
            if len(seq.itl_gaps) < MAX_ITL_SAMPLES:
                seq.itl_gaps.append(gap)
            if self.slo is not None:
                self.slo.observe(
                    "itl", gap, tenant=req.tenant, priority=req.priority or ""
                )
        seq.last_token_wall = now
        seq.sched_len = max(seq.sched_len, len(seq.generated))
        self.allocator.append_token(req.request_id, token)
        finish: Optional[str] = None
        if (
            (not req.sampling.ignore_eos)
            and req.eos_token_ids
            and token in req.eos_token_ids
            and len(seq.generated) > req.sampling.min_tokens
        ):
            finish = "stop"
        elif len(seq.generated) >= req.sampling.max_tokens:
            finish = "length"
        elif seq.pos >= self.config.max_model_len:
            finish = "length"
        out = StepOutput(req.request_id, token=token, cached_tokens=cached)
        if lp is not None and req.logprobs is not None:
            chosen, top_ids, top_vals = lp
            out.logprob = float(chosen)
            n = min(req.logprobs, len(top_ids))
            if n > 0:
                out.top_logprobs = [
                    (int(top_ids[i]), float(top_vals[i])) for i in range(n)
                ]
        if finish is not None:
            out.finished = True
            out.finish_reason = finish
            self._record_outcome(seq, finish)
            self._release(seq)
        return [out]

    def _finish(self, seq: RunningSeq, reason: str) -> list[StepOutput]:
        self._record_outcome(seq, reason, error=(reason == "error"))
        self._release(seq)
        return [StepOutput(seq.req.request_id, finished=True, finish_reason=reason)]

    def _record_request_error(self, req: EngineRequest) -> None:
        """Outcome for a request that failed BEFORE a sequence existed
        (oversized prompt, unknown adapter, admission crash): an error is an
        SLO miss, so it must reach the goodput plane like any finish."""
        events.emit(
            "request.failed",
            request_id=req.request_id, trace_id=req.trace_id,
            tenant=req.tenant, priority=req.priority or "",
            reason="rejected",
        )
        events.JOURNAL.pin(req.request_id, "error")
        sink = self.outcome_sink
        if sink is None:
            return
        now = time.monotonic()
        try:
            sink(RequestOutcome(
                request_id=req.request_id,
                scenario=req.scenario,
                tenant=req.tenant,
                adapter=req.lora_name,
                prompt_tokens=len(req.token_ids),
                duration_s=max(0.0, now - req.enqueue_ts) if req.enqueue_ts else 0.0,
                finish_reason="error",
                error=True,
            ))
        except Exception:
            log.exception("outcome sink failed for %s", req.request_id)

    def _record_outcome(self, seq: RunningSeq, reason: str, error: bool = False) -> None:
        """Fold one finished sequence into the goodput plane (one
        RequestOutcome per natural finish; cancels and preemption re-queues
        never reach here). Sink failures must never fail the engine step."""
        req = seq.req
        now = time.monotonic()
        ttft = None
        if seq.first_token_wall and req.enqueue_ts:
            ttft = max(0.0, seq.first_token_wall - req.enqueue_ts)
        events.emit(
            "request.failed" if error else "request.finished",
            request_id=req.request_id, trace_id=req.trace_id,
            tenant=req.tenant, priority=req.priority or "",
            reason=reason, output_tokens=len(seq.generated),
            ttft_ms=round(ttft * 1e3, 3) if ttft is not None else None,
        )
        # forensics auto-pin: a request that errored or blew its TTFT/ITL
        # budget gets its event chain copied to the capture ring NOW, so
        # /debug/requests/{id} still reconstructs it after ring eviction
        if self.meter is not None:
            # consumed-vs-admitted delta: what the request ACTUALLY used,
            # against the (prompt + output budget) the QoS bucket charged
            self.meter.charge_tokens(req.tenant, "prompt", seq.prompt_len)
            self.meter.charge_tokens(req.tenant, "output", len(seq.generated))
        pin_reason = "error" if error else self._slo_pin_reason(seq, ttft)
        if pin_reason:
            events.JOURNAL.pin(req.request_id, pin_reason)
        sink = self.outcome_sink
        if sink is None:
            return
        try:
            sink(RequestOutcome(
                request_id=req.request_id,
                scenario=req.scenario,
                tenant=req.tenant,
                adapter=req.lora_name,
                queue_wait_s=seq.queue_wait_s,
                ttft_s=ttft,
                itl_s=tuple(seq.itl_gaps),
                prompt_tokens=seq.prompt_len,
                output_tokens=len(seq.generated),
                cached_tokens=seq.cached_len,
                duration_s=max(0.0, now - req.enqueue_ts) if req.enqueue_ts else 0.0,
                finish_reason=reason,
                error=error,
            ))
        except Exception:
            log.exception("outcome sink failed for %s", req.request_id)

    def _slo_pin_reason(self, seq: RunningSeq, ttft: Optional[float]) -> Optional[str]:
        """Did this finished sequence blow a configured TTFT/ITL budget?
        (the auto-pin verdict for the forensic capture ring)"""
        if self.slo is None:
            return None
        ttft_target = self.slo.targets.get("ttft")
        if ttft is not None and ttft_target is not None and ttft > ttft_target:
            return "ttft_over_budget"
        itl_target = self.slo.targets.get("itl")
        if itl_target is not None and any(g > itl_target for g in seq.itl_gaps):
            return "itl_over_budget"
        return None

    def _cancel_fetch(self, seq: RunningSeq) -> None:
        """Drop an in-flight remote-prefix pull. The fetch coroutine only
        RETURNS data (the scatter happens in _poll_fetches, which skips
        finished/evicted sequences), so cancelling here can never leave a
        write racing the pages' next owner."""
        if seq.fetch is not None:
            try:
                seq.fetch.fut.cancel()
            except Exception:
                pass
            seq.fetch = None

    def _release(self, seq: RunningSeq, count_finished: bool = True) -> None:
        seq.finished = True
        self._cancel_fetch(seq)
        self._free_draft(seq)
        self._release_lora(seq)
        self.allocator.free_sequence(seq.req.request_id)
        if seq.slot >= 0 and self.slots[seq.slot] is seq:
            self.slots[seq.slot] = None
        elif seq in self.adopted_waiting:
            self.adopted_waiting.remove(seq)
        if count_finished:
            self.finished_count += 1

    def _pick_victim(self, exclude: RunningSeq) -> Optional[RunningSeq]:
        # a MIGRATING_OUT sequence is never a preemption victim: requeueing
        # it locally while the destination continues the same stream would
        # fork the request into two generators
        candidates = [
            s for s in self.slots
            if s is not None and s is not exclude and not s.migrating
        ]
        if not candidates:
            return None
        if self.config.qos:
            # QoS victim order: lowest priority class first (batch lanes pay
            # for page pressure before standard, standard before critical),
            # most-recently-admitted within a class — so a noisy batch burst
            # can never preempt a critical stream while any lower lane runs
            victim = max(
                candidates,
                key=lambda s: (priority_rank(s.req.priority), s.admitted_order),
            )
        else:
            victim = max(candidates, key=lambda s: s.admitted_order)
        events.emit(
            "sched.victim_picked",
            request_id=victim.req.request_id, trace_id=victim.req.trace_id,
            tenant=victim.req.tenant, priority=victim.req.priority or "",
            candidates=len(candidates), qos=bool(self.config.qos),
        )
        return victim

    def _preempt(self, seq: RunningSeq) -> None:
        """Return a sequence to the waiting queue; its work restarts later
        (prefix cache usually recovers most of it). Callers must drain the
        pipeline first so seq.generated is complete."""
        log.info("preempting %s (page pressure)", seq.req.request_id)
        self.preempt_count += 1
        cls = seq.req.priority or "standard"
        self.qos_preempted[cls] = self.qos_preempted.get(cls, 0) + 1
        events.emit(
            "sched.preempted",
            request_id=seq.req.request_id, trace_id=seq.req.trace_id,
            tenant=seq.req.tenant, priority=seq.req.priority or "",
            generated=len(seq.generated), slot=seq.slot,
        )
        seq.finished = True  # stray in-flight snapshots must skip it
        self._cancel_fetch(seq)
        # the draft cache dies with the slot; re-admission rebuilds it from
        # the (prompt + generated) resume prompt at the first spec round
        self._free_draft(seq)
        # the adapter pin dies with the slot too — re-admission re-acquires
        # (the host copy is cached, so a hot-swap back is one scatter)
        self._release_lora(seq)
        self.allocator.free_sequence(seq.req.request_id)
        if seq.slot >= 0 and self.slots[seq.slot] is seq:
            self.slots[seq.slot] = None
        new_req = EngineRequest(
            request_id=seq.req.request_id,
            token_ids=list(seq.req.token_ids) + seq.generated,
            # queue-entry clock carries the ORIGINAL submission forward: the
            # resumed wait, TTFT, and goodput duration all bill from when the
            # client first enqueued — a preemption must never make a request
            # look FASTER than an uninterrupted run of the same work
            enqueue_ts=seq.req.enqueue_ts or time.monotonic(),
            trace_id=seq.req.trace_id,
            images=seq.req.images,
            mm_embeds=seq.req.mm_embeds,  # offsets are prompt-relative: still valid
            logprobs=seq.req.logprobs,
            # prior output starts where the ORIGINAL prompt ended (earlier
            # preemptions included: the original split carries forward)
            penalty_output_from=(
                seq.req.penalty_output_from
                if seq.req.penalty_output_from is not None
                else seq.prompt_len
            ),
            # mrope_pos covers the OLD prompt length only: left None so it is
            # recomputed over prompt+generated at re-admission (delta included)
            # already-generated tokens count against max_tokens on resume;
            # every other sampling field (penalties, seed, min_p, ...) carries
            sampling=dataclasses.replace(
                seq.req.sampling,
                max_tokens=max(1, seq.req.sampling.max_tokens - len(seq.generated)),
                min_tokens=max(0, seq.req.sampling.min_tokens - len(seq.generated)),
            ),
            eos_token_ids=seq.req.eos_token_ids,
            # the holder hint survives preemption: the matched prefix is a
            # prefix of the UNCHANGED original prompt, and if our own cache
            # kept the pages the min-advantage gate skips the re-fetch anyway
            kv_holder_addr=seq.req.kv_holder_addr,
            kv_holder_blocks=seq.req.kv_holder_blocks,
            lora_name=seq.req.lora_name,
            # QoS/goodput attribution must survive the requeue: the resumed
            # request bills the same tenant at the same priority class
            tenant=seq.req.tenant,
            scenario=seq.req.scenario,
            priority=seq.req.priority,
            # admitted tokens were billed at the FIRST admission; the resumed
            # request must not double-charge the tenant's admitted count
            cost_admitted=seq.req.cost_admitted,
        )
        self.waiting.appendleft(new_req)
