"""Paged KV block allocator with prefix caching and KV event emission.

The worker-side analogue of the reference's KV block manager
(reference: lib/llm/src/kv/{manager,reuse,reserved}.rs semantics) fused with
vLLM-style prefix caching, re-designed for the JAX engine:

  - physical page 0 is reserved as the null/trash page (masked writes and
    page-table padding target it — see dynamo_tpu/ops/attention.py)
  - full blocks are identified by their chained sequence hash
    (dynamo_tpu/llm/tokens.py); a completed block's page is registered in the
    prefix cache and can be shared (refcounted) by later sequences
  - refcount-0 cached pages form an LRU "reuse pool": they still serve prefix
    hits but are reclaimed when fresh pages run out
    (reference: lib/llm/src/kv/reuse.rs:50 AvailableBlocks priority reuse)
  - block store / evict emit KvCacheEvents for the KV router's global index
    (reference: lib/llm/src/kv_router/protocols.rs:35-100, publisher.rs:33-74)

Pure Python bookkeeping — device arrays never flow through here; the scheduler
translates page ids into jnp page tables.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Optional

from dynamo_tpu.llm.tokens import TokenBlock, TokenSequence
from dynamo_tpu.llm.kv_events import KvCacheEvent, StoredBlock
from dynamo_tpu.utils import get_logger

log = get_logger("engine.pages")


@dataclass
class SequencePages:
    """Page state for one live sequence."""

    seq_id: str
    pages: list[int] = field(default_factory=list)  # logical block i -> physical page
    shared_prefix_pages: int = 0  # leading pages refcounted from the prefix cache
    token_seq: Optional[TokenSequence] = None  # hashing state (block_size = page_size)
    registered_hashes: list[int] = field(default_factory=list)  # sequence hashes we cached

    @property
    def num_pages(self) -> int:
        return len(self.pages)


class PageAllocator:
    """Physical page allocator + prefix cache for one engine's KV cache."""

    def __init__(
        self,
        num_pages: int,
        page_size: int,
        event_sink: Optional[Callable[[KvCacheEvent], None]] = None,
        offload=None,  # Optional[HostKvPool]: host-DRAM tier (engine/offload.py)
    ):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is reserved)")
        self.num_pages = num_pages
        self.page_size = page_size
        self.event_sink = event_sink
        self.offload = offload
        # off-device blocks (host DRAM *or* disk tier): meta survives until
        # the block leaves its LAST tier, when the one removed event fires
        self._offloaded_meta: dict[int, StoredBlock] = {}
        self._free: list[int] = list(range(num_pages - 1, 0, -1))  # stack; page 0 reserved
        # sequence_hash -> physical page holding that full block
        self._cache: dict[int, int] = {}
        self._cache_meta: dict[int, StoredBlock] = {}  # seq_hash -> event payload
        self._refcount: dict[int, int] = {}  # physical page -> live users
        # refcount-0 cached blocks, LRU order (oldest first): seq_hash -> page
        self._reusable: OrderedDict[int, int] = OrderedDict()
        self._seqs: dict[str, SequencePages] = {}
        # stats
        self.cache_hit_blocks = 0
        self.cache_query_blocks = 0
        self.peak_used_pages = 0  # page-pool occupancy high-watermark
        #: optional utils/metering.MeterLedger + HBM bytes one page costs —
        #: set by the engine when metering is on. Ownership model: a page is
        #: owned by the (tenant, request_id) that first allocated it; prefix
        #: hits and reusable-pool parking never re-own (residency is the
        #: benefit the cache sells, so its cost stays attributed); demotions
        #: to the host tier carry the owner down the ladder.
        self.meter = None
        self.meter_page_bytes = 0
        self._seq_owner: dict[str, tuple] = {}  # seq_id -> (tenant, rid)

    # ------------- capacity -------------

    @property
    def free_pages(self) -> int:
        """Immediately + reclaimably free pages."""
        return len(self._free) + len(self._reusable)

    @property
    def used_pages(self) -> int:
        return (self.num_pages - 1) - len(self._free)

    @property
    def active_pages(self) -> int:
        """Pages referenced by live sequences."""
        return (self.num_pages - 1) - len(self._free) - len(self._reusable)

    def _pop_free_page(self) -> int:
        return self._pop_free_pages(1)[0]

    def _pop_free_pages(self, n: int) -> list[int]:
        """Take ``n`` pages: the free list first, then LRU reclaim from the
        refcount-0 reusable pool — with the whole reclaim batch offloaded to
        the host tier in ONE device gather (the per-block save path pays a
        dispatch + D2H round trip per page, which serializes directly into
        TTFT when a deep prompt allocates thousands of pages). Raises
        MemoryError (nothing taken) when both sources run dry."""
        if n <= len(self._free):
            out = [self._free.pop() for _ in range(n)]
        else:
            if n > len(self._free) + len(self._reusable):
                raise MemoryError("out of KV pages")
            out = [self._free.pop() for _ in range(len(self._free))]
            out.extend(self._reclaim_reusable(n - len(out)))
        if self.used_pages > self.peak_used_pages:
            self.peak_used_pages = self.used_pages
        return out

    def _meter_acquire(self, pages: list[int], owner) -> None:
        """Metering edge: ``pages`` became HBM-resident under ``owner``."""
        if self.meter is not None and self.meter_page_bytes > 0:
            for page in pages:
                self.meter.kv_acquire(
                    "hbm", page, self.meter_page_bytes, owner
                )

    def _meter_release(self, page: int):
        """Metering edge: ``page`` left HBM. Returns the owner (carried down
        the ladder by demotion sites)."""
        if self.meter is not None:
            return self.meter.kv_release("hbm", page)
        return None

    def _reclaim_reusable(self, n: int) -> list[int]:
        """Evict up to ``n`` LRU refcount-0 cached blocks; with a host tier
        configured their KV is offloaded (one batched gather) instead of
        dropped. Returns the freed pages."""
        victims: list[tuple[int, object, int]] = []  # (seq_hash, meta, page)
        while self._reusable and len(victims) < n:
            seq_hash, page = self._reusable.popitem(last=False)
            del self._cache[seq_hash]
            victims.append((seq_hash, self._cache_meta.pop(seq_hash), page))
        if not victims:
            return []
        # metering: every victim page leaves HBM here; the owners ride into
        # the host pool so demoted residency keeps charging its creator
        owners = {h: self._meter_release(p) for h, _, p in victims}
        removed = []
        if self.offload is not None:
            dropped = set(
                self.offload.save_many(
                    [(h, p) for h, _, p in victims], owners=owners
                )
            )
            meta_by_hash = {h: m for h, m, _ in victims}
            for h, m, _ in victims:
                if h not in dropped:
                    self._offloaded_meta[h] = m
            for victim in dropped:
                vm = meta_by_hash.get(victim) or self._offloaded_meta.pop(victim, None)
                if vm is not None:
                    removed.append(vm.block_hash)
        else:
            removed = [m.block_hash for _, m, _ in victims]
        if removed:
            self._emit(KvCacheEvent.removed(removed))
        return [p for _, _, p in victims]

    def drain_to_host(self, n: int) -> int:
        """Pressure-driven offload: move up to ``n`` of the coldest
        refcount-0 cached blocks to the host tier (one batched gather) and
        return their pages to the free list — so allocation bursts find
        fresh pages instead of paying the reclaim transfer at the moment of
        exhaustion. Returns the number of pages freed."""
        if self.offload is None or not self._reusable:
            return 0
        pages = self._reclaim_reusable(n)
        self._free.extend(pages)
        return len(pages)

    # ------------- events -------------

    def _emit(self, event: KvCacheEvent) -> None:
        if self.event_sink is not None:
            self.event_sink(event)

    # ------------- sequence lifecycle -------------

    def lookup_prefix(self, prompt_tokens: list[int], salt: int = 0) -> int:
        """Number of leading tokens already cached in ANY tier (block
        granularity), without allocating. Disagg routing's prefix-hit estimate.
        ``salt`` = the request's LoRA adapter uid (0 = base): adapter-specific
        prefixes live under salted chained hashes and never cross-hit."""
        ts = TokenSequence(prompt_tokens, self.page_size, salt=salt)
        hits = 0
        for block in ts.blocks:
            h = block.sequence_hash
            if h in self._cache or (
                self.offload is not None and self.offload.in_any_tier(h)
            ):
                hits += 1
            else:
                break
        return hits * self.page_size

    def cached_page(self, seq_hash: int) -> Optional[int]:
        """Physical page holding a cached block, or None. Blocks parked in the
        refcount-0 reusable pool still serve reads (the fleet prefix-cache
        pull server looks blocks up here; callers run on the engine thread,
        so lookup and the subsequent gather dispatch are atomic)."""
        return self._cache.get(seq_hash)

    def allocate_sequence(
        self, seq_id: str, prompt_tokens: list[int], salt: int = 0,
        owner: Optional[tuple] = None,
    ) -> tuple[int, SequencePages]:
        """Allocate pages for a prompt, reusing cached prefix blocks.

        Returns (cached_len, seq_state): the first cached_len tokens already
        have KV in shared pages and must NOT be recomputed (except the last
        token if the full prompt hits, so there is always something to prefill).
        ``salt`` folds a LoRA adapter uid into the chained block identity, so
        an adapter's KV (its k/v projections carry the adapter delta) never
        serves — or is served by — another adapter's identical token prefix.
        """
        if seq_id in self._seqs:
            raise ValueError(f"sequence {seq_id} already allocated")
        ts = TokenSequence(prompt_tokens, self.page_size, salt=salt)
        state = SequencePages(seq_id=seq_id, token_seq=ts)
        # metering owner for every page this sequence newly acquires (device
        # prefix hits keep their original owner; restored pages re-own to
        # the restoring request — its prompt is why the bytes came back up)
        self._seq_owner[seq_id] = owner

        # 1. device-tier prefix hits: chain of full blocks present in cache
        device_hits: list[int] = []
        for block in ts.blocks:
            page = self._cache.get(block.sequence_hash)
            if page is None:
                break
            device_hits.append(page)

        # 2. host-tier hits continuing the chain: each costs a fresh device
        # page + a host->device block copy, but no recompute
        host_hit_hashes: list[int] = []
        if self.offload is not None:
            for block in ts.blocks[len(device_hits) :]:
                if block.sequence_hash in self.offload:
                    host_hit_hashes.append(block.sequence_hash)
                else:
                    break

        self.cache_query_blocks += len(ts.blocks)
        self.cache_hit_blocks += len(device_hits) + len(host_hit_hashes)

        # Never consume the *entire* prompt from cache: leave at least the last
        # token to prefill so the model produces next-token logits.
        total_hit = len(device_hits) + len(host_hit_hashes)
        if total_hit and total_hit * self.page_size >= len(prompt_tokens):
            if host_hit_hashes:
                host_hit_hashes.pop()
            else:
                device_hits.pop()

        for page in device_hits:
            self._ref_page(page)
        state.pages.extend(device_hits)
        state.shared_prefix_pages = len(device_hits)

        try:
            # host-tier blocks: fresh pages first, then ONE batched inject for
            # the whole prefix restore (the per-block path pays a dispatch +
            # transfer round trip per block, serialized into TTFT);
            # re-registered on-device so later sequences share them again
            host_pairs: list[tuple[int, int]] = []
            if host_hit_hashes:
                fresh = self._pop_free_pages(len(host_hit_hashes))
                self._meter_acquire(fresh, owner)
                for seq_hash, page in zip(host_hit_hashes, fresh):
                    self._refcount[page] = 1
                    state.pages.append(page)
                    host_pairs.append((seq_hash, page))
            hit_hashes = self.offload.load_many(host_pairs) if host_pairs else set()
            # only the contiguous restored prefix counts as cached: a block may
            # have been LRU-dropped from the host pool while its destination
            # page was being allocated (a save() can evict — load_many injects
            # the leading run only); pages past the first miss just get
            # overwritten by the prefill recompute
            restored = 0
            for seq_hash, page in host_pairs:
                if seq_hash not in hit_hashes:
                    break
                restored += 1
                self.offload.discard(seq_hash)
                meta = self._offloaded_meta.pop(seq_hash, None)
                if meta is not None:
                    self._cache[seq_hash] = page
                    self._cache_meta[seq_hash] = meta
                    state.registered_hashes.append(seq_hash)
                else:
                    # a host block with no tracked meta just left its LAST
                    # tier via discard() without re-registering on device:
                    # advertise the removal so no router ever points a fetch
                    # at a block this worker no longer holds (the block's
                    # engine identity IS its chained sequence hash)
                    self._emit(KvCacheEvent.removed([seq_hash]))

            if restored:
                from dynamo_tpu.utils import events

                events.emit(
                    "offload.restore", request_id=seq_id,
                    blocks=restored, host_hits=len(host_pairs),
                )

            cached_len = (len(device_hits) + restored) * self.page_size

            # 3. fresh pages for the rest of the prompt — one batched take
            # (the reclaim leg offloads its whole victim batch in one gather)
            total_pages_needed = -(-len(prompt_tokens) // self.page_size)
            need = total_pages_needed - len(state.pages)
            if need > 0:
                fresh = self._pop_free_pages(need)
                self._meter_acquire(fresh, owner)
                for page in fresh:
                    self._refcount[page] = 1
                    state.pages.append(page)
        except MemoryError:
            self._rollback(state)
            self._seq_owner.pop(seq_id, None)
            raise

        # Blocks completed by the prompt itself (all but what the prefix cache
        # already holds) get registered once their KV is actually computed —
        # the scheduler calls commit_prefilled().
        self._seqs[seq_id] = state
        return cached_len, state

    def promote_restored(self, seq_id: str, base_block: int, blocks: int) -> None:
        """A disk restore scattered ``blocks`` wire blocks into this
        sequence's pages starting at logical block ``base_block`` — promote
        them disk->device: drop the disk copies and re-register each block
        in the device prefix cache under its preserved meta, so later
        sequences share them again. No ``stored`` event fires (the block
        never emitted ``removed`` — its advertised identity stayed valid
        across the whole HBM->host->disk->HBM round trip)."""
        state = self._seqs.get(seq_id)
        disk = self.offload.disk if self.offload is not None else None
        if state is None or disk is None:
            return
        for i in range(base_block, base_block + blocks):
            if i >= len(state.pages) or i >= len(state.token_seq.blocks):
                break
            h = state.token_seq.blocks[i].sequence_hash
            disk.discard(h)
            if h in self._cache:
                continue  # another writer registered it while we restored
            meta = self._offloaded_meta.pop(h, None)
            if meta is not None:
                self._cache[h] = state.pages[i]
                self._cache_meta[h] = meta
                state.registered_hashes.append(h)
            else:
                # restored with no tracked meta: it just left its last tier
                # without re-registering — advertise the removal (same
                # contract as the host-restore leg above)
                self._emit(KvCacheEvent.removed([h]))

    def drop_disk_blocks(self, hashes: list) -> None:
        """Blocks whose disk files failed verification (corrupt/truncated)
        just left their last tier: discard the index entries and emit the
        one truthful ``removed`` per block."""
        disk = self.offload.disk if self.offload is not None else None
        if disk is None:
            return
        removed = []
        for h in hashes:
            disk.discard(h)
            meta = self._offloaded_meta.pop(h, None)
            if meta is not None and h not in self._cache:
                removed.append(meta.block_hash)
        if removed:
            self._emit(KvCacheEvent.removed(removed))

    def _rollback(self, state: SequencePages) -> None:
        """Undo a failed allocation. Cache-registered pages (shared prefix hits
        and host-tier reloads) return to the reusable pool — their on-device
        data is still valid; only uncached fresh pages go back to the free list."""
        pages = set(state.pages)
        page_to_hash = {p: h for h, p in self._cache.items() if p in pages}
        for page in state.pages:
            self._unref_page(page, evictable_hash=page_to_hash.get(page))
        state.pages.clear()

    def commit_prefilled(self, seq_id: str, prompt_len: int) -> None:
        """Register all full blocks covered by the (now computed) prompt KV."""
        state = self._seqs[seq_id]
        full_blocks = prompt_len // self.page_size
        for i in range(state.shared_prefix_pages, full_blocks):
            block = state.token_seq.blocks[i]
            self._register_block(state, block, state.pages[i])

    def ensure_capacity(self, seq_id: str, length: int) -> bool:
        """Make sure pages exist to hold `length` tokens. False if OOM."""
        state = self._seqs[seq_id]
        needed = -(-length // self.page_size)
        if state.num_pages >= needed:
            return True
        try:
            fresh = self._pop_free_pages(needed - state.num_pages)
        except MemoryError:
            return False
        self._meter_acquire(fresh, self._seq_owner.get(seq_id))
        for page in fresh:
            self._refcount[page] = 1
            state.pages.append(page)
        return True

    def append_token(self, seq_id: str, token: int) -> None:
        """Track a decoded token; registers blocks ONE TOKEN AFTER they fill.

        A decode-written block's last row's KV only exists once the
        block-following token has been fed (token ``p`` is sampled from fed
        position ``p-1``, so appending ``p`` proves KV through ``p-1``).
        Registering at fill time used to advertise — locally and through KV
        events to the radix/fleet caches — a block whose final position
        reads garbage to any sequence extending past it: forever if the
        writer finished exactly at the block boundary (a multi-turn
        conversation extending a cached response, a migrated history being
        re-admitted), or transiently if a reader raced the writer's next
        window. Deferring by one token makes every advertised block's KV
        actually complete; a sequence that ends at a block boundary simply
        never registers its final block (its KV is incomplete by
        construction and the prefill recompute is one block)."""
        state = self._seqs[seq_id]
        state.token_seq.push_token(token)
        n = len(state.token_seq)
        # the newest token (index n-1) proves KV through n-2: the last block
        # fully below that bound is safe to register
        if (n - 1) % self.page_size == 0 and n > self.page_size:
            idx = (n - 1) // self.page_size - 1
            if idx < len(state.pages):
                self._register_block(
                    state, state.token_seq.blocks[idx], state.pages[idx]
                )

    def free_sequence(self, seq_id: str) -> None:
        """Release a sequence. Full cached blocks become reusable (LRU);
        uncached pages return to the free list immediately."""
        state = self._seqs.pop(seq_id)
        self._seq_owner.pop(seq_id, None)
        page_to_hash = {}
        for i, block in enumerate(state.token_seq.blocks):
            if i < len(state.pages) and block.sequence_hash in self._cache and self._cache[block.sequence_hash] == state.pages[i]:
                page_to_hash[state.pages[i]] = block.sequence_hash
        for page in state.pages:
            self._unref_page(page, evictable_hash=page_to_hash.get(page))

    # ------------- internals -------------

    def _ref_page(self, page: int) -> None:
        self._refcount[page] = self._refcount.get(page, 0) + 1
        # a cached page in the reusable pool that regains a user leaves the pool
        for seq_hash, p in list(self._reusable.items()):
            if p == page:
                del self._reusable[seq_hash]
                break

    def _unref_page(self, page: int, evictable_hash: Optional[int]) -> None:
        rc = self._refcount.get(page, 0) - 1
        if rc > 0:
            self._refcount[page] = rc
            return
        self._refcount.pop(page, None)
        if evictable_hash is not None and self._cache.get(evictable_hash) == page:
            self._reusable[evictable_hash] = page  # cached, reclaimable, LRU tail
            self._reusable.move_to_end(evictable_hash)
            # metering: a reusable-pool page stays resident and keeps
            # charging its owner — no edge until reclaim
        else:
            self._meter_release(page)
            self._free.append(page)

    def _register_block(self, state: SequencePages, block: TokenBlock, page: int) -> None:
        if block.sequence_hash in self._cache:
            return  # dedupe: first writer wins, our copy stays private
        self._cache[block.sequence_hash] = page
        meta = StoredBlock(
            block_hash=block.sequence_hash,
            tokens_hash=block.block_hash,
            parent_hash=block.parent_sequence_hash,
        )
        self._cache_meta[block.sequence_hash] = meta
        state.registered_hashes.append(block.sequence_hash)
        self._emit(KvCacheEvent.stored(parent_hash=block.parent_sequence_hash, blocks=[meta]))
