"""Host-DRAM KV offload tier.

The TPU analogue of the reference's multi-tier KV block manager (reference:
lib/llm/src/kv/{manager,storage,layer}.rs — CUDA pinned-host staging +
copy streams; docs/architecture.md:91-96 claims +40% TTFT from system-memory
offload). On TPU-VM the host tier is plain numpy arrays in process memory;
device<->host movement goes through the runner's jitted block gather/scatter
(dynamo_tpu/engine/model_runner.py extract_pages/inject_pages).

Flow:
  - when the device prefix cache must reclaim a refcount-0 cached block, the
    block's KV is saved to the host pool instead of being dropped
  - allocate_sequence() consults the host pool after device-cache misses:
    hits are injected back into freshly-allocated device pages and count as
    cached prefix (no recompute)
  - the host pool is LRU-bounded; a victim DEMOTES to the disk tier
    (engine/kv_store.py) when one is attached, else it is dropped. Either
    way, `save`/`save_many` return only the hashes that left their LAST
    tier — the only blocks allowed to emit the `removed` KV event, so the
    prefix cache, router, and fleet state stay truthful across all three
    rungs of the ladder.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Optional

import numpy as np

from dynamo_tpu.utils import events, get_logger, tracing

log = get_logger("engine.offload")


def resolve_host_capacity_blocks(
    blocks: int, budget_bytes: int, page_bytes: int
) -> int:
    """Host-tier capacity in blocks from the two config knobs.

    ``budget_bytes`` divides by the model's ACTUAL per-page wire cost
    (model.kv_page_bytes — int8 caches store int8 pages + scale planes on
    the host too, ~half the bf16 bytes), so the same DRAM budget holds ~2x
    blocks under an int8 KV cache instead of silently assuming bf16. When
    both knobs are set the larger capacity wins. Pure arithmetic — the
    PR-8-follow-up unit tests pin it down."""
    from_bytes = budget_bytes // max(1, page_bytes) if budget_bytes > 0 else 0
    return max(blocks, from_bytes)


class HostKvPool:
    """LRU pool of KV blocks in host DRAM, keyed by chained sequence hash."""

    def __init__(self, runner, capacity_blocks: int = 0, block_bytes: int = 0):
        self.runner = runner
        self.capacity_blocks = capacity_blocks
        # per-block wire bytes at the ACTUAL cache dtype (telemetry: the
        # resident-bytes gauge; 0 = unknown, gauges render zero)
        self.block_bytes = block_bytes
        self._blocks: OrderedDict[int, np.ndarray] = OrderedDict()  # seq_hash -> [L,2,1,ps,H,D]
        #: optional engine/kv_store.DiskKvStore — the tier below this one;
        #: LRU victims demote into it instead of dropping
        self.disk = None
        self.saves = 0
        self.loads = 0
        self.drops = 0
        self.transfer_s = 0.0  # device<->host block movement (both directions)
        #: optional utils/metering.MeterLedger — byte-residency edges: blocks
        #: acquire under the owner the allocator hands down on demote, LRU
        #: victims release (carrying the owner further down to the disk tier)
        self.meter = None

    @property
    def bytes_resident(self) -> int:
        return len(self._blocks) * self.block_bytes

    def __len__(self) -> int:
        return len(self._blocks)

    def __contains__(self, seq_hash: int) -> bool:
        return seq_hash in self._blocks

    def in_any_tier(self, seq_hash: int) -> bool:
        """Membership across host DRAM AND the disk tier below it — the
        question ``lookup_prefix`` asks (any tier can still answer)."""
        return seq_hash in self._blocks or (
            self.disk is not None and seq_hash in self.disk
        )

    def _demote(self, victim: int, block, owner=None) -> list[int]:
        """One LRU victim leaves host DRAM: spill to disk when a disk tier
        is attached (returns only the hashes that left their LAST tier —
        disk-budget evictions), else the victim is simply gone. ``owner`` is
        the metering owner carried down the ladder."""
        if self.disk is None:
            return [victim]
        return self.disk.spill(victim, block, owner=owner)

    def _emit_spills(self, spills_before: int) -> None:
        """Journal the host->disk demotions a save batch caused (one batched
        event: demotion runs inside the eviction loop, per-victim events
        would swamp the ring under pressure)."""
        if self.disk is None:
            return
        n = self.disk.spills - spills_before
        if n > 0:
            events.emit("offload.disk_spill", request_id="", blocks=n)

    def save(self, seq_hash: int, page_id: int, owner=None) -> list[int]:
        """Copy a device page to host. Returns seq hashes that left their
        last tier (for removed-event emission)."""
        if self.capacity_blocks <= 0:
            return [seq_hash]  # offload disabled: block is simply gone
        t0 = time.monotonic()
        data = self.runner.extract_pages(np.asarray([page_id], np.int32))
        self.transfer_s += time.monotonic() - t0
        self._blocks[seq_hash] = data
        self._blocks.move_to_end(seq_hash)
        if self.meter is not None:
            self.meter.kv_acquire("host", seq_hash, self.block_bytes, owner)
        self.saves += 1
        dropped = []
        spills0 = self.disk.spills if self.disk is not None else 0
        while len(self._blocks) > self.capacity_blocks:
            victim, block = self._blocks.popitem(last=False)
            victim_owner = (
                self.meter.kv_release("host", victim)
                if self.meter is not None else None
            )
            dropped.extend(self._demote(victim, block, owner=victim_owner))
            self.drops += 1
        self._emit_spills(spills0)
        return dropped

    def save_many(self, pairs: list[tuple[int, int]],
                  owners: Optional[dict] = None) -> list[int]:
        """Copy a batch of device pages to host with ONE device gather (the
        pressure-eviction path: per-block save() pays a dispatch + D2H round
        trip per page, serialized into whatever allocation needed the pages).
        ``owners`` maps seq_hash -> metering owner handed down by the
        allocator. Returns seq hashes that left their last tier
        (removed-event emission)."""
        if self.capacity_blocks <= 0:
            return [h for h, _ in pairs]
        if not pairs:
            return []
        from dynamo_tpu.quant.kv import wire_split

        axis = getattr(getattr(self.runner, "model", None), "wire_n_axis", 2)
        t0 = time.monotonic()
        data = self.runner.extract_pages(
            np.asarray([p for _, p in pairs], np.int32)
        )
        blocks = wire_split(data, axis, len(pairs))
        dt = time.monotonic() - t0
        self.transfer_s += dt
        tracing.record_span("engine.kv_offload.save", t0, duration=dt,
                            attrs={"blocks": len(pairs)})
        for (seq_hash, _), block in zip(pairs, blocks):
            self._blocks[seq_hash] = block
            self._blocks.move_to_end(seq_hash)
            if self.meter is not None:
                self.meter.kv_acquire(
                    "host", seq_hash, self.block_bytes,
                    (owners or {}).get(seq_hash),
                )
        self.saves += len(pairs)
        dropped = []
        spills0 = self.disk.spills if self.disk is not None else 0
        while len(self._blocks) > self.capacity_blocks:
            victim, block = self._blocks.popitem(last=False)
            victim_owner = (
                self.meter.kv_release("host", victim)
                if self.meter is not None else None
            )
            dropped.extend(self._demote(victim, block, owner=victim_owner))
            self.drops += 1
        self._emit_spills(spills0)
        return dropped

    def load(self, seq_hash: int, page_id: int) -> bool:
        """Inject a host block into a device page. True on hit."""
        data = self._blocks.get(seq_hash)
        if data is None:
            return False
        self._blocks.move_to_end(seq_hash)
        t0 = time.monotonic()
        self.runner.inject_pages(np.asarray([page_id], np.int32), data)
        self.transfer_s += time.monotonic() - t0
        self.loads += 1
        return True

    def load_many(self, pairs: list[tuple[int, int]]) -> set[int]:
        """Inject host blocks into device pages with ONE device call.

        The per-block path pays a full dispatch + host->device transfer round
        trip per block — on a prefix-restore of N blocks that serializes N
        round trips directly into TTFT. Only the CONTIGUOUS leading run of
        hits is injected (a block may have been LRU-dropped between the
        caller's membership check and this call — e.g. by a save() triggered
        while allocating the destination pages — and blocks past the first
        miss can't count as cached prefix anyway). Returns the hit hashes."""
        hits: list[tuple[int, int]] = []
        for h, p in pairs:
            if h not in self._blocks:
                break
            hits.append((h, p))
        if not hits:
            return set()
        from dynamo_tpu.quant.kv import wire_concat

        axis = getattr(self.runner.model, "wire_n_axis", 2)
        # the batch is padded to a power of two inside inject_pages_bucketed
        # (shared with the streamed-disagg part scatter) so the donated
        # scatter compiles a handful of shapes, not one per prefix length
        n = len(hits)
        t0 = time.monotonic()
        # int8 caches store {"q","s"} wire dicts (page data + scale plane,
        # half the host bytes per block); wire_concat maps over both leaves
        data = wire_concat([self._blocks[h] for h, _ in hits], axis=axis)
        ids = np.asarray([p for _, p in hits], np.int32)
        self.runner.inject_pages_bucketed(ids, data, axis=axis)
        dt = time.monotonic() - t0
        self.transfer_s += dt
        tracing.record_span("engine.kv_offload.restore", t0, duration=dt,
                            attrs={"blocks": n})
        for h, _ in hits:
            self._blocks.move_to_end(h)
        self.loads += n
        return {h for h, _ in hits}

    def peek(self, seq_hash: int):
        """Read a host block without device movement (the fleet prefix-cache
        pull server's host-tier leg). Bumps LRU recency — a block peers keep
        pulling is a block worth keeping. The returned array is stored-once /
        never mutated, so handing out the reference is safe even if the pool
        later LRU-drops the entry mid-serialization."""
        data = self._blocks.get(seq_hash)
        if data is not None:
            self._blocks.move_to_end(seq_hash)
        return data

    def discard(self, seq_hash: int) -> None:
        if self._blocks.pop(seq_hash, None) is not None:
            if self.meter is not None:
                self.meter.kv_release("host", seq_hash)
