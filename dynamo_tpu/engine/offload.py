"""Host-DRAM KV offload tier.

The TPU analogue of the reference's multi-tier KV block manager (reference:
lib/llm/src/kv/{manager,storage,layer}.rs — CUDA pinned-host staging +
copy streams; docs/architecture.md:91-96 claims +40% TTFT from system-memory
offload). On TPU-VM the host tier is plain numpy arrays in process memory;
device<->host movement goes through the runner's jitted block gather/scatter
(dynamo_tpu/engine/model_runner.py extract_pages/inject_pages).

Flow:
  - when the device prefix cache must reclaim a refcount-0 cached block, the
    block's KV is saved to the host pool instead of being dropped
  - allocate_sequence() consults the host pool after device-cache misses:
    hits are injected back into freshly-allocated device pages and count as
    cached prefix (no recompute)
  - the host pool is LRU-bounded; dropping a block there emits the `removed`
    KV event (the block is now gone from every tier)
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional

import numpy as np

from dynamo_tpu.utils import get_logger

log = get_logger("engine.offload")


class HostKvPool:
    """LRU pool of KV blocks in host DRAM, keyed by chained sequence hash."""

    def __init__(self, runner, capacity_blocks: int = 0):
        self.runner = runner
        self.capacity_blocks = capacity_blocks
        self._blocks: OrderedDict[int, np.ndarray] = OrderedDict()  # seq_hash -> [L,2,1,ps,H,D]
        self.saves = 0
        self.loads = 0
        self.drops = 0

    def __len__(self) -> int:
        return len(self._blocks)

    def __contains__(self, seq_hash: int) -> bool:
        return seq_hash in self._blocks

    def save(self, seq_hash: int, page_id: int) -> list[int]:
        """Copy a device page to host. Returns seq hashes dropped from the pool
        (for removed-event emission)."""
        if self.capacity_blocks <= 0:
            return [seq_hash]  # offload disabled: block is simply gone
        data = self.runner.extract_pages(np.asarray([page_id], np.int32))
        self._blocks[seq_hash] = data
        self._blocks.move_to_end(seq_hash)
        self.saves += 1
        dropped = []
        while len(self._blocks) > self.capacity_blocks:
            victim, _ = self._blocks.popitem(last=False)
            dropped.append(victim)
            self.drops += 1
        return dropped

    def load(self, seq_hash: int, page_id: int) -> bool:
        """Inject a host block into a device page. True on hit."""
        data = self._blocks.get(seq_hash)
        if data is None:
            return False
        self._blocks.move_to_end(seq_hash)
        self.runner.inject_pages(np.asarray([page_id], np.int32), data)
        self.loads += 1
        return True

    def discard(self, seq_hash: int) -> None:
        self._blocks.pop(seq_hash, None)
