"""The native JAX serving engine: paged KV cache, continuous batching, TP over a
device mesh. Fills the slot the reference delegates to external GPU engines
(reference: lib/llm/src/engines/, SURVEY.md §7 step 3)."""

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.sampling import SamplingParams


async def build_async_engine(config: EngineConfig):
    from dynamo_tpu.engine.engine import AsyncJaxEngine

    engine = AsyncJaxEngine(config)
    await engine.start()
    return engine
