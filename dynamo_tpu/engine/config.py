"""Engine configuration."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class EngineConfig:
    model_id: str = "tiny"
    # paged KV cache; page_size doubles as the KV block size for hashing/routing
    page_size: int = 16
    num_pages: int = 512  # includes the reserved null page 0
    max_seqs: int = 8  # decode batch slots
    max_model_len: int = 2048
    prefill_buckets: tuple = (64, 128, 256, 512)  # padded prefill chunk lengths
    # long context — page-table width ladder (in PAGES). Every dispatch used
    # to pad page tables to the dense max_pages_per_seq width; at 128K/page 16
    # that is 8192 entries of H2D + gather per call even for a 200-token
    # chat. With a ladder, each sequence's table is padded only to its
    # current pow2 bucket, so short sequences keep their narrow traces and
    # only deep sequences pay wide gathers (one jit variant per width,
    # compiled via the warmup machinery). () = auto: min(128,
    # max_pages_per_seq) doubling up to max_pages_per_seq — a single width
    # (the pre-ladder behavior) whenever max_pages_per_seq <= 128.
    page_table_buckets: tuple = ()
    # depth-aware chunked prefill: a chunk's attention work scales with
    # chunk_len * context_depth, so fixed-size chunks get linearly slower as
    # prefill advances into a long prompt — starving colocated decode windows
    # and bloating per-chunk latency. The planner shrinks the chunk bucket
    # once depth * chunk would exceed max_prefill_chunk * prefill_flat_depth
    # (keeping per-chunk work roughly flat past that point, floored at the
    # smallest bucket). The default holds full-size chunks through the first
    # ~8K of context, so short-context configs chunk exactly as before.
    # 0 disables (always max_prefill_chunk).
    prefill_flat_depth: int = 8192
    tp: int = 1  # tensor-parallel degree over the mesh
    # sequence-parallel degree: >1 runs whole-prompt prefill as ring attention
    # over an "sp" mesh axis (long-context path; decode is unaffected).
    # Composes with tp (each tp head shard runs its own sp ring on the
    # (sp, tp) mesh); not with pp.
    sp: int = 1
    # pipeline-parallel stages: >1 shards the layer stack (and its KV pages)
    # over a "pp" mesh axis and runs GPipe microbatch rotation for both
    # prefill and decode (dynamo_tpu/parallel/pipeline.py). Composes with tp
    # (Megatron head split inside each stage on the (pp, tp) mesh); not with
    # sp. Requires num_layers % pp == 0.
    pp: int = 1
    # weight-only quantization mode applied at model-load time:
    #   None      — serve at the model's native dtype (bf16)
    #   "int8_wo" — big linear weights stored int8 + per-output-channel f32
    #               scales, dequantized inside the matmul; embeddings /
    #               lm_head / norms / routers stay bf16. Halves the weight
    #               HBM stream the decode roofline is made of
    #               (dynamo_tpu/quant/int8.py).
    quantize: str | None = None
    # KV cache storage dtype:
    #   None / "bf16" — pages at the model's native dtype
    #   "int8"        — pages stored int8 with one f32 scale per (page,
    #                   token row) (dynamo_tpu/quant/kv.py): halves the
    #                   attention HBM stream on both kernel families, ~2x
    #                   pages at the same HBM budget, half the disagg wire /
    #                   host-offload bytes. Composes with `quantize` (weights
    #                   and cache quantize independently). Llama-family
    #                   pools only (MLA's latent cache raises); not yet
    #                   composable with pp (the stage-sharded pool split).
    kv_cache_dtype: str | None = None
    # speculative decoding (dynamo_tpu/spec/): verify k draft tokens plus
    # one bonus token in ONE multi-query forward pass, advancing 1..k+1
    # tokens per round with no quality change. Two proposer kinds:
    #   "ngram:k"            — prompt-lookup over the sequence's own history
    #                          (incremental suffix index; repetition-heavy
    #                          workloads only)
    #   "draft:<model>:<k>"  — a second, smaller registry model drafts k
    #                          tokens per round in one batched on-device
    #                          dispatch with its own paged KV pool; real
    #                          draft probabilities make temperature>0
    #                          acceptance the exact Leviathan/Chen rule.
    #                          The draft loads with this engine's quantize /
    #                          kv_cache_dtype (int8 weights + int8 KV
    #                          compose).
    # None = classic one-token decode. Requests with penalties, logprobs,
    # min_tokens, or images fall back to the classic decode windows
    # automatically.
    speculative: str | None = None
    # multi-LoRA multiplexing (dynamo_tpu/lora/): adapter specs served by
    # this engine as ``<base>:<name>`` model names. Each spec is ``name``
    # (deterministic synthetic adapter — tests/bench), ``name=<dir>`` (the
    # canonical npz layer-stacked format), or ``name=random:<seed>``.
    # Adapters load into device-resident stacked pools [L, max_loras+1, ...]
    # and a mixed-adapter batch decodes in ONE gathered dispatch
    # (y += scale * (x @ A[ids]) @ B[ids]; slot 0 = the zero adapter for
    # base-only lanes). Non-resident adapters load asynchronously (their
    # requests wait; everyone else keeps serving) and LRU-evict to host.
    # () = LoRA disabled (no pool, traces unchanged).
    lora_adapters: tuple = ()
    # device adapter slots (excluding the reserved zero slot): more adapters
    # than slots multiplex through LRU eviction/hot-swap
    max_loras: int = 4
    # pool rank: adapters with smaller r zero-pad (exact); larger r rejected
    lora_rank: int = 8
    # cross-process disaggregation data plane (dynamo_tpu/disagg/dataplane.py):
    # stream KV to the decode worker per finished prefill chunk (v2 multi-part
    # wire protocol) instead of one monolithic post-prefill send. Streaming
    # overlaps the D2H staging + socket transfer of chunk i with chunk i+1's
    # compute, so the decode side holds most KV bytes by the time the
    # completion notification lands. False = legacy single-payload send.
    kv_stream: bool = True
    # parallel data-plane connections per destination; parts stripe across
    # lanes so one long prompt's multi-MB parts never head-of-line-block
    # other requests' transfers behind a single per-destination socket
    kv_stream_lanes: int = 2
    # fleet-wide prefix cache (disagg/prefix_fetch.py): when the KV router
    # attaches a remote prefix holder to a request (kv_holder_addr/blocks),
    # pull the matching KV pages from that peer over the dataplane instead of
    # recomputing them. The sequence waits in a FETCHING_KV state bounded by
    # prefix_fetch_timeout_s; any failure (timeout, dead peer, "gone")
    # degrades to recompute — never an error to the client.
    prefix_fetch: bool = True
    prefix_fetch_timeout_s: float = 5.0
    # live sequence migration (disagg/migrate.py): this engine may hand its
    # in-flight sequences to a peer mid-decode (drain/rebalance) and adopt a
    # peer's. The committed KV rides the pull dataplane via the seq_handoff
    # kind; a failed handoff resumes locally / recomputes from history, so
    # migration is never worse than preempt+recompute. False = the engine
    # refuses adoptions and drain degrades to attrition (and a draining
    # frontend answers a retriable 503 instead).
    migration: bool = True
    # deadline belt on one handoff: the destination's KV pull AND the
    # source's wait for the destination's first continuation token are both
    # bounded by this — on expiry the source resumes decoding locally
    migration_timeout_s: float = 10.0
    # only fetch when the holder's advantage over the local prefix cache is at
    # least this many blocks (a one-block pull rarely beats its own overhead)
    prefix_fetch_min_blocks: int = 1
    # multi-tenant QoS (utils/qos.py): priority-class scheduling — admission
    # order by class, priority weights composed with the prefill fairness
    # cap, preemption victims lowest-class-first, and a waiting critical
    # request may evict a lower-class lane (preferring live migration over
    # preempt+recompute when a peer can adopt). False = classes ignored:
    # pure FIFO admission and recency-only victims (the pre-QoS behavior,
    # and the bench's isolation-off arm).
    qos: bool = True
    # how long a critical request must sit queued with no free slot before
    # the scheduler evicts a lower-class lane for it (the anti-thrash gate)
    qos_preempt_wait_ms: float = 250.0
    worker_id: str = "worker-0"
    # SLO targets (milliseconds; None = untargeted). With any target set the
    # engine attaches an SloTracker (utils/slo.py) to the scheduler: rolling
    # TTFT/queue-wait percentiles + error-budget gauges ride worker stats and
    # /metrics. The DYNTPU_SLO_TTFT_MS / DYNTPU_SLO_ITL_MS /
    # DYNTPU_SLO_QUEUE_WAIT_MS env knobs fill unset fields.
    slo_ttft_ms: float | None = None
    slo_itl_ms: float | None = None
    # fraction of pages that must stay free for decode growth before admitting
    # a new sequence (simple admission control)
    watermark: float = 0.05
    # host-DRAM KV offload tier capacity in blocks (0 = disabled)
    host_cache_blocks: int = 0
    # host-DRAM KV tier budget in BYTES (0 = unset): resolved to blocks at
    # engine init using the model's ACTUAL per-page wire cost
    # (model.kv_page_bytes — an int8 cache's host blocks are int8 pages +
    # scale planes, ~half the bf16 bytes, so the same DRAM budget holds ~2x
    # blocks). When both knobs are set the larger resolved capacity wins;
    # sizing by bytes is the one that stays truthful across kv_cache_dtype.
    host_cache_bytes: int = 0
    # disk KV tier budget in BYTES (0 = disabled; requires a host tier —
    # the ladder demotes HBM -> host -> disk, never skips a rung). Host-pool
    # LRU victims spill to disk int8-compressed (engine/kv_store.py), so a
    # disk byte holds ~2x the bf16 context; restores ride the FETCHING_KV
    # deferred-admission path and never block the engine loop.
    disk_cache_bytes: int = 0
    # where the disk tier's block files live ("" = the DYNTPU_KV_DISK_DIR
    # env var, else a fresh tempdir owned — and cleaned — by the store)
    disk_cache_dir: str = ""
    # pressure-driven host offload (host_cache_blocks > 0 only): once page-
    # pool occupancy crosses this fraction, the scheduler proactively drains
    # the coldest refcount-0 cached blocks to the host tier in BATCHED saves
    # (one device gather per batch) — keeping the free list ahead of decode
    # growth so long-running sequences hit batched restores instead of
    # per-block reclaim round trips or whole-sequence preempt+recompute.
    # >= 1.0 disables the proactive drain (reclaim still batches on demand).
    offload_watermark: float = 0.90
    offload_drain_batch: int = 32
    # decode steps fused into one device call (lax.scan over steps with the
    # sampled-token feedback kept on device); amortizes dispatch + host<->device
    # transfer overhead. 1 = classic one-step decode. Streaming granularity and
    # worst-case wasted decode past EOS both scale with this.
    decode_steps: int = 8
    # decode windows dispatched ahead of result materialization (dispatch-ahead
    # pipelining; the token feedback lives on device so window N+1 never waits
    # for window N's tokens to reach the host). 1 = fully synchronous.
    pipeline_depth: int = 3
    # cross-request prefill packing: chunks of up to this many DISTINCT
    # sequences ride one prefill call (one weight pass). The effective lane
    # count per bucket is row-budgeted by lanes_for() (see its r5-measured
    # ~1024-row rationale). 1 = disabled (per-request prefill).
    prefill_lanes: int = 4
    # packed prefill calls dispatched ahead of result materialization (the
    # prefill analogue of pipeline_depth): call N+1's host prep + dispatch
    # overlap call N's device time, so the per-call fixed cost
    # (tools/profile_prefill.py) stops serializing with the kernel. 1 =
    # strict reconcile-before-next-dispatch — the old behavior in the mixed
    # decode+prefill regime, and the bench prefill_anatomy baseline arm.
    prefill_pipeline_depth: int = 2
    # admission fairness: at most this many (packed) prefill calls dispatch
    # per scheduler step before decode windows get the chip again. A request
    # burst otherwise serializes ALL its prefill passes ahead of any decode
    # window, stalling every running stream's ITL for the whole burst (and
    # the burst's own later requests gain nothing — their prefills still
    # queue). 0 = unbounded (pre-r5 behavior).
    prefill_batches_per_step: int = 2
    # cost attribution (utils/metering.py): per-(tenant, adapter, priority)
    # device-seconds at the step-anatomy seams + per-tenant KV byte-seconds
    # on every tier's allocate/free/demote/restore edges, conservation-
    # checked against the anatomy wall totals and the pool-occupancy
    # integrals. False = no MeterLedger anywhere: every hook is a
    # `meter is None` check, so the off path adds zero work per dispatch.
    metering: bool = True
    # pre-compile trace variants at startup so the first feature-bearing
    # request never hits a cold multi-second XLA compile mid-serving.
    #   False        — lazy (tests, short-lived engines)
    #   True         — everything blocking before start() returns
    #   "background" — core traces (default window + every bucket) blocking,
    #                  feature variants (logprobs/penalties) compiled between
    #                  serving steps after startup: first deploy of a new
    #                  geometry reaches readiness in roughly half the cold
    #                  compile time
    warmup: bool | str = False

    def __post_init__(self) -> None:
        if not isinstance(self.warmup, bool) and self.warmup != "background":
            # any other string would silently degrade to the FULL blocking
            # warmup (truthy), the opposite of what a typo'd "bg" intended
            raise ValueError(
                f"warmup must be True, False, or 'background'; got {self.warmup!r}"
            )
        if self.quantize is not None:
            from dynamo_tpu.quant import QUANT_MODES

            if self.quantize not in QUANT_MODES:
                raise ValueError(
                    f"quantize must be None or one of {QUANT_MODES}; got {self.quantize!r}"
                )
        if self.prefix_fetch_timeout_s <= 0:
            raise ValueError(
                f"prefix_fetch_timeout_s must be > 0; got {self.prefix_fetch_timeout_s}"
            )
        if self.migration_timeout_s <= 0:
            raise ValueError(
                f"migration_timeout_s must be > 0; got {self.migration_timeout_s}"
            )
        if self.qos_preempt_wait_ms < 0:
            raise ValueError(
                f"qos_preempt_wait_ms must be >= 0; got {self.qos_preempt_wait_ms}"
            )
        if self.kv_stream_lanes < 1:
            raise ValueError(
                f"kv_stream_lanes must be >= 1; got {self.kv_stream_lanes}"
            )
        if self.prefill_pipeline_depth < 1:
            raise ValueError(
                f"prefill_pipeline_depth must be >= 1; "
                f"got {self.prefill_pipeline_depth}"
            )
        if self.kv_cache_dtype is not None:
            from dynamo_tpu.quant import KV_CACHE_DTYPES

            if self.kv_cache_dtype not in KV_CACHE_DTYPES:
                raise ValueError(
                    f"kv_cache_dtype must be None or one of {KV_CACHE_DTYPES}; "
                    f"got {self.kv_cache_dtype!r}"
                )
            if self.kv_cache_dtype == "int8" and self.pp > 1:
                # the stage-sharded pool split (parallel/pipeline.py) has no
                # QuantizedPages wiring yet; fail at config time
                raise ValueError("kv_cache_dtype='int8' does not compose with pp > 1 yet")
        if self.offload_drain_batch < 1:
            raise ValueError(
                f"offload_drain_batch must be >= 1; got {self.offload_drain_batch}"
            )
        if self.host_cache_bytes < 0 or self.host_cache_blocks < 0:
            raise ValueError(
                "host cache capacity must be >= 0; got "
                f"blocks={self.host_cache_blocks} bytes={self.host_cache_bytes}"
            )
        if self.disk_cache_bytes < 0:
            raise ValueError(
                f"disk_cache_bytes must be >= 0; got {self.disk_cache_bytes}"
            )
        if self.disk_cache_bytes > 0 and not (
            self.host_cache_blocks > 0 or self.host_cache_bytes > 0
        ):
            raise ValueError(
                "disk_cache_bytes requires a host cache tier "
                "(host_cache_blocks or host_cache_bytes > 0): the KV ladder "
                "demotes HBM -> host -> disk and never skips a rung"
            )
        if any(b <= 0 for b in self.page_table_buckets):
            raise ValueError(
                f"page_table_buckets must be positive; got {self.page_table_buckets}"
            )
        if self.lora_adapters:
            if isinstance(self.lora_adapters, str):
                # yaml/CLI comma form normalizes here so every consumer sees
                # a tuple of specs
                self.lora_adapters = tuple(
                    s.strip() for s in self.lora_adapters.split(",") if s.strip()
                )
            else:
                self.lora_adapters = tuple(self.lora_adapters)
            if self.max_loras < 1:
                raise ValueError(f"max_loras must be >= 1; got {self.max_loras}")
            if self.lora_rank < 1:
                raise ValueError(f"lora_rank must be >= 1; got {self.lora_rank}")
            if self.pp > 1:
                # the pipeline shard_map's explicit _layer path has no LoRA
                # threading yet; fail at config time
                raise ValueError("lora_adapters do not compose with pp > 1 yet")
            from dynamo_tpu.lora.adapter import parse_adapter_specs

            parse_adapter_specs(self.lora_adapters)  # bad specs fail HERE
        # a bad speculative spec must fail at config time, not mid-serving
        self.spec  # noqa: B018 — parse_speculative raises on invalid input

    @property
    def spec(self):
        """Parsed SpecConfig for ``speculative`` (None when disabled)."""
        from dynamo_tpu.spec import parse_speculative

        return parse_speculative(self.speculative)

    @property
    def kv_quantized(self) -> bool:
        return self.kv_cache_dtype == "int8"

    @property
    def lora_enabled(self) -> bool:
        return bool(self.lora_adapters)

    @property
    def max_pages_per_seq(self) -> int:
        return -(-self.max_model_len // self.page_size)

    @property
    def table_buckets(self) -> tuple:
        """Resolved page-table width ladder (ascending, last ==
        max_pages_per_seq). Explicit ``page_table_buckets`` entries clamp to
        the dense width; auto mode doubles from min(128, max_pages_per_seq),
        which degenerates to the single dense width for short contexts."""
        mp = self.max_pages_per_seq
        if self.page_table_buckets:
            ladder = sorted({min(int(b), mp) for b in self.page_table_buckets if b > 0})
            if not ladder or ladder[-1] != mp:
                ladder.append(mp)
            return tuple(ladder)
        widths = []
        w = min(128, mp)
        while w < mp:
            widths.append(w)
            w *= 2
        widths.append(mp)
        return tuple(widths)

    def table_bucket_for(self, n_pages: int) -> int:
        """Smallest ladder width holding ``n_pages`` page-table entries."""
        for w in self.table_buckets:
            if n_pages <= w:
                return w
        raise ValueError(
            f"{n_pages} pages exceed max_pages_per_seq {self.max_pages_per_seq}"
        )

    @property
    def max_prefill_chunk(self) -> int:
        return max(self.prefill_buckets)

    def chunk_len_for(self, depth: int, backlog_rows: int = 0) -> int:
        """Depth-aware prefill chunk bucket for a chunk starting at context
        ``depth`` tokens: the largest bucket b with b * (depth + b) within
        the flat-depth work budget, floored at the smallest bucket — so
        per-chunk latency stays roughly flat as prefill advances into a long
        prompt instead of growing linearly with context.

        ``backlog_rows`` (total un-prefilled rows pending across sequences)
        promotes the bucket under a deep backlog by doubling the work
        budget: every dispatch pays the same fixed per-call cost, so when
        far more work is queued than one flat-latency chunk, fewer, larger
        dispatches win — the chunk-latency flatness the shrink buys is moot
        while the backlog itself dominates any single stream's TTFT."""
        top = self.max_prefill_chunk
        if self.prefill_flat_depth <= 0:
            return top
        budget = top * max(self.prefill_flat_depth, top)
        if backlog_rows >= 2 * top:
            budget *= 2
        best = min(self.prefill_buckets)
        for b in self.prefill_buckets:
            if b * (depth + b) <= budget:
                best = max(best, b)
        return best

    def lanes_for(self, bucket: int) -> int:
        """Packed-prefill lane count for a bucket: bounded by prefill_lanes
        and a ~1024-row budget. r5 on-chip: per-CALL cost is dominated by a
        ~10 ms fixed component (flat from 128 to 512 rows), so packing keeps
        paying well past the old 512-row cap — 2x512 rows measured 20.2 ms
        vs 2 separate calls at 33.7 ms (-40%); beyond ~1024 rows compute
        finally dominates and padding risk outweighs the amortization."""
        return max(1, min(self.prefill_lanes, 1024 // bucket))

    def bucket_for(self, n: int) -> int:
        """Smallest bucket >= n (n must be <= max bucket)."""
        for b in self.prefill_buckets:
            if n <= b:
                return b
        raise ValueError(f"chunk {n} exceeds max prefill bucket {self.max_prefill_chunk}")

    @classmethod
    def for_model(cls, model_id: str | None, **overrides) -> "EngineConfig":
        return cls(model_id=model_id or "tiny", **overrides)
